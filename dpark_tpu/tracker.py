"""Central KV tracker service over TCP.

Reference parity: dpark/tracker.py — a tiny zmq REQ/REP KV server carrying
map-output and cache locations between driver and executors (SURVEY.md
section 2.8).  This implementation speaks length-prefixed pickle over a
plain TCP socket (no zmq dependency): the single-host masters use the
in-process MapOutputTracker in env.py; this server is the DCN metadata
plane for multi-host deployments (driver runs TrackerServer, remote hosts
use TrackerClient).
"""

import pickle
import socket
import socketserver
import struct
import threading

from dpark_tpu.utils.log import get_logger

logger = get_logger("tracker")


import uuid as _uuid


class GetValueMessage:
    def __init__(self, key):
        self.key = key


class _Mutation:
    """Mutating messages carry a unique id; the server dedups replays so a
    client's retry-after-connection-error is exactly-once."""

    def __init__(self):
        self.msg_id = _uuid.uuid4().hex


class SetValueMessage(_Mutation):
    def __init__(self, key, value):
        super().__init__()
        self.key = key
        self.value = value


class AddItemMessage(_Mutation):
    def __init__(self, key, item):
        super().__init__()
        self.key = key
        self.item = item


class RemoveItemMessage(_Mutation):
    def __init__(self, key, item):
        super().__init__()
        self.key = key
        self.item = item


class StopTrackerMessage:
    pass


def _send_msg(sock, obj):
    data = pickle.dumps(obj, -1)
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_msg(sock):
    header = _recv_exact(sock, 4)
    (n,) = struct.unpack("<I", header)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tracker connection closed")
        buf += chunk
    return buf


class TrackerServer:
    def __init__(self, host="0.0.0.0", port=0):
        self.data = {}
        self.lock = threading.Lock()
        self._applied = {}          # msg_id -> reply (bounded)
        self._applied_order = []
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        reply = outer._handle(msg)
                        _send_msg(self.request, reply)
                        if isinstance(msg, StopTrackerMessage):
                            outer._server.shutdown()
                            return
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = None

    @property
    def addr(self):
        host, port = self._server.server_address[:2]
        if host == "0.0.0.0":
            host = socket.gethostname()
        return "%s:%d" % (host, port)

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        logger.debug("tracker server on %s", self.addr)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(2)
            self._thread = None

    def _handle(self, msg):
        with self.lock:
            if isinstance(msg, GetValueMessage):
                return self.data.get(msg.key)
            if isinstance(msg, _Mutation):
                if msg.msg_id in self._applied:
                    return self._applied[msg.msg_id]    # retry replay
                if isinstance(msg, SetValueMessage):
                    self.data[msg.key] = msg.value
                elif isinstance(msg, AddItemMessage):
                    self.data.setdefault(msg.key, []).append(msg.item)
                elif isinstance(msg, RemoveItemMessage):
                    items = self.data.get(msg.key, [])
                    if msg.item in items:
                        items.remove(msg.item)
                self._applied[msg.msg_id] = True
                self._applied_order.append(msg.msg_id)
                if len(self._applied_order) > 100_000:
                    old = self._applied_order[:50_000]
                    del self._applied_order[:50_000]
                    for mid in old:
                        self._applied.pop(mid, None)
                return True
            if isinstance(msg, StopTrackerMessage):
                return True
        return None


class TrackerClient:
    def __init__(self, addr):
        host, _, port = addr.partition(":")
        self.addr = (host, int(port))
        self._sock = None
        self._lock = threading.Lock()

    def _conn(self):
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, timeout=30)
        return self._sock

    def call(self, msg):
        with self._lock:
            try:
                sock = self._conn()
                _send_msg(sock, msg)
                return _recv_msg(sock)
            except (ConnectionError, OSError):
                self.close()
                sock = self._conn()
                _send_msg(sock, msg)
                return _recv_msg(sock)

    def get(self, key):
        return self.call(GetValueMessage(key))

    def set(self, key, value):
        return self.call(SetValueMessage(key, value))

    def add_item(self, key, item):
        return self.call(AddItemMessage(key, item))

    def remove_item(self, key, item):
        return self.call(RemoveItemMessage(key, item))

    def stop_server(self):
        return self.call(StopTrackerMessage())

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
