"""Central KV tracker service over TCP.

Reference parity: dpark/tracker.py — a tiny zmq REQ/REP KV server carrying
map-output and cache locations between driver and executors (SURVEY.md
section 2.8).  This implementation speaks length-prefixed JSON over a
plain TCP socket (no zmq dependency): the single-host masters use the
in-process MapOutputTracker in env.py; this server is the DCN metadata
plane for multi-host deployments (driver runs TrackerServer, remote hosts
use TrackerClient).

Wire safety: frames are JSON, never pickle — the tracker listens on the
network and unpickling untrusted bytes is arbitrary code execution (same
rule as dpark_tpu/dcn.py).  Binary values (e.g. pickled Broadcast
handles a DEPLOYMENT chooses to stash) survive via a base64 wrapper; the
tracker itself never deserializes them.  DPARK_DCN_SECRET, when set,
MACs every frame in both directions with HMAC-SHA256.
"""

import base64
import hashlib
import hmac
import json
import os
import socket
import socketserver
import struct
import threading
import time

from dpark_tpu.utils.log import get_logger

logger = get_logger("tracker")


import uuid as _uuid


class GetValueMessage:
    op = "get"

    def __init__(self, key):
        self.key = key


class _Mutation:
    """Mutating messages carry a unique id; the server dedups replays so a
    client's retry-after-connection-error is exactly-once."""

    def __init__(self):
        self.msg_id = _uuid.uuid4().hex


class SetValueMessage(_Mutation):
    op = "set"

    def __init__(self, key, value):
        super().__init__()
        self.key = key
        self.value = value


class AddItemMessage(_Mutation):
    op = "add"

    def __init__(self, key, item):
        super().__init__()
        self.key = key
        self.item = item


class RemoveItemMessage(_Mutation):
    op = "remove"

    def __init__(self, key, item):
        super().__init__()
        self.key = key
        self.item = item


class StopTrackerMessage:
    op = "stop"


def _wrap(v):
    """JSON-encodable view of a value; bytes ride as base64 (opaque to
    the tracker — never deserialized server-side)."""
    if isinstance(v, bytes):
        return {"__b64__": base64.b64encode(v).decode("ascii")}
    if isinstance(v, (list, tuple)):
        return [_wrap(x) for x in v]
    if isinstance(v, dict):
        return {k: _wrap(x) for k, x in v.items()}
    return v


def _unwrap(v):
    if isinstance(v, dict):
        if set(v) == {"__b64__"}:
            return base64.b64decode(v["__b64__"])
        return {k: _unwrap(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_unwrap(x) for x in v]
    return v


def _msg_to_frame(msg):
    if isinstance(msg, GetValueMessage):
        body = [msg.op, msg.key]
    elif isinstance(msg, SetValueMessage):
        body = [msg.op, msg.msg_id, msg.key, _wrap(msg.value)]
    elif isinstance(msg, (AddItemMessage, RemoveItemMessage)):
        body = [msg.op, msg.msg_id, msg.key, _wrap(msg.item)]
    elif isinstance(msg, StopTrackerMessage):
        body = [msg.op]
    else:
        raise TypeError("unknown tracker message %r" % (msg,))
    return json.dumps(body, separators=(",", ":")).encode()


def _secret():
    return os.environ.get("DPARK_DCN_SECRET", "").encode()


def _send_raw(sock, data):
    secret = _secret()
    if secret:
        data = hmac.new(secret, data, hashlib.sha256).digest() + data
    sock.sendall(struct.pack("<I", len(data)) + data)


def _send_msg(sock, obj):
    _send_raw(sock, json.dumps(_wrap(obj),
                               separators=(",", ":")).encode())


def _recv_frame(sock):
    header = _recv_exact(sock, 4)
    (n,) = struct.unpack("<I", header)
    data = _recv_exact(sock, n)
    secret = _secret()
    if secret:
        tag, data = data[:32], data[32:]
        want = hmac.new(secret, data, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise PermissionError("tracker frame MAC mismatch")
    return data


def _recv_msg(sock):
    return _unwrap(json.loads(_recv_frame(sock).decode("utf-8")))


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tracker connection closed")
        buf += chunk
    return buf


class TrackerServer:
    def __init__(self, host="0.0.0.0", port=0):
        self.data = {}
        self.lock = threading.Lock()
        self._applied = {}          # msg_id -> reply (bounded)
        self._applied_order = []
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        try:
                            body = _recv_msg(self.request)
                        except (ValueError, TypeError,
                                PermissionError, json.JSONDecodeError):
                            return     # malformed/unauthenticated frame
                        try:
                            reply, stop = outer._handle(body)
                        except (TypeError, KeyError, IndexError) as e:
                            # well-formed JSON, wrong shape — but the
                            # same exceptions from a genuine handler
                            # bug on internal traffic must be visible
                            logger.warning(
                                "tracker dropped frame %.80r: %s",
                                body, e)
                            return
                        _send_msg(self.request, reply)
                        if stop:
                            outer._server.shutdown()
                            return
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = None

    @property
    def addr(self):
        host, port = self._server.server_address[:2]
        if host == "0.0.0.0":
            host = socket.gethostname()
        return "%s:%d" % (host, port)

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        logger.debug("tracker server on %s", self.addr)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(2)
            self._thread = None

    def _handle(self, body):
        """body is the decoded JSON frame [op, ...]; returns
        (reply, stop_server)."""
        op = body[0] if body else None
        with self.lock:
            if op == "get":
                return self.data.get(body[1]), False
            if op in ("set", "add", "remove"):
                msg_id, key, value = body[1], body[2], body[3]
                if msg_id in self._applied:
                    return self._applied[msg_id], False  # retry replay
                if op == "set":
                    self.data[key] = value
                elif op == "add":
                    self.data.setdefault(key, []).append(value)
                else:
                    items = self.data.get(key, [])
                    if value in items:
                        items.remove(value)
                self._applied[msg_id] = True
                self._applied_order.append(msg_id)
                if len(self._applied_order) > 100_000:
                    old = self._applied_order[:50_000]
                    del self._applied_order[:50_000]
                    for mid in old:
                        self._applied.pop(mid, None)
                return True, False
            if op == "stop":
                return True, True
        return None, False


class TrackerClient:
    def __init__(self, addr):
        host, _, port = addr.partition(":")
        self.addr = (host, int(port))
        self._sock = None
        self._lock = threading.Lock()

    def _conn(self):
        if self._sock is None:
            # conf-driven deadline (ISSUE 20 satellite): the tracker
            # shares the dcn fetch deadline instead of a hardcoded 30s
            from dpark_tpu import conf
            timeout = float(getattr(conf, "DCN_TIMEOUT_MS",
                                    30000)) / 1000.0
            self._sock = socket.create_connection(self.addr,
                                                  timeout=timeout)
        return self._sock

    def call(self, msg):
        """One tracker round-trip with conf.DCN_RETRIES total attempts
        on a fresh connection, exponential-full-jitter backoff between
        them (dcn.backoff_delays — one schedule, every control-plane
        caller).  Safe to retry blindly: mutations carry a msg_id the
        server deduplicates, so a reply lost in transit cannot
        double-apply."""
        from dpark_tpu import conf, dcn
        frame = _msg_to_frame(msg)
        attempts = max(2, int(getattr(conf, "DCN_RETRIES", 2) or 2))
        delays = dcn.backoff_delays(attempts)
        last_err = None
        with self._lock:
            for k in range(attempts):
                try:
                    sock = self._conn()
                    _send_raw(sock, frame)
                    return _recv_msg(sock)
                except (ConnectionError, OSError) as e:
                    last_err = e
                    self.close()
                    d = next(delays, None)
                    if d is None:
                        break
                    time.sleep(d)
            raise last_err

    def get(self, key):
        return self.call(GetValueMessage(key))

    def set(self, key, value):
        return self.call(SetValueMessage(key, value))

    def add_item(self, key, item):
        return self.call(AddItemMessage(key, item))

    def remove_item(self, key, item):
        return self.call(RemoveItemMessage(key, item))

    def stop_server(self):
        return self.call(StopTrackerMessage())

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
