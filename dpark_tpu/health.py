"""Online health plane (ISSUE 14 tentpole): streaming telemetry
sketches, subsystem grading, and the flight recorder.

The PR 8 trace plane measures per-fetch latency, decode outcomes, and
phase timings, but nothing consumed them ONLINE: spans died in the
spool, /metrics exported totals without tails, and the JobServer had
no notion of whether a tenant's latency was healthy.  This module
closes that gap without re-parsing anything: a :class:`HealthSink`
subscribes to ``trace.TracePlane.record`` (one ``is None`` check when
off — the established faults/trace contract) and folds spans AS THEY
ARE EMITTED into compact, bounded, merge-associative sketches:

* **latency tails** — log2-bucketed histograms with p50/p95/p99
  estimates keyed by SITE:
  ``fetch.bucket:<peer>`` (reduce-side bucket fetches per serving
  peer), ``dcn.transfer:<peer>`` / ``dcn.bulk.fetch:<peer>`` /
  ``dcn.bulk.serve:<peer>`` (host bridge and bulk data plane per
  peer), ``wave:<sig>`` / ``stage.exec`` (device execution per
  program signature), ``spill.write`` / ``spill.read``,
  ``executor.compile:<sig>`` / ``dispatch:<sig>`` (count-only —
  instant events).
* **rates** — decode outcomes, fetch failures, bulk-stream failures,
  degrade/abort events, compile counts.
* **pressure** — cumulative spill bytes read/written (HBM pressure is
  read live off the executor by :func:`api_health`; it is a gauge of
  NOW, not a foldable stream).

Three consumers ride the sink:

1. **site stats -> adapt store** (ROADMAP item 5's named handoff):
   :func:`persist_site_tails` appends per-site digest DELTAS to the
   crc-framed adapt store (``adapt.record_site_tail``), so a fresh
   process — and eventually the straggler-adaptive coder — reads the
   observed per-site tail distribution back (``adapt.site_tails()``).
   Worker processes fold their sketches into the cross-process
   cross-process counters merge (one atomically-rewritten
   ``health-<host>-<pid>.jsonl`` beside the counters file — see
   trace._write_process_health), so driver-side tails include
   multiproc fetches — the same merge that closed the fault/decode
   counter blindspot in PR 8.
2. **per-tenant SLO accounting** — service.py tracks attainment and
   multi-window burn; :func:`api_health` attaches the graded verdict.
3. **the flight recorder** — warning-and-above events land in an
   always-armed bounded ring (``trace._FLIGHT``) even with
   ``DPARK_TRACE=off``; on job abort, stage degrade, or SIGUSR2,
   :func:`flight_dump` writes a crc-framed snapshot (ring contents +
   health sketches + recovery summary + adapt decisions) under
   ``DPARK_FLIGHT_DIR`` for post-mortem via ``tools/dtrace --flight``.

Everything here is advisory: a fold/persist/dump failure logs at
debug and never breaks a job.  With ``DPARK_HEALTH=off`` the sink is
None and the whole plane costs one predicate per trace record.
"""

import json
import math
import os
import socket
import threading
import time

from dpark_tpu import conf
from dpark_tpu import locks
from dpark_tpu.utils.log import get_logger

logger = get_logger("health")

MODES = ("off", "on")

# log2 bucket layout shared by every sketch: bucket 0 holds durations
# <= _B0 seconds (0.1 ms), bucket i holds (_B0 * 2^(i-1), _B0 * 2^i].
# 36 buckets reach ~= 55 minutes; anything longer clips into the last
# bucket.  The layout is FIXED (not configurable) so digests written
# by one process/version merge bit-identically with another's.
_B0 = 1e-4
NBUCKETS = 36

_SINK = None                 # the `is None` check trace.record makes
_lock = locks.named_lock("health.install")   # guards install/clear


class Sketch:
    """One bounded log-bucketed latency histogram.  Folding is O(1),
    merging is bucket-wise addition (associative and commutative —
    asserted in tests), and the memory is NBUCKETS ints regardless of
    how many observations stream through."""

    __slots__ = ("buckets", "n", "sum")

    def __init__(self):
        self.buckets = [0] * NBUCKETS
        self.n = 0
        self.sum = 0.0

    @staticmethod
    def bucket_of(seconds):
        if seconds <= _B0:
            return 0
        return min(NBUCKETS - 1,
                   1 + int(math.log2(seconds / _B0)))

    @staticmethod
    def bucket_edge(i):
        """Upper edge of bucket i in seconds."""
        return _B0 * (2 ** i) if i else _B0

    def add(self, seconds):
        self.buckets[self.bucket_of(max(0.0, float(seconds)))] += 1
        self.n += 1
        self.sum += max(0.0, float(seconds))

    def merge(self, other):
        for i, v in enumerate(other.buckets):
            self.buckets[i] += v
        self.n += other.n
        self.sum += other.sum
        return self

    def quantile(self, q):
        """Estimated q-quantile in seconds (None when empty): find the
        bucket holding the q-th observation and interpolate
        geometrically inside it (log-uniform assumption — the honest
        middle of a log bucket)."""
        if not self.n:
            return None
        target = q * self.n
        acc = 0
        for i, v in enumerate(self.buckets):
            acc += v
            if acc >= target:
                hi = self.bucket_edge(i)
                if i == 0:
                    return hi
                lo = self.bucket_edge(i - 1)
                # position of the target inside this bucket
                frac = 1.0 - (acc - target) / max(1, v)
                return lo * ((hi / lo) ** max(0.0, min(1.0, frac)))
        return self.bucket_edge(NBUCKETS - 1)

    def to_dict(self):
        """Sparse, JSON-safe digest (the wire/store format)."""
        return {"b": {str(i): v for i, v in enumerate(self.buckets)
                      if v},
                "n": self.n, "s": round(self.sum, 6)}

    @classmethod
    def from_dict(cls, d):
        sk = cls()
        try:
            for i, v in (d.get("b") or {}).items():
                i = int(i)
                if 0 <= i < NBUCKETS:
                    sk.buckets[i] = int(v)
            sk.n = int(d.get("n", sum(sk.buckets)))
            sk.sum = float(d.get("s", 0.0))
        except (TypeError, ValueError):
            pass
        return sk

    def summary(self):
        """{"n", "p50_ms", "p95_ms", "p99_ms", "mean_ms"} — the
        human/bench view.  Count-only sketches (instant events, sum
        0) report just "n"."""
        out = {"n": self.n}
        if self.n and self.sum > 0:
            for name, q in (("p50_ms", 0.50), ("p95_ms", 0.95),
                            ("p99_ms", 0.99)):
                out[name] = round(self.quantile(q) * 1e3, 3)
            out["mean_ms"] = round(self.sum / self.n * 1e3, 3)
        return out


def merge_digests(a, b):
    """Merge two digest dicts (the to_dict shape) — used by the
    cross-process counter merge and the adapt-store fold."""
    sk = Sketch.from_dict(a or {})
    sk.merge(Sketch.from_dict(b or {}))
    return sk.to_dict()


# ---------------------------------------------------------------------------
# the sink: site routing for trace records
# ---------------------------------------------------------------------------

def _peer_of(args):
    """Best-effort peer identity from span args: an explicit `peer`,
    else the host of a `uri`."""
    peer = args.get("peer")
    if peer:
        return str(peer)
    uri = args.get("uri")
    if not uri:
        return None
    u = str(uri)
    for scheme in ("tcp://", "http://", "file://", "hbm://"):
        if u.startswith(scheme):
            u = u[len(scheme):]
            break
    return u.split("/", 1)[0].rsplit(":", 1)[0] or "local"


class HealthSink:
    """The in-process streaming aggregator.  fold() is called from
    TracePlane.record with every emitted record; everything is bounded
    (HEALTH_MAX_SITES site sketches, HEALTH_STAGE_SKETCHES per-stage
    fetch sketches) and guarded by one lock."""

    def __init__(self):
        self.lock = locks.named_lock("health.sink")
        self.sites = {}          # site -> Sketch (bounded)
        self.rates = {}          # event name -> count
        self.gauges = {"spill_bytes_written": 0,
                       "spill_bytes_read": 0}
        # per-(job, stage) fetch-latency sketches for the web UI's
        # stage fetch-p99 column (bounded: oldest evicts)
        self.stage_fetch = {}
        self._stage_order = []
        self.folded = 0
        self.dropped_sites = 0
        # deltas already persisted to the adapt store, per site
        self._persisted = {}
        self._last_persist = 0.0

    # -- folding ---------------------------------------------------------
    def _site_sketch(self, site):
        sk = self.sites.get(site)
        if sk is None:
            cap = int(getattr(conf, "HEALTH_MAX_SITES", 256) or 0)
            if cap and len(self.sites) >= cap:
                # overflow folds into the base site (before the ":"),
                # so totals stay honest even past the key cap
                self.dropped_sites += 1
                base = site.split(":", 1)[0]
                sk = self.sites.get(base)
                if sk is None and len(self.sites) < cap + 16:
                    sk = self.sites[base] = Sketch()
                return sk
            sk = self.sites[site] = Sketch()
        return sk

    def fold(self, rec):
        name = rec.get("name", "")
        dur = float(rec.get("dur", 0.0) or 0.0)
        args = rec.get("args") or {}
        with self.lock:
            self.folded += 1
            if name == "fetch.bucket":
                site = "fetch.bucket:%s" % (_peer_of(args) or "local")
                sk = self._site_sketch(site)
                if sk is not None:
                    sk.add(dur)
                if "error" in args:
                    self.rates["fetch.error"] = \
                        self.rates.get("fetch.error", 0) + 1
                key = (rec.get("job"), rec.get("stage"))
                if key != (None, None):
                    ssk = self.stage_fetch.get(key)
                    if ssk is None:
                        cap = int(getattr(conf, "HEALTH_STAGE_SKETCHES",
                                          256) or 256)
                        if len(self._stage_order) >= cap:
                            old = self._stage_order.pop(0)
                            self.stage_fetch.pop(old, None)
                        ssk = self.stage_fetch[key] = Sketch()
                        self._stage_order.append(key)
                    ssk.add(dur)
            elif name in ("dcn.transfer", "dcn.bulk.fetch",
                          "dcn.bulk.serve"):
                site = "%s:%s" % (name, _peer_of(args) or "local")
                sk = self._site_sketch(site)
                if sk is not None:
                    sk.add(dur)
                if "error" in args:
                    self.rates["dcn.error"] = \
                        self.rates.get("dcn.error", 0) + 1
            elif name == "wave":
                site = "wave:%s" % (args.get("sig") or "?")
                sk = self._site_sketch(site)
                if sk is not None:
                    sk.add(dur)
            elif name == "stage.exec":
                sk = self._site_sketch("stage.exec")
                if sk is not None:
                    sk.add(dur)
            elif name in ("compile", "dispatch"):
                # instant events: count-only sketches keyed by the
                # program signature (latency lives in wave/stage.exec)
                site = "executor.%s:%s" % (
                    name, args.get("sig") or args.get("program")
                    or "?")
                sk = self._site_sketch(site)
                if sk is not None:
                    sk.n += 1
                self.rates[name] = self.rates.get(name, 0) + 1
            elif name in ("spill.write", "spill.read"):
                sk = self._site_sketch(name)
                if sk is not None:
                    sk.add(dur)
                gk = "spill_bytes_written" if name == "spill.write" \
                    else "spill_bytes_read"
                self.gauges[gk] += int(args.get("bytes", 0) or 0)
            elif name.startswith("decode."):
                self.rates[name] = self.rates.get(name, 0) + 1
            elif name in ("fetch.failed", "dcn.bulk.failed",
                          "stage.degrade", "job.abort"):
                self.rates[name] = self.rates.get(name, 0) + 1
            elif name == "job":
                state = args.get("state")
                if state:
                    self.rates["job.%s" % state] = \
                        self.rates.get("job.%s" % state, 0) + 1

    # -- reading back ----------------------------------------------------
    def snapshot(self):
        """Full digest view (the wire/store shapes) under the lock."""
        with self.lock:
            return {
                "sites": {s: sk.to_dict()
                          for s, sk in self.sites.items()},
                "rates": dict(self.rates),
                "gauges": dict(self.gauges),
                "stage_fetch": {"%s:%s" % k: sk.to_dict()
                                for k, sk in self.stage_fetch.items()},
                "folded": self.folded,
                "dropped_sites": self.dropped_sites,
            }

    def site_digests(self):
        with self.lock:
            return {s: sk.to_dict() for s, sk in self.sites.items()}


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(mode=None):
    """Install (mode "on") or clear (mode "off") the process sink.
    None reads conf.DPARK_HEALTH.  Returns the sink or None.  The
    sink only ever sees records the TRACE plane emits — with
    DPARK_TRACE=off there is nothing to fold and the plane is inert
    either way."""
    global _SINK
    if mode is None:
        mode = str(getattr(conf, "DPARK_HEALTH", "on") or "on")
    mode = str(mode).lower()
    if mode not in MODES:
        raise ValueError("DPARK_HEALTH=%r (expected off|on)" % mode)
    with _lock:
        _SINK = HealthSink() if mode == "on" else None
        return _SINK


def active():
    return _SINK is not None


def mode():
    return "on" if _SINK is not None else "off"


def sink():
    return _SINK


def snapshot():
    s = _SINK
    return s.snapshot() if s is not None else {
        "sites": {}, "rates": {}, "gauges": {}, "stage_fetch": {},
        "folded": 0, "dropped_sites": 0}


# ---------------------------------------------------------------------------
# offline twin: fold a record list (spool load) into a fresh sink
# ---------------------------------------------------------------------------

def fold_records(records):
    """Build a registry from already-collected trace records (the
    tools/dtrace --health path and the live-vs-offline consistency
    test).  Skips counter events' own record rows but MERGES any
    worker health digests they carry, so the offline view matches the
    driver's merged live view."""
    s = HealthSink()
    worker = {}
    for rec in records:
        if rec.get("cat") == "counters":
            h = (rec.get("args") or {}).get("health")
            if h:
                # cumulative per process: newest per (host, pid) wins
                worker[(rec.get("host"), rec.get("pid"))] = h
            continue
        try:
            s.fold(rec)
        except Exception:
            pass
    # NOTE: worker spool files already hold the worker's own spans, so
    # folding them above covers what the digests summarize; the
    # digests only fill in when a worker's SPAN spool was capped but
    # its counters file (never capped) still shipped the sketch.  Take
    # the per-site MAX of fold-vs-digest counts so neither source
    # double-counts the other.
    for digest in worker.values():
        for site, d in (digest or {}).items():
            have = s.sites.get(site)
            cand = Sketch.from_dict(d)
            if have is None or cand.n > have.n:
                s.sites[site] = cand
    return s


def summarize_sites(site_digests):
    """{site: digest} -> {site: summary} sorted by site."""
    out = {}
    for site in sorted(site_digests):
        out[site] = Sketch.from_dict(site_digests[site]).summary()
    return out


def merged_site_digests(include_workers=True):
    """The driver's merged per-site view: the local sink's sketches
    plus (in spool mode) the latest worker-process health digests
    from the counters merge — multiproc fetch tails finally surface
    on the driver."""
    s = _SINK
    out = dict(s.site_digests()) if s is not None else {}
    if include_workers:
        try:
            from dpark_tpu import trace
            workers = trace.merged_worker_counters().get("health") \
                or {}
            for site, digest in workers.items():
                out[site] = merge_digests(out.get(site), digest)
        except Exception:
            pass
    return out


def summary():
    """The `health` section for bench artifacts: mode + per-site tail
    summaries + event rates.  {"mode": "off", "sites": {}} when the
    plane is off."""
    s = _SINK
    if s is None:
        return {"mode": "off", "sites": {}, "rates": {}}
    snap = s.snapshot()
    return {"mode": "on",
            "sites": summarize_sites(merged_site_digests()),
            "rates": snap["rates"],
            "gauges": snap["gauges"],
            "folded": snap["folded"]}


# ---------------------------------------------------------------------------
# consumer 1: per-site tails -> the adapt store (ROADMAP item 5)
# ---------------------------------------------------------------------------

def persist_site_tails(force=False):
    """Append each site's UNPERSISTED observations to the adapt store
    as a digest delta (the store folds deltas by bucket addition, so
    repeated persists never double-count).  Throttled to once per
    conf.HEALTH_PERSIST_MIN_S unless forced.  Returns the number of
    sites written."""
    s = _SINK
    if s is None:
        return 0
    try:
        from dpark_tpu import adapt
        if not adapt.enabled():
            return 0
        now = time.time()
        min_s = float(getattr(conf, "HEALTH_PERSIST_MIN_S", 30.0)
                      or 0.0)
        with s.lock:
            if not force and now - s._last_persist < min_s:
                return 0
            s._last_persist = now
        # the MERGED view: local sketches plus worker-process digests
        # from the counters merge — on a multiprocess master the
        # driver itself fetches nothing, and the whole point of the
        # handoff is the WORKERS' observed tails
        merged = merged_site_digests()
        deltas = {}
        with s.lock:
            for site, digest in merged.items():
                sk = Sketch.from_dict(digest)
                if not sk.sum:
                    continue             # count-only: no tail to store
                prev = s._persisted.get(site)
                if prev is None:
                    prev = s._persisted[site] = ([0] * NBUCKETS, 0.0)
                delta = Sketch()
                for i, v in enumerate(sk.buckets):
                    delta.buckets[i] = v - prev[0][i]
                delta.n = sum(delta.buckets)
                if delta.n <= 0:
                    continue
                # the sum delta rides too: summary() gates percentile
                # output on sum > 0, so a stored tail must read back
                # as a REAL latency sketch, not a count-only one
                delta.sum = max(0.0, sk.sum - prev[1])
                deltas[site] = delta.to_dict()
                s._persisted[site] = (list(sk.buckets), sk.sum)
        for site, digest in deltas.items():
            adapt.record_site_tail(site, digest)
        return len(deltas)
    except Exception as e:
        logger.debug("persist_site_tails failed: %s", e)
        return 0


# ---------------------------------------------------------------------------
# grading: the /api/health verdicts (reused offline by dtrace --health)
# ---------------------------------------------------------------------------

def _grade_of(value, yellow, red):
    if value is None:
        return "green"
    if value >= red:
        return "red"
    if value >= yellow:
        return "yellow"
    return "green"


def _worst(*grades):
    for g in ("red", "yellow"):
        if g in grades:
            return g
    return "green"


def grade(site_digests, rates, tenants=None, counters=None,
          ledger_data=None):
    """Grade each subsystem green/yellow/red WITH the evidence (tail
    ms, rates, thresholds) attached.  Pure function of its inputs so
    the offline twin (tools/dtrace --health) and the live endpoint
    compute identical verdicts from identical data.

    `ledger_data` (ISSUE 15): {"top_programs", "top_tenants",
    "conservation"} from the resource attribution plane — top-k
    consumers attach as evidence so a yellow verdict NAMES its likely
    consumer, and the conservation check grades as its own
    `attribution` subsystem."""
    rates = rates or {}
    counters = counters or {}
    sites = summarize_sites(site_digests or {})
    out = {}

    def tail(prefix, field="p99_ms"):
        vals = [(s, d[field]) for s, d in sites.items()
                if s.startswith(prefix) and field in d]
        if not vals:
            return None, None
        return max(vals, key=lambda kv: kv[1])

    # shuffle fetch: worst per-peer p99 + failure rate over fetches
    fy = float(getattr(conf, "HEALTH_FETCH_P99_YELLOW_MS", 250.0))
    fr = float(getattr(conf, "HEALTH_FETCH_P99_RED_MS", 1000.0))
    site, p99 = tail("fetch.bucket")
    fetches = sum(d["n"] for s, d in sites.items()
                  if s.startswith("fetch.bucket"))
    # an exhausted fetch shows up BOTH as an error-carrying span
    # (fetch.error) and a flight event (fetch.failed) — take the max,
    # not the sum, so one failure isn't graded twice
    fails = max(rates.get("fetch.error", 0),
                rates.get("fetch.failed", 0))
    fail_rate = fails / fetches if fetches else 0.0
    ey = float(getattr(conf, "HEALTH_ERROR_RATE_YELLOW", 0.01))
    er = float(getattr(conf, "HEALTH_ERROR_RATE_RED", 0.10))
    out["shuffle_fetch"] = {
        "grade": _worst(_grade_of(p99, fy, fr),
                        _grade_of(fail_rate if fetches else None,
                                  ey, er)),
        "evidence": {"worst_site": site, "p99_ms": p99,
                     "fetches": fetches, "failures": fails,
                     "failure_rate": round(fail_rate, 4),
                     "thresholds": {"p99_ms": [fy, fr],
                                    "failure_rate": [ey, er]}}}
    # dcn / bulk plane
    site, p99 = tail("dcn.")
    dcn_fails = rates.get("dcn.error", 0) \
        + rates.get("dcn.bulk.failed", 0)
    dcn_n = sum(d["n"] for s, d in sites.items()
                if s.startswith("dcn."))
    dcn_rate = dcn_fails / dcn_n if dcn_n else 0.0
    dy = float(getattr(conf, "HEALTH_DCN_P99_YELLOW_MS", 500.0))
    dr = float(getattr(conf, "HEALTH_DCN_P99_RED_MS", 2000.0))
    out["dcn"] = {
        "grade": _worst(_grade_of(p99, dy, dr),
                        _grade_of(dcn_rate if dcn_n else None,
                                  ey, er)),
        "evidence": {"worst_site": site, "p99_ms": p99,
                     "transfers": dcn_n, "failures": dcn_fails,
                     "failure_rate": round(dcn_rate, 4),
                     "thresholds": {"p99_ms": [dy, dr],
                                    "failure_rate": [ey, er]}}}
    # coding: decode failures vs decode activity
    repairs = rates.get("decode.repair", 0) \
        + rates.get("decode.straggler_win", 0)
    dfails = rates.get("decode.decode_failures", 0)
    decodes = repairs + dfails
    drate = dfails / decodes if decodes else 0.0
    out["coding"] = {
        "grade": _grade_of(drate if decodes else None, ey, er),
        "evidence": {"repairs": repairs, "decode_failures": dfails,
                     "failure_rate": round(drate, 4),
                     "thresholds": {"failure_rate": [ey, er]}}}
    # executor: wave tail + degrade events
    site, p99 = tail("wave:")
    wy = float(getattr(conf, "HEALTH_WAVE_P99_YELLOW_MS", 5000.0))
    wr = float(getattr(conf, "HEALTH_WAVE_P99_RED_MS", 30000.0))
    degrades = rates.get("stage.degrade", 0)
    out["executor"] = {
        "grade": _worst(_grade_of(p99, wy, wr),
                        "yellow" if degrades else "green"),
        "evidence": {"worst_wave_sig": site, "wave_p99_ms": p99,
                     "compiles": rates.get("compile", 0),
                     "degrades": degrades,
                     "thresholds": {"wave_p99_ms": [wy, wr]}}}
    if ledger_data and ledger_data.get("top_programs"):
        # a yellow/red executor verdict should NAME its likely
        # consumer (ISSUE 15 satellite): the heaviest programs by
        # attributed device-seconds ride the evidence
        out["executor"]["evidence"]["top_programs"] = \
            ledger_data["top_programs"]
    # spill I/O
    site, p99 = tail("spill.")
    sy = float(getattr(conf, "HEALTH_SPILL_P99_YELLOW_MS", 500.0))
    sr = float(getattr(conf, "HEALTH_SPILL_P99_RED_MS", 5000.0))
    out["spill"] = {
        "grade": _grade_of(p99, sy, sr),
        "evidence": {"worst_site": site, "p99_ms": p99,
                     "thresholds": {"p99_ms": [sy, sr]}}}
    # scheduler: recovery counters + aborts.  One aborted job emits
    # BOTH a job span with state=aborted and a job.abort flight event
    # — max, not sum, so the evidence reports the true count
    aborts = max(rates.get("job.abort", 0),
                 rates.get("job.aborted", 0))
    resubmits = int(counters.get("resubmits", 0) or 0)
    out["scheduler"] = {
        "grade": _worst("red" if aborts else "green",
                        "yellow" if resubmits else "green"),
        "evidence": {"aborts": aborts, "resubmits": resubmits,
                     "retries": int(counters.get("retries", 0) or 0),
                     "fetch_failed": int(counters.get("fetch_failed",
                                                      0) or 0)}}
    # crash-consistent control plane (ISSUE 20): journal + peer-lease
    # evidence.  A refused journal file is red — completed work exists
    # on disk that this process cannot replay (schema newer than it
    # understands).  Lease expiries / suspect peers / skipped frames
    # are yellow: recovery WORKED, but a peer died or a frame tore and
    # an operator should know.
    jstats = lease = None
    try:
        from dpark_tpu import journal as _journal
        jstats = _journal.stats()
    except Exception:
        jstats = None
    try:
        from dpark_tpu import dcn as _dcn
        lease = _dcn.liveness_stats()
    except Exception:
        lease = None
    if jstats is not None or lease is not None:
        jc = (jstats or {}).get("counters") or {}
        lc = (lease or {}).get("counters") or {}
        if int(jc.get("refused_files", 0) or 0):
            g = "red"
        elif int(jc.get("skipped_frames", 0) or 0) \
                or int(lc.get("lease_expiries", 0) or 0) \
                or (lease or {}).get("suspect"):
            g = "yellow"
        else:
            g = "green"
        out["recovery"] = {
            "grade": g,
            "evidence": {
                "journal": jstats, "liveness": lease,
                "resumed_stages": int(counters.get("resumed_stages",
                                                   0) or 0)}}
    # per-tenant SLO (only when a service with declared SLOs is live)
    if tenants:
        by = float(getattr(conf, "SERVICE_SLO_BURN_YELLOW", 1.0))
        br = float(getattr(conf, "SERVICE_SLO_BURN_RED", 2.0))
        worst = "green"
        for t in tenants.values():
            burn = max((t.get("burn") or {}).values() or [0.0])
            worst = _worst(worst, _grade_of(burn, by, br))
        out["service_slo"] = {
            "grade": worst,
            "evidence": {"tenants": tenants,
                         "thresholds": {"burn": [by, br]}}}
        if ledger_data and ledger_data.get("top_tenants"):
            # who is consuming the shared mesh (ISSUE 15): the
            # heaviest tenants by HBM byte-seconds ride the SLO
            # evidence so a burning tenant's verdict names the
            # neighbor crowding it
            out["service_slo"]["evidence"]["top_tenants"] = \
                ledger_data["top_tenants"]
    if ledger_data and ledger_data.get("conservation") is not None:
        # the conservation check (ISSUE 15 acceptance): attributed
        # device-seconds must reconcile with measured mesh busy time
        # — a shortfall means untracked consumption the quota/
        # preemption work (ROADMAP item 3) could not bill
        cons = ledger_data["conservation"]
        ok = cons.get("ok")
        ev = dict(cons)
        if ledger_data.get("top_programs"):
            ev["top_programs"] = ledger_data["top_programs"]
        if ledger_data.get("top_tenants"):
            ev["top_tenants"] = ledger_data["top_tenants"]
        out["attribution"] = {
            "grade": "green" if ok in (True, None) else "yellow",
            "evidence": ev}
    return out


def api_health(scheduler=None):
    """The /api/health payload: merged site summaries, rates, graded
    subsystems with evidence, per-tenant SLO stats, per-stage fetch
    p99s, and live pressure gauges — built from defensive snapshots
    (a scrape racing a running job returns valid JSON, never an
    error)."""
    s = _SINK
    snap = snapshot()
    digests = merged_site_digests()
    counters = {}
    tenants = None
    try:
        if scheduler is not None \
                and hasattr(scheduler, "metrics_snapshot"):
            counters = scheduler.metrics_snapshot().get("counters",
                                                        {}) or {}
    except Exception:
        counters = {}
    try:
        svc = getattr(scheduler, "_service", None) \
            if scheduler is not None else None
        if svc is None and scheduler is not None:
            # a ClientScheduler facade: reach through to the server
            svc = getattr(getattr(scheduler, "server", None),
                          "scheduler", None)
            svc = getattr(svc, "_service", None) \
                if svc is not None else None
        if svc is not None:
            tenants = svc.tenant_slo_stats() or None
    except Exception:
        tenants = None
    ledger_data = None
    try:
        from dpark_tpu import ledger
        if ledger.active():
            # one snapshot + one merged-totals pass per scrape (the
            # UI polls this endpoint; tenant_totals re-reads the
            # worker sidecar files)
            lsnap = ledger.snapshot()
            ltotals = ledger.tenant_totals()
            ledger_data = {
                "top_programs": ledger.top_programs(snap=lsnap),
                "top_tenants": ledger.top_tenants(totals=ltotals),
                "conservation": ledger.conservation(scheduler,
                                                    snap=lsnap),
            }
    except Exception:
        ledger_data = None
    out = {
        "mode": mode(),
        "sites": summarize_sites(digests),
        "rates": snap.get("rates", {}),
        "gauges": dict(snap.get("gauges", {})),
        "subsystems": grade(digests, snap.get("rates"), tenants,
                            counters, ledger_data=ledger_data),
        "stage_fetch": {},
        "folded": snap.get("folded", 0),
    }
    if tenants is not None:
        out["tenants"] = tenants
    try:
        # straggler-adaptive coded shuffle (ISSUE 19): per-peer decode
        # outcomes next to the coding grade, and the chosen-(k,m)
        # history as executor evidence — the operator's answer to
        # "which peer made the policy escalate, and to what".
        # Evidence only; grades are unchanged.
        from dpark_tpu import coding
        per_peer = coding.stats().get("per_peer") or {}
        if per_peer and "coding" in out["subsystems"]:
            out["subsystems"]["coding"]["evidence"]["by_peer"] = \
                per_peer
        choices = coding.code_history()
        if choices and "executor" in out["subsystems"]:
            out["subsystems"]["executor"]["evidence"][
                "code_choices"] = choices
    except Exception:
        pass
    try:
        # AOT executable-cache counters (ISSUE 17) for the UI topline
        from dpark_tpu import aotcache
        aot = aotcache.stats()
        if aot is not None:
            out["aot"] = aot
    except Exception:
        pass
    try:
        # shared-computation result-cache counters (ISSUE 18)
        from dpark_tpu import resultcache
        rc = resultcache.stats()
        if rc is not None:
            out["result_cache"] = rc
    except Exception:
        pass
    try:
        # crash-journal counters (ISSUE 20) for the UI topline
        from dpark_tpu import journal
        js = journal.stats()
        if js is not None:
            out["journal"] = js
    except Exception:
        pass
    try:
        # peer-lease liveness (ISSUE 20): suspect set + expiry counts
        from dpark_tpu import dcn
        lv = dcn.liveness_stats()
        if lv is not None:
            out["liveness"] = lv
    except Exception:
        pass
    if s is not None:
        with s.lock:
            out["stage_fetch"] = {
                "%s:%s" % k: sk.summary()
                for k, sk in s.stage_fetch.items()}
    try:
        ex = getattr(scheduler, "executor", None) \
            if scheduler is not None else None
        if ex is not None:
            out["gauges"]["hbm_store_bytes"] = \
                int(getattr(ex, "_store_bytes", 0))
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# consumer 3: the flight recorder
# ---------------------------------------------------------------------------

_flight_lock = locks.named_lock("health.flight")
_flight_dumps = 0
_sigusr2_installed = False


def flight_dir():
    return getattr(conf, "DPARK_FLIGHT_DIR", "") or ""


def flight_dump(reason, scheduler=None, record=None):
    """Write one crc-framed post-mortem snapshot under
    DPARK_FLIGHT_DIR: header, the always-armed warning ring plus the
    trace ring tail, the health sketches, the scheduler's recovery
    summary, and the adapt decision log.  Returns the path, or None
    when the dir is unset / the per-process dump cap is hit / the
    write fails (best-effort, never raises)."""
    global _flight_dumps
    d = flight_dir()
    if not d:
        return None
    try:
        from dpark_tpu import trace
        from dpark_tpu.utils import frame_jsonl
        with _flight_lock:
            cap = int(getattr(conf, "FLIGHT_MAX_DUMPS", 16) or 0)
            if cap and _flight_dumps >= cap:
                return None
            _flight_dumps += 1
            seq = _flight_dumps
        os.makedirs(d, exist_ok=True)
        host = socket.gethostname()
        pid = os.getpid()
        path = os.path.join(d, "flight-%s-%d-%d.jsonl"
                            % (host, pid, seq))
        recs = [{"kind": "flight.header", "reason": str(reason),
                 "ts": round(time.time(), 6), "host": host,
                 "pid": pid, "run": trace.run_id()}]
        ring = trace.flight_snapshot()
        seen = {id(r) for r in ring}
        # the trace ring's tail rides along when a plane is up — the
        # immediate context around the warning events
        for r in trace.snapshot()[-256:]:
            if id(r) not in seen:
                ring.append(r)
        ring.sort(key=lambda r: r.get("ts", 0.0))
        recs.extend({"kind": "flight.event", "rec": r} for r in ring)
        recs.append({"kind": "flight.health", "snapshot": snapshot()})
        try:
            from dpark_tpu import ledger
            if ledger.active():
                # resource attribution rides the post-mortem (ISSUE
                # 15): who held the mesh when things went wrong
                recs.append({"kind": "flight.ledger",
                             "snapshot": ledger.snapshot()})
        except Exception:
            pass
        if record is not None:
            try:
                recs.append({"kind": "flight.job",
                             "record": json.loads(json.dumps(
                                 record, default=str))})
            except Exception:
                pass
        try:
            if scheduler is not None \
                    and hasattr(scheduler, "recovery_summary"):
                recs.append({"kind": "flight.recovery",
                             "summary": scheduler.recovery_summary()})
        except Exception:
            pass
        try:
            from dpark_tpu import adapt
            recs.append({"kind": "flight.adapt",
                         "summary": adapt.summary()})
        except Exception:
            pass
        blob = b""
        for rec in recs:
            try:
                blob += frame_jsonl(rec)
            except Exception:
                continue             # one unserializable row, not all
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        logger.warning("flight recorder dumped %d records -> %s "
                       "(reason: %s)", len(recs), path, reason)
        return path
    except Exception as e:
        logger.debug("flight dump failed: %s", e)
        return None


def load_flight(path):
    """Parse one flight dump back into its records (corrupt lines
    skip — the shared crc-framed contract).  Returns the record
    list."""
    from dpark_tpu.utils import unframe_jsonl
    with open(path, "rb") as f:
        raw = f.read()
    return unframe_jsonl(raw)[0]


def install_sigusr2():
    """Arm SIGUSR2 -> flight_dump("sigusr2") (main thread only; a
    no-op anywhere signals cannot be installed).  Called lazily the
    first time a scheduler finishes a job with DPARK_FLIGHT_DIR
    set — `kill -USR2 <pid>` then snapshots a LIVE process."""
    global _sigusr2_installed
    if _sigusr2_installed or not flight_dir():
        return False
    try:
        import signal

        def _on_usr2(signum, frame):
            flight_dump("sigusr2")

        signal.signal(signal.SIGUSR2, _on_usr2)
        _sigusr2_installed = True
        return True
    except (ValueError, OSError, AttributeError):
        # not the main thread / platform without SIGUSR2
        return False


# ---------------------------------------------------------------------------
# scheduler hooks (one call per job; cheap checks first)
# ---------------------------------------------------------------------------

def job_finished(scheduler, record):
    """Called from the scheduler's run_job finalizer: SLO accounting
    for service jobs, flight dump on abort, throttled site-tail
    persistence, SIGUSR2 arming.  Best-effort — never raises into
    the job path."""
    try:
        svc = getattr(scheduler, "_service", None)
        if svc is not None:
            try:
                svc.note_job_done(record)
            except Exception as e:
                logger.debug("slo accounting failed: %s", e)
        if record.get("state") == "aborted":
            from dpark_tpu import trace
            trace.flight("job.abort", "sched", job=record.get("id"),
                         scope=record.get("scope"),
                         seconds=record.get("seconds"))
            flight_dump("job-abort:%s" % record.get("id"),
                        scheduler=scheduler, record=record)
        if flight_dir():
            install_sigusr2()
        if _SINK is not None:
            persist_site_tails()
    except Exception as e:
        logger.debug("health job_finished hook failed: %s", e)


def _init_from_conf():
    m = str(getattr(conf, "DPARK_HEALTH", "on") or "on").lower()
    if m == "on":
        configure("on")


_init_from_conf()
