"""MutableDict: coarse-grained mutable KV usable inside jobs.

Reference parity: dpark/mutable_dict.py (SURVEY.md section 2.1) — a
partitioned dict whose writes inside tasks are buffered per-process and
merged back on the driver after each job, with conflict resolution by
write generation (last generation wins).  Reads see the driver snapshot
from job start (shipped via broadcast-like file), plus local writes.
"""

import os
import pickle
import threading
import uuid

from dpark_tpu.utils import atomic_file, compress, decompress
from dpark_tpu.utils.phash import portable_hash

_registry = {}           # uuid -> MutableDict instance in this process
_local = threading.local()


class MutableDict:
    def __init__(self, partitions=16):
        self.uuid = uuid.uuid4().hex
        self.partitions = partitions
        self.generation = 0
        self.data = {}                   # driver-side authoritative
        self._key_gen = {}               # key -> generation of last write
        self._updates = {}               # worker-side buffered writes
        self.is_driver = True
        _registry[self.uuid] = self
        self._snapshot_path_cache = None

    # -- api used inside and outside tasks -------------------------------
    def get(self, key, default=None):
        updates = self._updates
        if key in updates:
            return updates[key][0]
        return self.data.get(key, default)

    def _on_driver(self):
        """Fork-safe driver detection: instance flags survive fork, the
        env singleton's is_master is corrected by the worker bootstrap."""
        from dpark_tpu.env import env
        return self.is_driver and (not env.started or env.is_master)

    def put(self, key, value):
        if self._on_driver():
            self.generation += 1         # new snapshot for the next job
            self.data[key] = value
            self._key_gen[key] = self.generation
        else:
            self._updates[key] = (value, self.generation + 1)

    def __getitem__(self, key):
        val = self.get(key, _MISSING)
        if val is _MISSING:
            raise KeyError(key)
        return val

    def __setitem__(self, key, value):
        self.put(key, value)

    def __contains__(self, key):
        return self.get(key, _MISSING) is not _MISSING

    def items(self):
        merged = dict(self.data)
        merged.update({k: v for k, (v, g) in self._updates.items()})
        return merged.items()

    def partition_of(self, key):
        return portable_hash(key) % self.partitions

    # -- shipping ---------------------------------------------------------
    def _snapshot_path(self):
        from dpark_tpu.env import env
        d = os.path.join(env.workdir, "mutable_dict")
        return os.path.join(d, "%s-%d" % (self.uuid, self.generation))

    def _write_snapshot(self):
        path = self._snapshot_path()
        if not os.path.exists(path):
            with atomic_file(path) as f:
                f.write(compress(pickle.dumps(self.data, -1)))
        return path

    def __getstate__(self):
        path = self._write_snapshot() if self.is_driver else None
        return (self.uuid, self.partitions, self.generation,
                path or self._snapshot_path_cache)

    def __setstate__(self, state):
        self.uuid, self.partitions, self.generation, path = state
        self._snapshot_path_cache = path
        existing = _registry.get(self.uuid)
        if existing is not None and existing.generation >= self.generation:
            self.__dict__ = existing.__dict__
            return
        self.is_driver = False
        self._updates = {}
        self.data = {}
        self._key_gen = {}
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                self.data = pickle.loads(decompress(f.read()))
        _registry[self.uuid] = self

    # -- task lifecycle (driver merges updates shipped with results) -----
    def flush_updates(self):
        ups, self._updates = self._updates, {}
        return ups

    def merge_updates(self, updates):
        """Driver-side merge: per-key, a write from generation >= the
        key's last-written generation wins (same-generation tasks of one
        job race arbitrarily — reference semantics)."""
        changed = False
        for key, (value, gen) in updates.items():
            if gen >= self._key_gen.get(key, -1):
                self.data[key] = value
                self._key_gen[key] = gen
                changed = True
        if changed:
            # new generation so the NEXT job's snapshot is rewritten with
            # the merged state (snapshot files are keyed by generation)
            self.generation += 1


_MISSING = object()


def clear_task_updates():
    """Drop buffered writes (task start, and after a failed task) so a
    failed attempt's partial state never ships with a later task."""
    for md in _registry.values():
        if not md._on_driver():
            md._updates = {}


def collect_task_updates():
    """Gather buffered updates from every MutableDict in this process
    (called by the task runner, shipped back with results)."""
    out = {}
    for u, md in _registry.items():
        if not md._on_driver() and md._updates:
            out[u] = md.flush_updates()
    return out


def merge_on_driver(all_updates):
    for u, updates in (all_updates or {}).items():
        md = _registry.get(u)
        if md is not None and md._on_driver():
            md.merge_updates(updates)
