"""Shared-computation plane: the cross-tenant sub-plan result cache
(ISSUE 18 tentpole; ROADMAP item 5's first two legs).

At millions-of-users scale the same dashboards hit the same tables:
two tenants running the identical ``ctx.sql`` group-by each paid a
full scan + device exchange, even though the plan-signature machinery
(adapt.stable_key) can already prove the work identical and the
tabular v2 stats footer gives a cheap per-chunk source fingerprint.
PR 17 made restarts skip the COMPILE; this plane makes repeated
queries skip the WORK.

Keying.  An entry's identity is ``stable_key(("rc", format,
plan_signature(root), dtypes, fingerprints))``:

  * ``query.logical.plan_signature`` — the canonical subtree shape
    INCLUDING every expression text (``sketch()`` prints only
    ``name:func`` for aggregates, so ``sum(b)`` and ``sum(c)`` would
    collide on it);
  * the scan segments' resolved dtypes (the same promotion the adapt
    pricing key uses);
  * one ``tabular.source_fingerprint`` per part file: v2 files digest
    the footer statistics (rewriting any chunk drifts the digest
    without reading a data byte), v1 files fall back to
    (path, mtime_ns, size) — mutation ALWAYS means a miss, never a
    stale serve.

Serving.  The planner probes at plan time (``planner._rule_reuse``):

  * a FULL hit presets the planned query's row cache — no scan, no
    device exchange, no scheduler job; the logical root is replaced by
    a ``CachedResult`` leaf so ``explain()`` shows what did not run;
  * a PARTIAL-AGGREGATE hit (same group-by keys + mergeable combiner
    — sum/count/min/max, no avg/UDA — over the same source, where the
    cached entry's filter box is CONTAINED in the new query's and the
    difference is one single-interval residual on one int column)
    rewrites the plan to merge the cached aggregate rows with a
    residual scan over only the uncovered interval — the pane
    MergeTree's share-the-overlap idea lifted out of dstream/panes
    into a query-plane service ("Partial Partial Aggregates",
    PAPERS.md).

Storage.  Host-memory tier with size-budgeted LRU eviction
(``DPARK_RESULT_CACHE_BUDGET`` bytes); ``disk`` mode adds a
crc-framed on-disk tier that survives restarts alongside the AOT
cache — entry files are tmp+rename with a crc-framed header and a
crc/length-checked pickled payload, the index is O_APPEND whole-line
jsonl (the adapt-store idioms), and ANY defect means "miss and
recompute", never an error.

Tenancy.  One JobServer's tenants share the cache by default — a hit
is a hit no matter who stored it.  ``opt_out(tenant)`` removes a
tenant from BOTH directions (reads and writes); the ledger bills an
entry's byte-seconds of residency to the tenant that stored it and
counts hits/served-bytes against the tenant that was served (zero
scan device-seconds — the conservation check still holds because a
served query never touches the mesh).

Modes (``DPARK_RESULT_CACHE`` / conf.RESULT_CACHE):

  off   no plane installed.  The seams cost exactly one module-global
        load + ``is None`` check — the same off-mode contract as the
        faults/trace/health/ledger/lockcheck/aot planes,
        machine-checked by the ``plane-contract`` dlint rule.
  mem   host-memory LRU only.
  disk  mem + write-through to the on-disk tier; a restarting server
        boots its hottest entries back (ranked by the adapt store's
        reuse profiles) and serves its first repeated query with zero
        scan chunks.
"""

import os
import pickle
import threading
import time

from dpark_tpu import conf, locks
from dpark_tpu.utils import atomic_file, frame_jsonl, unframe_jsonl
from dpark_tpu.utils.log import get_logger

logger = get_logger("resultcache")

__all__ = ["MODES", "ResultCachePlane", "configure", "active",
           "plane", "stats", "probe", "offer", "merge_group_rows",
           "opt_out", "tenant"]

MODES = ("off", "mem", "disk")

# entry-format generation: bump on any layout/keying change so old
# dirs (and old-format index lines) skip instead of mis-serving
FORMAT = "dpark-rc-1"

INDEX_FILE = "index.jsonl"

COUNTERS = ("hits", "partial_hits", "misses", "stores",
            "store_errors", "oversize", "evictions", "disk_loads",
            "disk_stores", "load_errors", "version_skips",
            "preloaded", "opt_outs")

# partial merges admit only combiners whose FINAL value is also the
# mergeable accumulator (avg's final is s/c — not re-mergeable)
MERGEABLE = ("sum", "count", "min", "max")

_PLANE = None
_tls = threading.local()


def _crc(data):
    from dpark_tpu.shuffle import spill_crc
    return spill_crc(data)


class tenant:
    """Context manager overriding the tenant the calling thread's
    probes/offers attribute to (tests and embedded callers without a
    ClientScheduler)."""

    def __init__(self, name):
        self.name = str(name)

    def __enter__(self):
        self._prev = getattr(_tls, "tenant", None)
        _tls.tenant = self.name
        return self

    def __exit__(self, *exc):
        _tls.tenant = self._prev
        return False


def merge_group_rows(cached, fresh, nk, kinds):
    """Merge two disjoint-source group-aggregate row sets (rows are
    (key..., val...) tuples of one schema): sum/count add, min/max
    fold, keys present on one side only pass through.  Output is
    sorted by key so the merged path is deterministic."""
    acc = {}
    for row in cached:
        acc[row[:nk]] = list(row[nk:])
    for row in fresh:
        key = row[:nk]
        vals = acc.get(key)
        if vals is None:
            acc[key] = list(row[nk:])
            continue
        for i, kind in enumerate(kinds):
            v = row[nk + i]
            if kind in ("sum", "count"):
                vals[i] = vals[i] + v
            elif kind == "min":
                vals[i] = v if v < vals[i] else vals[i]
            else:                   # max
                vals[i] = v if v > vals[i] else vals[i]
    return [k + tuple(v) for k, v in sorted(acc.items())]


def _interval_contains(outer, inner):
    """Closed-interval containment with None = unbounded."""
    lo1, hi1 = outer
    lo2, hi2 = inner
    if lo1 is not None and (lo2 is None or lo2 < lo1):
        return False
    if hi1 is not None and (hi2 is None or hi2 > hi1):
        return False
    return True


def _residual_intervals(new, cand):
    """The (up to two) closed int intervals of ``new - cand`` given
    ``cand`` contained in ``new``."""
    lo1, hi1 = new
    lo2, hi2 = cand
    out = []
    if lo2 is not None and (lo1 is None or lo1 <= lo2 - 1):
        out.append((lo1, lo2 - 1))
    if hi2 is not None and (hi1 is None or hi1 >= hi2 + 1):
        out.append((hi2 + 1, hi1))
    return out


def _range_pred_text(col, rng):
    lo, hi = rng
    parts = []
    if lo is not None:
        parts.append("%s >= %d" % (col, lo))
    if hi is not None:
        parts.append("%s <= %d" % (col, hi))
    return " and ".join(parts)


class ResultCachePlane:
    """One JobServer's shared sub-plan result cache."""

    def __init__(self, mode, cache_dir, budget_bytes):
        self.mode = mode
        self.dir = cache_dir
        self.budget = max(1, int(budget_bytes))
        self._mu = locks.named_lock("resultcache.store")
        self._counters = {k: 0 for k in COUNTERS}
        self._mem = {}           # key -> entry (insertion order = LRU)
        self._bytes = 0
        self._partials = {}      # group_sig -> {key, ...}
        self._opt_out = set()
        if mode == "disk":
            try:
                os.makedirs(cache_dir, exist_ok=True)
            except OSError as e:
                logger.debug("result cache dir %s: %s", cache_dir, e)

    # -- bookkeeping -----------------------------------------------------
    def _bump(self, name, n=1):
        with self._mu:
            self._counters[name] += n

    def set_opt_out(self, tenant_name, flag=True):
        with self._mu:
            if flag:
                self._opt_out.add(str(tenant_name))
            else:
                self._opt_out.discard(str(tenant_name))

    def _tenant_of(self, pq):
        t = getattr(_tls, "tenant", None)
        if t:
            return str(t)
        sched = getattr(getattr(pq, "ctx", None), "scheduler", None)
        return str(getattr(sched, "client", None) or "local")

    def stats(self):
        with self._mu:
            out = dict(self._counters)
            out["mode"] = self.mode
            out["entries"] = len(self._mem)
            out["bytes"] = int(self._bytes)
            out["budget_bytes"] = int(self.budget)
        return out

    # -- keying ----------------------------------------------------------
    def _key_of(self, pq):
        """(key, group_sig, ranges, meta) of a plannable query, or
        None when the plan is uncacheable (non-tabular source, UDA
        aggregates, unsignable expressions).  group_sig/ranges/meta
        are None unless the plan is partial-merge ELIGIBLE."""
        from dpark_tpu import adapt, tabular
        from dpark_tpu.query import logical
        if pq.mode not in ("scan", "group", "join", "join_group"):
            return None
        try:
            sig = logical.plan_signature(pq.root)
        except Exception:
            return None
        fps = []
        for seg in pq.segs:
            src = seg.scan.source
            if not isinstance(src, tabular.TabularRDD):
                return None     # in-memory sources mutate invisibly
            fps.append(tuple(tabular.source_fingerprint(p)
                             for p in src.files))
        for node in logical.iter_plan(pq.root):
            if isinstance(node, logical.GroupAgg):
                for a in node.aggs:
                    if a[1] == "uda" or a[3] is not None:
                        return None     # UDA identity is not stable
        dtypes = tuple(sorted((k, str(v)) for s in pq.segs
                              for k, v in s.dtypes.items()))
        key = adapt.stable_key(("rc", FORMAT, sig, dtypes,
                                tuple(fps)))
        group_sig = ranges = meta = None
        part = self._partial_shape(pq)
        if part is not None:
            ranges, g_node = part
            scan = pq.segs[0].scan
            gsig = ("rc-part", FORMAT,
                    tuple((n, ce.expr) for n, ce in g_node.keys),
                    tuple((a[0], a[1],
                           a[2].expr if a[2] is not None else None)
                          for a in g_node.aggs),
                    ("Scan", scan.table_name, tuple(scan.fields)),
                    dtypes, tuple(fps))
            group_sig = adapt.stable_key(gsig)
            g = pq._group
            meta = {"ranges": {c: list(r) for c, r in ranges.items()},
                    "nk": int(g["nk"]), "kinds": list(g["kinds"]),
                    "fields": list(g["key_names"])
                    + list(g["agg_names"])}
        return key, group_sig, ranges, meta

    def _partial_shape(self, pq):
        """(filter ranges, GroupAgg node) when the plan is
        partial-merge eligible, else None: a single-segment group over
        Filter-only scan ops, general-reduce lowering, mergeable
        combiners, no egest, and every predicate fully captured as an
        int-column range box."""
        from dpark_tpu.query.logical import Filter
        if pq.mode != "group" or pq.egest_ops or len(pq.segs) != 1:
            return None
        g = pq._group
        # any lowering whose FINAL rows are still mergeable
        # accumulators qualifies (avg finalizes s/c, UDAs are opaque
        # — both excluded below via kinds)
        if g is None or g["lower"] not in ("classified", "reduce"):
            return None
        if not g["kinds"] or any(k not in MERGEABLE
                                 for k in g["kinds"]):
            return None
        sh = getattr(pq, "_shape", None) or {}
        ops = sh.get("scan_ops", ())
        if any(not isinstance(op, Filter) for op in ops):
            return None
        preds = [p for op in ops for p in op.preds]
        ranges = self._full_ranges(preds, pq.segs[0])
        if ranges is None:
            return None
        return ranges, sh["group"]

    @staticmethod
    def _full_ranges(preds, seg):
        """{col: (lo, hi)} ONLY when every predicate is a conjunction
        of ``int_col <cmp> int_literal`` compares — the ranges then
        EXACTLY describe the filter region (unlike planner
        _skip_bounds, which is a conservative superset), so interval
        arithmetic on them is sound.  None on any uncaptured piece."""
        import ast
        dtypes = getattr(seg, "src_dtypes", None) or seg.dtypes or {}
        out = {}

        def add(col, lo, hi):
            plo, phi = out.get(col, (None, None))
            if lo is not None:
                plo = lo if plo is None else max(plo, lo)
            if hi is not None:
                phi = hi if phi is None else min(phi, hi)
            out[col] = (plo, phi)

        def visit(node):
            if isinstance(node, ast.BoolOp) \
                    and isinstance(node.op, ast.And):
                return all(visit(v) for v in node.values)
            if not isinstance(node, ast.Compare) \
                    or len(node.ops) != 1:
                return False
            left, op, right = (node.left, node.ops[0],
                               node.comparators[0])
            flip = False
            if isinstance(left, ast.Name) \
                    and isinstance(right, ast.Constant):
                name, const = left.id, right.value
            elif isinstance(right, ast.Name) \
                    and isinstance(left, ast.Constant):
                name, const = right.id, left.value
                flip = True
            else:
                return False
            if isinstance(const, bool) \
                    or not isinstance(const, int):
                return False
            try:
                import numpy as np
                if np.dtype(dtypes.get(name, object)).kind != "i":
                    return False
            except TypeError:
                return False
            opname = type(op).__name__
            if flip:
                opname = {"Lt": "Gt", "LtE": "GtE", "Gt": "Lt",
                          "GtE": "LtE"}.get(opname, opname)
            if opname == "Eq":
                add(name, const, const)
            elif opname == "Gt":
                add(name, const + 1, None)
            elif opname == "GtE":
                add(name, const, None)
            elif opname == "Lt":
                add(name, None, const - 1)
            elif opname == "LtE":
                add(name, None, const)
            else:
                return False
            return True

        for p in preds:
            body = p.tree.body if p.tree is not None else None
            if body is None or not visit(body):
                return None
        return out

    # -- memory tier -----------------------------------------------------
    def get(self, key):
        """Entry for ``key`` or None: memory first (LRU touch), then
        the disk tier in disk mode (a disk hit re-enters memory)."""
        with self._mu:
            ent = self._mem.get(key)
            if ent is not None:
                # LRU touch: re-insert at the MRU end
                del self._mem[key]
                self._mem[key] = ent
                return ent
        if self.mode != "disk":
            return None
        ent = self._load_entry(key)
        if ent is None:
            return None
        self._bump("disk_loads")
        self._insert(key, ent, write_disk=False)
        return ent

    def _insert(self, key, ent, write_disk):
        evicted = []
        with self._mu:
            old = self._mem.pop(key, None)
            if old is not None:
                self._bytes -= old["nbytes"]
            self._mem[key] = ent
            self._bytes += ent["nbytes"]
            if ent.get("group_sig"):
                self._partials.setdefault(ent["group_sig"],
                                          set()).add(key)
            while self._bytes > self.budget and len(self._mem) > 1:
                k, e = next(iter(self._mem.items()))
                if k == key:
                    break
                del self._mem[k]
                self._bytes -= e["nbytes"]
                self._counters["evictions"] += 1
                evicted.append((k, e))
        # events emit OUTSIDE the plane mutex (resultcache.store
        # orders before trace.plane in locks.DOCUMENTED_ORDER, but
        # not holding it across the sink fold is cheaper and safer)
        from dpark_tpu import trace
        for k, e in evicted:
            # in disk mode the entry file survives eviction — only
            # the memory-tier residency (the billed byte-seconds)
            # ends here
            trace.event("resultcache.release", "resultcache", sid=k,
                        bytes=e["nbytes"], reason="evict",
                        tenant=e.get("tenant"))

    def offer(self, pq, rows):
        """Store one finished query's result rows under the offer the
        probe recorded at plan time.  Size-gated; never raises."""
        off = getattr(pq, "_cache_offer", None)
        if off is None:
            return False
        pq._cache_offer = None
        try:
            key = off["key"]
            fields = list(pq._out_fields or [])
            meta = off.get("meta")
            blob = pickle.dumps((fields, list(rows), meta),
                                protocol=pickle.HIGHEST_PROTOCOL)
            nbytes = len(blob)
            if nbytes > self.budget:
                self._bump("oversize")
                return False
            ent = {"rows": list(rows), "fields": fields,
                   "nbytes": nbytes, "meta": meta,
                   "group_sig": off.get("group_sig"),
                   "tenant": off.get("tenant")}
            self._insert(key, ent, write_disk=True)
            self._bump("stores")
            from dpark_tpu import trace
            trace.event("resultcache.store", "resultcache", sid=key,
                        bytes=nbytes, tenant=off.get("tenant"))
            if self.mode == "disk":
                self._store_entry(key, blob, ent)
            return True
        except Exception as e:
            logger.debug("result cache offer failed: %s", e)
            self._bump("store_errors")
            return False

    # -- disk tier -------------------------------------------------------
    def _entry_path(self, key):
        return os.path.join(self.dir, key + ".rc")

    def _store_entry(self, key, blob, ent):
        try:
            header = {"fmt": FORMAT, "k": key,
                      "nbytes": len(blob),
                      "group_sig": ent.get("group_sig"),
                      "tenant": ent.get("tenant"),
                      "created": round(time.time(), 3)}
            with atomic_file(self._entry_path(key)) as f:
                f.write(frame_jsonl(header))
                f.write(b"%08x %08x\n" % (_crc(blob), len(blob)))
                f.write(blob)
            self._append_index({"k": key, "fmt": FORMAT,
                                "nbytes": len(blob),
                                "group_sig": ent.get("group_sig"),
                                "meta": ent.get("meta")})
            self._bump("disk_stores")
        except Exception as e:
            logger.debug("result cache disk store failed for %s: %s",
                         key, e)
            self._bump("store_errors")

    def _append_index(self, rec):
        """One crc-framed line, one O_APPEND write: concurrent
        replicas interleave whole lines (the adapt-store idiom)."""
        line = frame_jsonl(rec)
        fd = os.open(os.path.join(self.dir, INDEX_FILE),
                     os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)

    def index(self):
        """{key: latest index record}, current-format lines only.
        Torn/corrupt lines skip; duplicate keys fold latest-wins."""
        try:
            with open(os.path.join(self.dir, INDEX_FILE), "rb") as f:
                raw = f.read()
        except OSError:
            return {}
        recs, _ = unframe_jsonl(raw)
        out = {}
        for r in recs:
            k = r.get("k")
            if not k:
                continue
            if r.get("fmt") != FORMAT:
                continue
            out[str(k)] = r
        return out

    def _load_entry(self, key):
        """Read one entry file; None on ANY defect — missing file,
        torn header, format drift, payload crc or length mismatch,
        unpicklable blob.  Corruption means recompute, never an
        error (the adapt-store contract)."""
        try:
            with open(self._entry_path(key), "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            head, _, rest = raw.partition(b"\n")
            recs, skipped = unframe_jsonl(head + b"\n")
            if skipped or not recs:
                raise ValueError("corrupt header")
            header = recs[0]
            if header.get("fmt") != FORMAT:
                self._bump("version_skips")
                return None
            crcline, _, blob = rest.partition(b"\n")
            crc_hex, _, len_hex = crcline.partition(b" ")
            if len(blob) != int(len_hex, 16):
                raise ValueError("truncated payload")
            if int(crc_hex, 16) != _crc(blob):
                raise ValueError("payload crc mismatch")
            fields, rows, meta = pickle.loads(blob)
            gs = header.get("group_sig")
            if meta is not None and isinstance(meta.get("ranges"),
                                               dict):
                meta["ranges"] = {c: tuple(r) for c, r in
                                  meta["ranges"].items()}
            return {"rows": [tuple(r) for r in rows],
                    "fields": list(fields), "nbytes": len(blob),
                    "meta": meta, "group_sig": gs,
                    "tenant": header.get("tenant")}
        except Exception as e:
            logger.debug("result cache entry %s unusable: %s",
                         key, e)
            self._bump("load_errors")
            return None

    def boot(self, budget_bytes=None):
        """Disk-mode boot: load the index and preload the hottest
        entries (ranked by the adapt store's reuse profiles) into the
        memory tier up to a byte budget, so a restarted server's
        first repeated query serves from memory.  Returns a summary
        for service_stats; never raises past the caller's guard."""
        t0 = time.time()
        if self.mode != "disk":
            return {"entries": 0, "preloaded": 0, "bytes": 0,
                    "ms": 0.0}
        idx = self.index()
        try:
            from dpark_tpu import adapt
            profiles = adapt.reuse_profiles()
        except Exception:
            profiles = {}

        def _score(rec):
            prof = profiles.get(str(rec.get("k"))) or {}
            return (float(prof.get("hits", 0) or 0),
                    -float(rec.get("nbytes", 0) or 0))

        cap = min(self.budget,
                  int(budget_bytes or self.budget)) // 2
        loaded = 0
        nbytes = 0
        for rec in sorted(idx.values(), key=_score, reverse=True):
            key = str(rec.get("k"))
            if nbytes + int(rec.get("nbytes", 0) or 0) > cap:
                continue
            ent = self._load_entry(key)
            if ent is None:
                continue
            self._insert(key, ent, write_disk=False)
            self._bump("preloaded")
            loaded += 1
            nbytes += ent["nbytes"]
        return {"entries": len(idx), "preloaded": loaded,
                "bytes": int(nbytes),
                "ms": round((time.time() - t0) * 1e3, 1)}

    # -- probing ---------------------------------------------------------
    def probe(self, pq):
        """Plan-time cache consult: returns "hit", "partial", or None
        (miss/ineligible).  On a miss the offer for this key is left
        on the planned query so its first execution stores back."""
        tname = self._tenant_of(pq)
        with self._mu:
            opted_out = tname in self._opt_out
        if opted_out:
            self._bump("opt_outs")
            return None
        keyinfo = self._key_of(pq)
        if keyinfo is None:
            return None
        key, group_sig, ranges, meta = keyinfo
        ent = self.get(key)
        if ent is not None:
            self._serve_full(pq, key, ent, tname, tier="full")
            return "hit"
        got = None
        if group_sig is not None:
            got = self._probe_partial(pq, key, group_sig, ranges,
                                      tname)
        pq._cache_offer = {"key": key, "group_sig": group_sig,
                           "meta": meta, "tenant": tname}
        if got is None:
            self._bump("misses")
            self._reuse_note(key, misses=1)
        return got

    def _serve_full(self, pq, key, ent, tname, tier):
        from dpark_tpu import trace
        from dpark_tpu.query.logical import CachedResult
        replaced = pq.root.describe()
        pq._rows_cache = list(ent["rows"])
        pq._out_fields = list(ent["fields"])
        pq.root = CachedResult(list(ent["fields"]), replaced,
                               key[:12])
        pq.decide("result-cache", "plan", "cache",
                  "%s hit: %d rows served from the shared result "
                  "cache (stored by tenant %r); no scan, no device "
                  "exchange" % (tier, len(ent["rows"]),
                                ent.get("tenant")))
        self._bump("hits")
        self._reuse_note(key, hits=1)
        trace.event("resultcache.serve", "resultcache", sid=key,
                    bytes=ent["nbytes"], tier=tier, tenant=tname)

    def _probe_partial(self, pq, key, group_sig, new_ranges, tname):
        """Candidate walk: same group signature, contained filter box,
        single-interval residual on exactly one column."""
        with self._mu:
            cand_keys = list(self._partials.get(group_sig, ()))
        if self.mode == "disk" and not cand_keys:
            cand_keys = [k for k, r in self.index().items()
                         if r.get("group_sig") == group_sig]
        for key2 in cand_keys:
            if key2 == key:
                continue
            ent = self.get(key2)
            if ent is None or ent.get("meta") is None:
                continue
            meta = ent["meta"]
            cand_ranges = {c: tuple(r) for c, r in
                           (meta.get("ranges") or {}).items()}
            plan = self._residual_plan(pq, new_ranges, cand_ranges)
            if plan is None:
                continue
            if plan == "equal":
                # range-equivalent filters with different texts
                # ("t >= 100000" vs "t > 99999"): the cached rows ARE
                # the answer
                self._serve_full(pq, key2, ent, tname,
                                 tier="equivalent")
                return "hit"
            from dpark_tpu import trace
            pq._partial = {"rows": list(ent["rows"]),
                           "nk": int(meta["nk"]),
                           "kinds": tuple(meta["kinds"]),
                           "fields": list(ent["fields"]),
                           "residual": plan, "key": key2}
            pq.decide("result-cache", "plan", "cache",
                      "partial-aggregate hit: cached rows for ranges "
                      "%s merge with a residual scan of %s"
                      % (dict(sorted(cand_ranges.items())),
                         plan.children[0].describe()))
            self._bump("partial_hits")
            self._reuse_note(key2, partials=1)
            trace.event("resultcache.serve", "resultcache", sid=key2,
                        bytes=ent["nbytes"], tier="partial",
                        tenant=tname)
            return "partial"
        return None

    def _residual_plan(self, pq, new_ranges, cand_ranges):
        """A fresh GroupAgg(Filter(Scan)) logical tree covering
        exactly ``new - cand``, "equal" when the regions coincide, or
        None when the difference is not one single-interval column."""
        from dpark_tpu.query import exprs as E
        from dpark_tpu.query.logical import Filter, GroupAgg, Scan
        cols = set(new_ranges) | set(cand_ranges)
        diff_col = None
        residual = None
        for c in sorted(cols):
            n = new_ranges.get(c, (None, None))
            k = cand_ranges.get(c, (None, None))
            if n == k:
                continue
            if not _interval_contains(n, k):
                return None     # cached is not narrower: no merge
            if diff_col is not None:
                return None     # two differing columns: not a box
            ivs = _residual_intervals(n, k)
            if len(ivs) > 1:
                return None     # split residual needs two scans
            diff_col = c
            residual = ivs[0] if ivs else None
        if diff_col is None or residual is None:
            return "equal" if diff_col is None else None
        old_scan = pq.segs[0].scan
        scan = Scan(old_scan.source, list(old_scan.fields),
                    old_scan.table_name)
        texts = [_range_pred_text(diff_col, residual)]
        for c in sorted(cols):
            if c == diff_col:
                continue
            t = _range_pred_text(c, new_ranges.get(c, (None, None)))
            if t:
                texts.append(t)
        preds = [E.compile_expr(t, list(scan.fields))
                 for t in texts if t]
        g = pq._shape["group"]
        return GroupAgg(Filter(scan, preds), list(g.keys),
                        list(g.aggs))

    def _reuse_note(self, key, hits=0, misses=0, partials=0):
        try:
            from dpark_tpu import adapt
            adapt.record_reuse(key, hits=hits, misses=misses,
                               partials=partials)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# module seams (plane-contract shapes, registered in
# analysis/concurrency.py PLANE_SEAMS)
# ---------------------------------------------------------------------------

def probe(pq):
    """Plan-time cache consult for one planned query: "hit",
    "partial", or None.  One global load + ``is None`` check when the
    plane is off."""
    plane = _PLANE
    if plane is None:
        return None
    return plane.probe(pq)


def offer(pq, rows):
    """Run-time store-back of a finished query whose probe recorded
    an offer.  One global load + ``is None`` check when off."""
    plane = _PLANE
    if plane is None:
        return False
    return plane.offer(pq, rows)


def stats():
    """Hot counters + mode/occupancy for /metrics and /api/health;
    None when the plane is off."""
    plane = _PLANE
    if plane is None:
        return None
    return plane.stats()


def opt_out(tenant_name, flag=True):
    """Remove (or re-admit) one tenant from cross-tenant sharing —
    both directions: an opted-out tenant neither reads nor stores."""
    plane = _PLANE
    if plane is None:
        return False
    plane.set_opt_out(tenant_name, flag)
    return True


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(mode=None, cache_dir=None, budget_bytes=None):
    """Install (mem/disk) or clear (off) the process plane.  None
    reads conf.RESULT_CACHE.  Returns the installed plane or None."""
    global _PLANE
    if mode is None:
        mode = str(getattr(conf, "RESULT_CACHE", "off") or "off")
    mode = str(mode).strip().lower()
    if mode in ("", "0", "none", "disable", "disabled"):
        mode = "off"
    if mode not in MODES:
        raise ValueError("DPARK_RESULT_CACHE=%r (expected "
                         "off|mem|disk)" % mode)
    if mode == "off":
        _PLANE = None
        return None
    _PLANE = ResultCachePlane(
        mode, cache_dir or conf.RESULT_CACHE_DIR,
        budget_bytes or getattr(conf, "RESULT_CACHE_BUDGET", 0)
        or (64 << 20))
    return _PLANE


def active():
    return _PLANE is not None


def plane():
    return _PLANE


def _init_from_conf():
    m = str(getattr(conf, "RESULT_CACHE", "off") or "off")
    if m not in ("off", ""):
        configure(m)


_init_from_conf()
