"""Resident executor service (ISSUE 9): one mesh, many concurrent jobs.

dpark's one-process-one-job heritage made every CLI run pay the full
trace+compile bill and hold the mesh exclusively.  This module splits
"mesh owner" from "job driver": a long-lived :class:`JobServer` owns
the ONE scheduler (and, for ``-m tpu``, the one JAXExecutor + device
mesh) for the life of the process, and N drivers multiplex their DAGs
onto it.  Concurrent jobs share the bounded compiled-program cache and
the HBM shuffle store (quota/LRU arbitration with disk spill — see
executor._evict_hbm), so a warm re-submission compiles NOTHING and a
second tenant never cold-starts the mesh.

Two transports:

* **in-process threads** — every :class:`DparkContext` created with
  ``DPARK_SERVICE=<master spec>`` (or master ``service[:spec]``)
  attaches a :class:`ClientScheduler` to the process-global server;
  each context's ``runJob`` drives its own job from its own thread.
* **remote** — :func:`serve` listens on the dcn framed-TCP channel and
  accepts pickled *job functions* (``fn(ctx) -> result``).  Shipping
  the driver FUNCTION rather than a built RDD graph sidesteps both
  the splits-stay-driver-side serialization contract and cross-client
  rdd/shuffle id collisions: the graph is built inside the server,
  in the server's id namespace.  :class:`ServiceClient` is the caller
  side.  Job payloads are unpickled BY DESIGN (a job is code); set
  DPARK_DCN_SECRET so only HMAC-authenticated peers can submit.

Scheduling: each ``submit_tasks`` call becomes one WORK ITEM (a "wave
slot") in the owning job's FIFO queue; ``conf.SERVICE_SLOTS`` slot
threads drain the queues WEIGHTED ROUND-ROBIN (a weight-2 job gets two
turns per cycle), so a long job cannot starve a short one.  Device
stages additionally serialize on the executor's mesh lock — the
fairness interleaving is between jobs' stages, and the overlap win is
one job's host/object-path work riding alongside another's device
stage.  Admission control bounds the blast radius: at most
``conf.SERVICE_MAX_JOBS`` jobs run concurrently, at most
``conf.SERVICE_QUEUE_MAX`` wait; past that, submission FAILS fast.

With ``DPARK_SERVICE`` unset nothing here is imported on the hot path
and every seam is one ``is None`` check (the faults.py contract).
"""

import base64
import itertools
import pickle
import threading
import time
import traceback
from collections import deque

from dpark_tpu import aotcache
from dpark_tpu import conf
from dpark_tpu import locks
from dpark_tpu import resultcache
from dpark_tpu.utils.log import get_logger

logger = get_logger("service")

_STOP = object()                 # slot-thread shutdown sentinel


class _Work:
    """One submit_tasks call from one job's driver — the unit the
    fair dispatcher interleaves."""
    __slots__ = ("sched", "record", "stage", "tasks", "report")

    def __init__(self, sched, record, stage, tasks, report):
        self.sched = sched
        self.record = record
        self.stage = stage
        self.tasks = tasks
        self.report = report


class _JobState:
    __slots__ = ("queue", "weight", "credits", "record")

    def __init__(self, weight, record):
        self.queue = deque()
        self.weight = max(1, int(weight or 1))
        self.credits = self.weight
        self.record = record


class _Tenant:
    """Per-tenant SLO accounting (ISSUE 14): tenants declare a per-job
    latency target (``ServiceClient(..., slo_ms=)`` /
    ``DPARK_SERVICE_SLO``); the server tracks lifetime attainment and
    a multi-window burn rate — how fast violations consume the
    ``1 - SERVICE_SLO_TARGET`` error budget (burn 1.0 = exactly as
    fast as allowed; 2.0 = twice as fast, the classic paging
    threshold).  The window deque is bounded by the longest burn
    horizon, so a resident server's memory stays flat."""
    __slots__ = ("slo_ms", "jobs", "violations", "window")

    def __init__(self, slo_ms):
        self.slo_ms = float(slo_ms)
        self.jobs = 0
        self.violations = 0
        self.window = deque()           # (ts, ok)

    def note(self, now, ok):
        self.jobs += 1
        if not ok:
            self.violations += 1
        self.window.append((now, ok))
        horizon = max(conf.SERVICE_SLO_WINDOWS or (600.0,))
        while self.window and self.window[0][0] < now - horizon:
            self.window.popleft()

    def stats(self, now):
        budget = max(1e-9, 1.0 - float(conf.SERVICE_SLO_TARGET))
        burn = {}
        for w in (conf.SERVICE_SLO_WINDOWS or (600.0,)):
            recent = [ok for ts, ok in self.window if ts >= now - w]
            rate = (sum(1 for ok in recent if not ok) / len(recent)
                    if recent else 0.0)
            burn["%ds" % int(w)] = round(rate / budget, 3)
        return {"slo_ms": self.slo_ms, "jobs": self.jobs,
                "violations_total": self.violations,
                "attainment": round(1.0 - self.violations
                                    / self.jobs, 4)
                if self.jobs else 1.0,
                "burn": burn}


def _make_scheduler(spec):
    """The job server's INNER scheduler — the actual mesh owner.
    Accepts the same master grammar as DparkContext."""
    from dpark_tpu import schedule
    master, _, arg = str(spec or "local").partition(":")
    if master in ("", "local"):
        return schedule.LocalScheduler()
    if master in ("process", "multiprocess"):
        return schedule.MultiProcessScheduler(int(arg) if arg else None)
    if master == "fleet":
        return schedule.LocalFleetScheduler(int(arg) if arg else 2)
    if master == "tpu":
        from dpark_tpu.backend.tpu import TPUScheduler
        return TPUScheduler(int(arg) if arg else None)
    raise ValueError("unknown service master %r "
                     "(local/process/fleet/tpu)" % (spec,))


class JobServer:
    """Owns one scheduler (mesh + executor) and multiplexes many
    concurrent jobs onto it with weighted-round-robin fairness."""

    def __init__(self, master=None, slots=None, max_jobs=None,
                 queue_max=None):
        self.master = master or conf.DPARK_SERVICE or "local"
        self.slots = max(1, int(slots or conf.SERVICE_SLOTS))
        self.max_jobs = max(1, int(max_jobs or conf.SERVICE_MAX_JOBS))
        self.queue_max = int(conf.SERVICE_QUEUE_MAX
                             if queue_max is None else queue_max)
        self.scheduler = None
        self._threads = []
        self._cv = threading.Condition()
        self._jobs = {}              # job id -> _JobState
        self._rr = []                # job ids in round-robin order
        self._rr_pos = 0
        self._stopped = False
        self._started = False
        self._tls = threading.local()     # per-driver client/weight
        # admission control
        self._adm_cv = threading.Condition()
        self._active_jobs = 0
        self._waiting_jobs = 0
        # graceful degradation (ISSUE 20): draining refuses NEW jobs
        # while in-flight ones run to their wave boundaries
        self._draining = False
        self._lock = locks.named_lock("service.server")
        # per-tenant bulk-stream bytes (ISSUE 12; see note_bulk)
        self._bulk_bytes = {}
        # per-tenant SLO accounting (ISSUE 14; see note_job_done)
        self._tenants = {}
        # boot-warming summary (ISSUE 17; see _boot_warm)
        self._aot_warm = None
        # result-cache boot summary (ISSUE 18; see _boot_resultcache)
        self._rc_boot = None

    # -- lifecycle -------------------------------------------------------
    def start(self):
        with self._lock:
            if self._started:
                return self
            from dpark_tpu.env import env
            env.start(is_master=True)
            self.scheduler = _make_scheduler(self.master)
            self.scheduler.start()
            self.scheduler._service = self
            # instant-on serving (ISSUE 17): with the AOT plane
            # installed, pre-deserialize the hottest previously-seen
            # programs before the first submission arrives
            plane = aotcache._PLANE
            if plane is not None:
                self._boot_warm(plane)
            # shared computation (ISSUE 18): a disk-tier result cache
            # preloads its hottest entries so the first repeated
            # query after a restart serves with zero scan chunks
            rc = resultcache._PLANE
            if rc is not None:
                self._boot_resultcache(rc)
            self._stopped = False
            for i in range(self.slots):
                t = threading.Thread(target=self._slot_loop,
                                     name="dpark-service-slot-%d" % i,
                                     daemon=True)
                t.start()
                self._threads.append(t)
            self._started = True
            import atexit
            atexit.register(self.stop)
            logger.info("job server up: master=%s slots=%d "
                        "max_jobs=%d", self.master, self.slots,
                        self.max_jobs)
        return self

    def _boot_warm(self, plane):
        """Boot-warming pass (ISSUE 17): deserialize the hottest
        previously-seen programs — ranked by the adapt store's
        observed compile ms x hit count — into the AOT plane's
        preload map under the DPARK_AOT_WARM_BUDGET_MS deadline, so a
        restarted server's first submission starts from loaded
        executables.  The pass runs under the ``__boot__``
        pseudo-tenant span context: any work it triggers folds to the
        boot account, never a real tenant's, and the ledger's
        conservation ratio stays 1.0 (no mesh occupancy is drawn).
        Never raises — a defective cache dir means a cold start, not
        a dead server."""
        budget = float(getattr(conf, "AOT_WARM_BUDGET_MS", 0.0) or 0.0)
        if budget <= 0:
            return
        from dpark_tpu import trace
        try:
            with trace.ctx(job="__boot__"):
                summary = plane.warm(budget)
            self._aot_warm = summary
            logger.info(
                "aot boot warm: %d/%d entries in %.0f ms (budget "
                "%.0f ms)", summary["warmed"], summary["entries"],
                summary["ms"], summary["budget_ms"])
        except Exception as e:
            logger.debug("aot boot warm failed: %s", e)

    def _boot_resultcache(self, rc):
        """Result-cache boot pass (ISSUE 18): load the disk tier's
        index and preload the hottest entries (ranked by the adapt
        store's reuse profiles) into the memory tier.  Same contract
        as _boot_warm: runs as the ``__boot__`` pseudo-tenant, never
        raises — a defective cache dir means cold serving, not a dead
        server."""
        from dpark_tpu import trace
        try:
            with trace.ctx(job="__boot__"):
                summary = rc.boot()
            self._rc_boot = summary
            if summary.get("entries"):
                logger.info(
                    "result cache boot: %d/%d entries (%d bytes) in "
                    "%.0f ms", summary["preloaded"],
                    summary["entries"], summary["bytes"],
                    summary["ms"])
        except Exception as e:
            logger.debug("result cache boot failed: %s", e)

    def stop(self):
        with self._lock:
            if not self._started:
                return
            self._started = False
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=10)
        self._threads = []
        sched = self.scheduler
        if sched is not None:
            sched._service = None
            sched.stop()

    # -- submission (driver side) ---------------------------------------
    def declare_slo(self, client, slo_ms):
        """Register (or update) a tenant's per-job latency target in
        ms.  0/None clears nothing — an SLO once declared sticks for
        the life of the server (the accounting history must not reset
        because one submission omitted the knob)."""
        if not client or not slo_ms:
            return
        with self._cv:
            t = self._tenants.get(client)
            if t is None:
                self._tenants[client] = _Tenant(slo_ms)
            else:
                t.slo_ms = float(slo_ms)

    def note_job_done(self, record):
        """SLO accounting hook (called once per finished service job
        via health.job_finished): grade the job's submit-to-done
        latency against its tenant's declared target and attach the
        verdict to the record (the web UI's SLO column)."""
        client = record.get("client")
        if not client:
            return
        import time as _time
        now = _time.time()
        with self._cv:
            t = self._tenants.get(client)
            if t is None or not t.slo_ms:
                return
            # submit-to-done: record["seconds"] measures from record
            # mint (post-admission submit) through the last yielded
            # partition, so it already contains queue_wait_ms and
            # first_wave_ms — the evidence fields ride the record
            lat = float(record.get("seconds", 0.0) or 0.0) * 1e3
            ok = lat <= t.slo_ms
            t.note(now, ok)
            record["slo"] = {"slo_ms": t.slo_ms,
                             "latency_ms": round(lat, 1),
                             "ok": bool(ok)}

    def tenant_slo_stats(self):
        """{client: {slo_ms, jobs, violations_total, attainment,
        burn: {window: rate}}} for every tenant with a declared SLO
        — /metrics and /api/health read this."""
        import time as _time
        now = _time.time()
        with self._cv:
            return {c: t.stats(now)
                    for c, t in self._tenants.items()}

    def submit(self, rdd, func, partitions=None, allow_local=False,
               client=None, weight=None, slo_ms=None):
        """Generator over per-partition results, like run_job — but
        admission-controlled and driven through the fair dispatcher.
        The generator body runs on the CALLING thread: that thread IS
        the job's driver."""
        self.start()
        # NESTED submissions bypass admission: a driver thread that
        # already holds a slot (iterating one job's generator while
        # submitting another — e.g. a sortByKey bounds sample, or user
        # code collecting inside an iterate loop) must not block on
        # the cap it is itself holding — at saturation that is a
        # permanent deadlock, every slot waiting on itself
        depth = getattr(self._tls, "adm_depth", 0)
        if depth == 0:
            with self._adm_cv:
                if self._draining:
                    # nested submissions (depth > 0) still pass: an
                    # admitted job must be able to FINISH its own
                    # sortByKey samples etc. while the server drains
                    raise RuntimeError(
                        "service draining: admission stopped")
                if self.queue_max \
                        and self._waiting_jobs >= self.queue_max:
                    raise RuntimeError(
                        "service admission queue full (%d jobs "
                        "waiting, DPARK_SERVICE_QUEUE_MAX=%d)"
                        % (self._waiting_jobs, self.queue_max))
                self._waiting_jobs += 1
                try:
                    while self._active_jobs >= self.max_jobs:
                        self._adm_cv.wait()
                finally:
                    self._waiting_jobs -= 1
                self._active_jobs += 1
        self._tls.adm_depth = depth + 1
        try:
            sched = self.scheduler
            # run_job reads these thread-locals when minting the record
            sched._tls.client = client or getattr(
                self._tls, "client", None)
            # tenant SLO declaration (ISSUE 14): an explicit slo_ms
            # wins; otherwise the process default (DPARK_SERVICE_SLO)
            # applies to every tenant that declared nothing
            self.declare_slo(sched._tls.client,
                             slo_ms or conf.SERVICE_SLO_MS)
            self._tls.weight = weight or getattr(
                self._tls, "weight", None) or conf.SERVICE_WEIGHT
            yield from sched.run_job(rdd, func, partitions,
                                     allow_local)
        finally:
            self._tls.adm_depth = depth
            if depth == 0:
                with self._adm_cv:
                    self._active_jobs -= 1
                    self._adm_cv.notify()

    # -- graceful degradation (ISSUE 20) ---------------------------------
    def drain(self, timeout=30.0):
        """Stop admitting jobs, wait (bounded) for in-flight jobs to
        finish their wave-boundary work, then flush the crash journal
        so a subsequent exit loses nothing.  Idempotent; returns a
        summary the caller (or the remote `drain` endpoint) can log.
        Never raises — drain is the LAST thing a dying server does."""
        deadline = time.time() + max(0.0, float(timeout or 0.0))
        with self._adm_cv:
            already = self._draining
            self._draining = True
            while self._active_jobs > 0 and time.time() < deadline:
                self._adm_cv.wait(timeout=min(
                    1.0, max(0.01, deadline - time.time())))
            active = self._active_jobs
            waiting = self._waiting_jobs
        flushed = False
        try:
            from dpark_tpu import journal
            journal.flush()
            flushed = journal.active()
        except Exception as e:
            logger.warning("journal flush on drain failed: %s", e)
        summary = {"draining": True, "was_draining": already,
                   "drained": active == 0, "active_jobs": active,
                   "waiting_jobs": waiting,
                   "journal_flushed": flushed}
        logger.info("service drain: %s", summary)
        return summary

    def undrain(self):
        """Re-open admission after a drain (tests / operator rollback
        of a cancelled shutdown)."""
        with self._adm_cv:
            self._draining = False
            self._adm_cv.notify_all()

    # -- dispatcher ------------------------------------------------------
    def enqueue(self, sched, record, stage, tasks, report):
        """scheduler._dispatch hands every submit_tasks call here.
        Auto-registers the job (nested jobs — e.g. a sortByKey bounds
        sample submitted from inside an admitted job's driver — bypass
        admission: blocking them would deadlock their parent)."""
        jid = record["id"]
        with self._cv:
            state = self._jobs.get(jid)
            if state is None:
                state = self._jobs[jid] = _JobState(
                    getattr(self._tls, "weight", None)
                    or conf.SERVICE_WEIGHT, record)
                self._rr.append(jid)
            state.queue.append(_Work(sched, record, stage, tasks,
                                     report))
            self._cv.notify()

    def _next_work(self):
        """Weighted round-robin across jobs with queued work; blocks
        when idle.  Jobs burn one credit per turn; when every job with
        work is out of credits, a new cycle replenishes them."""
        with self._cv:
            while True:
                if self._stopped:
                    return _STOP
                # prune finished, drained jobs
                for jid in [j for j, s in self._jobs.items()
                            if not s.queue
                            and s.record.get("state") != "running"]:
                    del self._jobs[jid]
                    self._rr.remove(jid)
                busy = [j for j in self._rr if self._jobs[j].queue]
                if not busy:
                    self._cv.wait()
                    continue
                if all(self._jobs[j].credits <= 0 for j in busy):
                    for j in busy:
                        self._jobs[j].credits = self._jobs[j].weight
                n = len(self._rr)
                for off in range(n):
                    jid = self._rr[(self._rr_pos + off) % n]
                    state = self._jobs[jid]
                    if state.queue and state.credits > 0:
                        state.credits -= 1
                        self._rr_pos = (self._rr_pos + off + 1) % n
                        return state.queue.popleft()
                # busy jobs exist but none had credits: loop replenishes

    def _slot_loop(self):
        while True:
            item = self._next_work()
            if item is _STOP:
                return
            self._execute(item)

    def _execute(self, item):
        from dpark_tpu import adapt, trace
        sched, record = item.sched, item.record
        if "_t_submit" in record and "queue_wait_ms" not in record:
            # first stage execution of the job: everything before this
            # was queue wait (the per-job column in the web UI and the
            # bench `service` section)
            record["queue_wait_ms"] = round(
                (time.time() - record["_t_submit"]) * 1e3, 1)
        # attribute note_stage / store ownership / adapt decisions
        # taken on THIS thread to the right job
        sched._current_record = record
        adapt.set_current_job(record["id"])
        reported = set()

        def report(task, status, payload, _orig=item.report):
            reported.add(id(task))
            _orig(task, status, payload)

        try:
            with trace.ctx(job=record["id"], stage=item.stage.id):
                sched.submit_tasks(item.stage, item.tasks, report)
        except BaseException:
            # a crash here must surface to the JOB's event loop (its
            # driver owns retries/abort), never kill the slot thread.
            # Only tasks not already reported get the failure — a
            # double event would corrupt the driver's in-flight count.
            err = traceback.format_exc()
            logger.warning("stage execution failed in service slot:\n%s",
                           err)
            for task in item.tasks:
                if id(task) not in reported:
                    item.report(task, "failed", err)
        finally:
            adapt.set_current_job(None)
            sched._current_record = None

    # -- observability ---------------------------------------------------
    def note_bulk(self, client, nbytes):
        """Per-tenant bulk-stream accounting (ISSUE 12): bytes of job
        results streamed to each remote tenant over the bulk
        channel."""
        with self._cv:
            bulk = getattr(self, "_bulk_bytes", None)
            if bulk is None:
                bulk = self._bulk_bytes = {}
            bulk[client] = bulk.get(client, 0) + nbytes

    def service_stats(self):
        with self._cv:
            queued_items = sum(len(s.queue)
                               for s in self._jobs.values())
            bulk = dict(getattr(self, "_bulk_bytes", None) or {})
        with self._adm_cv:
            waiting = self._waiting_jobs
            active = self._active_jobs
        out = {"master": self.master, "slots": self.slots,
               "jobs_running": active, "jobs_queued": waiting,
               "work_items_queued": queued_items,
               "max_jobs": self.max_jobs, "bulk": bulk,
               "draining": self._draining,
               "tenants": self.tenant_slo_stats()}
        ex = getattr(self.scheduler, "executor", None)
        if ex is not None:
            out["program_cache"] = ex.program_cache_stats()
        if self._aot_warm is not None:
            out["aot_warm"] = dict(self._aot_warm)
        if self._rc_boot is not None:
            out["result_cache_boot"] = dict(self._rc_boot)
        rc = resultcache.stats()
        if rc is not None:
            out["result_cache"] = rc
        return out


# ---------------------------------------------------------------------------
# process-global server + the DparkContext seam
# ---------------------------------------------------------------------------

_SERVER = None
_SERVER_LOCK = locks.named_lock("service.global")
_client_ids = itertools.count(1)


def get_server(master=None):
    """The process-global JobServer (created on first use).  A master
    spec is honored only at creation; later callers share the
    existing mesh owner regardless of what they asked for."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is None:
            _SERVER = JobServer(master)
        elif master and _SERVER.master != master:
            logger.warning(
                "service already running with master=%s; ignoring "
                "requested %s", _SERVER.master, master)
        return _SERVER


def shutdown():
    """Stop and forget the process-global server (tests)."""
    global _SERVER
    with _SERVER_LOCK:
        srv, _SERVER = _SERVER, None
    if srv is not None:
        srv.stop()


class ClientScheduler:
    """What a DparkContext sees when attached to the service: the
    scheduler interface, with every job routed through the shared
    JobServer.  Unknown attributes (history, metrics_snapshot,
    executor, ...) delegate to the inner scheduler so the web UI and
    bench plumbing work unchanged."""

    is_service_client = True     # DparkContext.stop: leave env alive

    def __init__(self, server, client=None, weight=None, slo_ms=None,
                 share_results=None):
        self.server = server
        self.client = client or "client-%d" % next(_client_ids)
        self.weight = weight or conf.SERVICE_WEIGHT
        # per-tenant SLO (ISSUE 14): explicit target, else the
        # process default (DPARK_SERVICE_SLO); 0 = untracked
        self.slo_ms = slo_ms if slo_ms is not None \
            else conf.SERVICE_SLO_MS
        # cross-tenant result sharing (ISSUE 18): tenants share the
        # result cache by default; share_results=False opts this
        # tenant out of BOTH directions (no reads, no stores)
        if share_results is not None:
            resultcache.opt_out(self.client,
                                flag=not share_results)

    def start(self):
        self.server.start()

    def stop(self):
        # the server (and its mesh) outlives any one context
        pass

    def run_job(self, rdd, func, partitions=None, allow_local=False):
        return self.server.submit(rdd, func, partitions, allow_local,
                                  client=self.client,
                                  weight=self.weight,
                                  slo_ms=self.slo_ms)

    def default_parallelism(self):
        self.start()
        return self.server.scheduler.default_parallelism()

    def service_stats(self):
        return self.server.service_stats()

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.server.scheduler, name)


def client_scheduler(master=None, client=None):
    """The DPARK_SERVICE seam target: a per-context facade over the
    process-global server."""
    return ClientScheduler(get_server(master), client=client)


# ---------------------------------------------------------------------------
# remote transport: job FUNCTIONS over the dcn framed channel
# ---------------------------------------------------------------------------

def _context_for(server, client, slo_ms=None):
    """A DparkContext whose scheduler is a service client — what a
    remote job function receives as its `ctx`."""
    from dpark_tpu.context import DparkContext
    from dpark_tpu.env import env
    env.start(is_master=True)
    ctx = DparkContext("local")
    ctx.scheduler = ClientScheduler(server, client=client,
                                    slo_ms=slo_ms)
    ctx.started = True           # scheduler is live; skip start()
    # a remote fn calling ctx.stop() must not tear down the SERVER's
    # env/scheduler — the context is a per-request facade
    ctx.stop = lambda: None
    return ctx


def serve(addr="127.0.0.1:0", master=None, server=None):
    """Listen for remote job submissions on the dcn framed-TCP
    channel; returns the FramedServer (bind_address tells the port).

    Request grammar (JSON array like every dcn request):
      ("job", client, b64(serialize.dumps(fn))[, slo_ms])
          -> pickled fn(ctx); the optional 4th element declares the
          tenant's per-job latency SLO in ms (ISSUE 14) — a pre-SLO
          server simply never receives it (clients omit it when unset)
      ("job_bulk", client, b64(...)[, slo_ms])  -> same result, streamed over
          the chunk-framed bulk channel (ISSUE 12) — concurrent
          tenants' result streams multiplex through the shared
          per-peer windows, and per-tenant stream bytes land in
          service_stats()["bulk"]
      ("stats",)                                 -> pickled stats dict
      ("drain"[, timeout_s])                     -> pickled drain summary:
          stop admission, wait (bounded) for in-flight jobs, flush the
          crash journal (ISSUE 20 graceful degradation)
    """
    import os
    from dpark_tpu import dcn
    from dpark_tpu.utils import compress
    srv = server or get_server(master)
    srv.start()
    if not os.environ.get("DPARK_DCN_SECRET"):
        logger.warning(
            "serving WITHOUT DPARK_DCN_SECRET: any peer that can "
            "reach this port can submit arbitrary code")

    def run_job(client, payload, slo_ms=None):
        from dpark_tpu import serialize
        fn = serialize.loads(base64.b64decode(payload))
        ctx = _context_for(srv, "remote:%s" % client, slo_ms=slo_ms)
        return compress(pickle.dumps(fn(ctx), -1))

    def handle(req):
        kind = req[0]
        if kind == "job":
            client, payload = req[1], req[2]
            return run_job(client, payload,
                           req[3] if len(req) > 3 else None)
        if kind == "job_bulk":
            client, payload = req[1], req[2]
            blob = run_job(client, payload,
                           req[3] if len(req) > 3 else None)

            def note_sent(peer, nbytes, nchunks, _client=client):
                srv.note_bulk(_client, nbytes)
                # result streams count in the bulk plane's per-peer
                # sent counters too — /metrics must see ALL bulk
                # traffic, not just shuffle/broadcast payloads
                from dpark_tpu import bulkplane
                bulkplane._count_sent(peer, nbytes, nchunks)

            return dcn.BulkPayload({"kind": "blob"},
                                   dcn.chunked(blob),
                                   on_sent=note_sent)
        if kind == "stats":
            return compress(pickle.dumps(srv.service_stats(), -1))
        if kind == "drain":
            timeout = float(req[1]) if len(req) > 1 else 30.0
            return compress(pickle.dumps(srv.drain(timeout), -1))
        raise ValueError("unknown service request %r" % (kind,))

    host, _, port = str(addr).partition(":")
    framed = dcn.FramedServer(handle, host or "127.0.0.1",
                              int(port or 0), name="dpark-service")
    framed.start()
    logger.info("service listening on tcp://%s:%d"
                % framed.bind_address)
    return framed


class ServiceClient:
    """Caller side of the remote transport: ships a job FUNCTION to a
    served JobServer and returns its result.  The function runs as a
    driver thread inside the server — `fn(ctx)` builds its DAG there,
    in the server's id namespace."""

    def __init__(self, addr, client=None, timeout=600, slo_ms=None):
        addr = str(addr)
        if not addr.startswith("tcp://"):
            addr = "tcp://" + addr
        self.uri = addr
        self.client = client or "client-%d" % next(_client_ids)
        self.timeout = timeout
        # per-tenant SLO declaration (ISSUE 14): rides each job
        # request as an optional 4th element, so a pre-SLO server
        # never sees an unknown shape when the knob is unset
        self.slo_ms = slo_ms

    def run(self, fn):
        from dpark_tpu import conf, dcn, serialize
        from dpark_tpu.utils import decompress
        payload = base64.b64encode(serialize.dumps(fn)).decode("ascii")
        extra = (float(self.slo_ms),) if self.slo_ms else ()
        if conf.BULK_PLANE:
            # results stream back chunk-framed over the bulk channel
            # (ISSUE 12); a pre-bulk server answers "unknown service
            # request" and the plain single-frame path runs instead
            from dpark_tpu import bulkplane
            try:
                _, view = bulkplane.fetch(
                    self.uri,
                    ("job_bulk", self.client, payload) + extra,
                    timeout=self.timeout)
                return pickle.loads(decompress(bytes(view)))
            except bulkplane.BulkUnsupported:
                pass
        resp = dcn.fetch(self.uri,
                         ("job", self.client, payload) + extra,
                         timeout=self.timeout)
        return pickle.loads(decompress(resp))

    def stats(self):
        from dpark_tpu import dcn
        from dpark_tpu.utils import decompress
        resp = dcn.fetch(self.uri, ("stats",), timeout=self.timeout)
        return pickle.loads(decompress(resp))

    def drain(self, timeout_s=30.0):
        """Ask the server to stop admission, finish in-flight jobs and
        flush its crash journal; returns the server's drain summary."""
        from dpark_tpu import dcn
        from dpark_tpu.utils import decompress
        resp = dcn.fetch(self.uri, ("drain", float(timeout_s)),
                         timeout=self.timeout)
        return pickle.loads(decompress(resp))


def main(argv=None):
    """``python -m dpark_tpu.service --listen 127.0.0.1:7077 -m tpu``
    — a standalone resident mesh owner for remote clients."""
    import argparse
    p = argparse.ArgumentParser(
        prog="dpark_tpu.service",
        description="resident executor service (mesh owner)")
    p.add_argument("--listen", default="127.0.0.1:0",
                   metavar="HOST:PORT")
    p.add_argument("-m", "--master", default=None,
                   help="backing master spec (local, tpu[:N], ...)")
    args = p.parse_args(argv)
    framed = serve(args.listen, master=args.master)
    print("dpark_tpu service on tcp://%s:%d" % framed.bind_address,
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        framed.stop()
        shutdown()


if __name__ == "__main__":
    main()
