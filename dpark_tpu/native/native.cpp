// Host-side native kernels for dpark_tpu (reference parity: the reference's
// native bits were portable_hash.pyx [Cython], crc32c C speedups and lz4
// codecs — SURVEY.md section 2.6).  TPU-native equivalents: bulk portable
// hashing for partition planning, crc32c for storage integrity, newline
// splitting and dictionary token encoding to feed device_put with columnar
// data.  Compiled with plain g++ into libdpark_native.so, bound via ctypes
// (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <unordered_map>
#include <string>
#include <vector>

extern "C" {

// --------------------------------------------------------------------------
// portable hash: murmur3 fmix32 over (lo ^ hi) words, bit-identical to
// dpark_tpu/utils/phash.py portable_hash()/_hash_int and phash_device().
// --------------------------------------------------------------------------
static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

uint32_t phash_i64(int64_t x) {
    uint64_t u = (uint64_t)x;
    uint32_t lo = (uint32_t)(u & 0xFFFFFFFFu);
    uint32_t hi = (uint32_t)((u >> 32) & 0xFFFFFFFFu);
    return fmix32(lo ^ hi);
}

void phash_i64_array(const int64_t* xs, uint32_t* out, int64_t n) {
    for (int64_t i = 0; i < n; i++) out[i] = phash_i64(xs[i]);
}

// Composite (tuple) key hash over `ncols` int64 columns laid out
// contiguously (cols[c*n + i] = column c, row i): portable_hash's own
// tuple recipe — h = 0x345678; per item h = (h ^ hash(item)) *
// 0x9E3779B1; fmix32(h ^ ncols) — applied per row.  Bit-identical to
// phash.py portable_hash((k1, ..., kn)) / phash_np_cols /
// phash_device_cols, so multi-column shuffle routing agrees across
// every implementation.
void phash_i64_cols(const int64_t* cols, int64_t ncols, int64_t n,
                    uint32_t* out) {
    if (ncols == 1) { phash_i64_array(cols, out, n); return; }
    for (int64_t i = 0; i < n; i++) {
        uint32_t h = 0x345678u;
        for (int64_t c = 0; c < ncols; c++) {
            h = (h ^ phash_i64(cols[c * n + i])) * 0x9E3779B1u;
        }
        out[i] = fmix32(h ^ (uint32_t)ncols);
    }
}

// FNV-1a over bytes + fmix32 finalizer — matches phash.py _hash_bytes.
uint32_t phash_bytes(const uint8_t* data, int64_t n) {
    uint32_t h = 0x811C9DC5u;
    for (int64_t i = 0; i < n; i++) {
        h = (h ^ data[i]) * 0x01000193u;
    }
    return fmix32(h);
}

// --------------------------------------------------------------------------
// crc32c (Castagnoli), table-driven — storage integrity (beansdb records,
// tabular chunks).  Standard polynomial 0x82F63B78.
// --------------------------------------------------------------------------
static uint32_t crc32c_table[256];
static bool crc32c_ready = false;

static void crc32c_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
        crc32c_table[i] = c;
    }
    crc32c_ready = true;
}

uint32_t crc32c(const uint8_t* data, int64_t n, uint32_t crc) {
    if (!crc32c_ready) crc32c_init();
    crc = ~crc;
    for (int64_t i = 0; i < n; i++)
        crc = crc32c_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

// --------------------------------------------------------------------------
// newline splitter: fill start/length arrays for each line in buf.
// Returns the number of lines found (at most max_lines); a trailing
// fragment without '\n' counts as a line.
// --------------------------------------------------------------------------
int64_t split_lines(const uint8_t* buf, int64_t n,
                    int64_t* starts, int64_t* lens, int64_t max_lines) {
    int64_t count = 0;
    int64_t start = 0;
    for (int64_t i = 0; i < n && count < max_lines; i++) {
        if (buf[i] == '\n') {
            int64_t len = i - start;
            if (len > 0 && buf[start + len - 1] == '\r') len--;
            starts[count] = start;
            lens[count] = len;
            count++;
            start = i + 1;
        }
    }
    if (start < n && count < max_lines) {
        starts[count] = start;
        lens[count] = n - start;
        count++;
    }
    return count;
}

// --------------------------------------------------------------------------
// TokenDict: exact string -> dense int64 id dictionary encoder.  Feeds the
// device wordcount path: host tokenizes+encodes, device reduces int64 ids,
// host decodes ids back to strings.  (The reference counts Python strings
// in dicts; this is the columnar equivalent.)
// --------------------------------------------------------------------------
struct TokenDict {
    std::unordered_map<std::string, int64_t> map;
    std::vector<std::string> rev;
};

void* tokendict_new() { return new TokenDict(); }

void tokendict_free(void* h) { delete (TokenDict*)h; }

int64_t tokendict_size(void* h) {
    return (int64_t)((TokenDict*)h)->rev.size();
}

// Tokenize buf on ASCII whitespace, encode each token to its id (assigning
// new ids in first-seen order), write ids into out (capacity max_tokens).
// Returns the number of tokens written.
int64_t tokendict_encode(void* h, const uint8_t* buf, int64_t n,
                         int64_t* out, int64_t max_tokens) {
    TokenDict* d = (TokenDict*)h;
    int64_t count = 0;
    int64_t i = 0;
    while (i < n && count < max_tokens) {
        while (i < n && (buf[i] == ' ' || buf[i] == '\t' ||
                         buf[i] == '\n' || buf[i] == '\r')) i++;
        if (i >= n) break;
        int64_t start = i;
        while (i < n && !(buf[i] == ' ' || buf[i] == '\t' ||
                          buf[i] == '\n' || buf[i] == '\r')) i++;
        std::string tok((const char*)buf + start, (size_t)(i - start));
        auto it = d->map.find(tok);
        int64_t id;
        if (it == d->map.end()) {
            id = (int64_t)d->rev.size();
            d->map.emplace(std::move(tok), id);
            d->rev.push_back(std::string((const char*)buf + start,
                                         (size_t)(i - start)));
        } else {
            id = it->second;
        }
        out[count++] = id;
    }
    return count;
}

// Single-byte-separator tokenizer: split buf into \n-lines (stripping
// trailing \r runs, like TextFileRDD's rstrip(b"\r\n")), then each
// line on `sep`, encoding EVERY field INCLUDING empty ones — exact
// str.split(sep) semantics, which unlike whitespace split preserves
// empty fields between consecutive separators and yields [""] for an
// empty line.  Backs canonical chains like
// flatMap(lambda l: l.split("\t")).
int64_t tokendict_encode_sep(void* h, const uint8_t* buf, int64_t n,
                             uint8_t sep, int64_t* out,
                             int64_t max_tokens) {
    TokenDict* d = (TokenDict*)h;
    int64_t count = 0;
    int64_t i = 0;
    while (i < n && count < max_tokens) {
        int64_t line_end = i;
        while (line_end < n && buf[line_end] != '\n') line_end++;
        int64_t e = line_end;
        while (e > i && buf[e - 1] == '\r') e--;
        int64_t start = i;
        for (int64_t j = i; j <= e && count < max_tokens; j++) {
            if (j == e || buf[j] == sep) {
                std::string tok((const char*)buf + start,
                                (size_t)(j - start));
                auto it = d->map.find(tok);
                int64_t id;
                if (it == d->map.end()) {
                    id = (int64_t)d->rev.size();
                    d->rev.push_back(tok);
                    d->map.emplace(std::move(tok), id);
                } else {
                    id = it->second;
                }
                out[count++] = id;
                start = j + 1;
            }
        }
        i = line_end + 1;
    }
    return count;
}

// Encode ONE exact string (no tokenization — the key may contain
// whitespace) to its dense id, assigning a new id on first sight.
int64_t tokendict_put(void* h, const uint8_t* buf, int64_t n) {
    TokenDict* d = (TokenDict*)h;
    std::string tok((const char*)buf, (size_t)n);
    auto it = d->map.find(tok);
    if (it != d->map.end()) return it->second;
    int64_t id = (int64_t)d->rev.size();
    d->rev.push_back(tok);
    d->map.emplace(std::move(tok), id);
    return id;
}

// CSV record-boundary scanner: exact RFC4180-style state machine.  A
// quote only OPENS a quoted field at field start (after delimiter or
// newline); inside a quoted field a doubled quote is a literal; a bare
// quote inside an unquoted field is a literal and never flips state —
// which is where the simpler quote-parity heuristic corrupts records.
// Emits record-start offsets >= target stepping by `step` into out.
// state bits: 1 = in_quoted, 2 = field_start, 4 = pending close quote.
int64_t csv_scan(const uint8_t* buf, int64_t n, uint8_t quote,
                 uint8_t delim, int64_t state_in, int64_t* state_out,
                 int64_t base, int64_t target, int64_t step,
                 int64_t* target_out, int64_t* out, int64_t max_out) {
    bool in_quoted = state_in & 1;
    bool field_start = state_in & 2;
    bool pending = state_in & 4;
    int64_t cnt = 0;
    for (int64_t i = 0; i < n; i++) {
        uint8_t c = buf[i];
        if (pending) {
            pending = false;
            if (c == quote) continue;        // doubled quote: literal
            in_quoted = false;               // previous quote closed
        }
        if (in_quoted) {
            if (c == quote) pending = true;  // close or doubled?
            continue;
        }
        if (c == '\n') {
            int64_t off = base + i + 1;
            if (off >= target && cnt < max_out) {
                out[cnt++] = off;
                target = off + step;
            }
            field_start = true;
        } else if (c == delim) {
            field_start = true;
        } else if (c == quote && field_start) {
            in_quoted = true;
            field_start = false;
        } else {
            field_start = false;
        }
    }
    *state_out = (in_quoted ? 1 : 0) | (field_start ? 2 : 0)
               | (pending ? 4 : 0);
    *target_out = target;
    return cnt;
}

// Merge src's vocabulary into dst IN src-id ORDER, writing
// remap[i] = dst id of src token i.  Backbone of the parallel text
// ingest: worker threads tokenize into private dicts with the GIL
// released, the driver merges them in split order so global ids come
// out identical to a serial walk.  Returns src's size.
int64_t tokendict_merge(void* dst_h, void* src_h, int64_t* remap) {
    TokenDict* dst = (TokenDict*)dst_h;
    TokenDict* src = (TokenDict*)src_h;
    int64_t m = (int64_t)src->rev.size();
    for (int64_t i = 0; i < m; i++) {
        const std::string& tok = src->rev[(size_t)i];
        auto it = dst->map.find(tok);
        int64_t id;
        if (it != dst->map.end()) {
            id = it->second;
        } else {
            id = (int64_t)dst->rev.size();
            dst->rev.push_back(tok);
            dst->map.emplace(tok, id);
        }
        remap[i] = id;
    }
    return m;
}

// Copy token `id` into out (capacity cap); returns its length or -1.
int64_t tokendict_get(void* h, int64_t id, uint8_t* out, int64_t cap) {
    TokenDict* d = (TokenDict*)h;
    if (id < 0 || id >= (int64_t)d->rev.size()) return -1;
    const std::string& s = d->rev[(size_t)id];
    int64_t n = (int64_t)s.size();
    if (n > cap) return -1;
    std::memcpy(out, s.data(), (size_t)n);
    return n;
}

}  // extern "C"
