"""ctypes bindings for the C++ host kernels (dpark_tpu/native/native.cpp).

Reference parity: replaces dpark's Cython portable_hash + C crc32c + native
codec dependencies (SURVEY.md section 2.6).  The shared library is built
lazily with g++ on first import and cached next to the source; every
binding degrades to a pure-Python fallback when no compiler is available.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

from dpark_tpu.utils.log import get_logger

logger = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native.cpp")
_SO = os.path.join(_HERE, "libdpark_native.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    import tempfile
    fd, tmp = tempfile.mkstemp(prefix=".build-", suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
               "-o", tmp, _SRC]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _SO)        # atomic rename: concurrent builds safe
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def get_lib():
    """The loaded shared library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError) as e:
            logger.info("native library unavailable (%s); pure-Python "
                        "fallbacks in use", e)
            return None
        lib.phash_i64.restype = ctypes.c_uint32
        lib.phash_i64.argtypes = [ctypes.c_int64]
        lib.phash_i64_array.restype = None
        lib.phash_i64_array.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        lib.phash_i64_cols.restype = None
        lib.phash_i64_cols.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p]
        lib.phash_bytes.restype = ctypes.c_uint32
        lib.phash_bytes.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.crc32c.restype = ctypes.c_uint32
        lib.crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                               ctypes.c_uint32]
        lib.split_lines.restype = ctypes.c_int64
        lib.split_lines.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_int64]
        lib.tokendict_new.restype = ctypes.c_void_p
        lib.tokendict_free.argtypes = [ctypes.c_void_p]
        lib.tokendict_size.restype = ctypes.c_int64
        lib.tokendict_size.argtypes = [ctypes.c_void_p]
        lib.tokendict_encode.restype = ctypes.c_int64
        lib.tokendict_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64]
        lib.tokendict_encode_sep.restype = ctypes.c_int64
        lib.tokendict_encode_sep.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_uint8, ctypes.c_void_p, ctypes.c_int64]
        lib.tokendict_get.restype = ctypes.c_int64
        lib.tokendict_get.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64]
        lib.tokendict_put.restype = ctypes.c_int64
        lib.tokendict_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        lib.tokendict_merge.restype = ctypes.c_int64
        lib.tokendict_merge.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.csv_scan.restype = ctypes.c_int64
        lib.csv_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint8,
            ctypes.c_uint8, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
            ctypes.c_int64]
        _lib = lib
        return _lib


def phash_i64_bulk(keys):
    """uint32 portable hash of an int64 numpy array (C++ when available)."""
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    lib = get_lib()
    out = np.empty(keys.shape, dtype=np.uint32)
    if lib is not None:
        lib.phash_i64_array(keys.ctypes.data, out.ctypes.data, keys.size)
        return out
    from dpark_tpu.utils.phash import portable_hash
    for i, k in enumerate(keys.ravel()):
        out.ravel()[i] = portable_hash(int(k))
    return out


def phash_i64_cols_bulk(cols):
    """Composite (tuple-key) uint32 portable hash over N int64 column
    arrays — C++ when available, phash_np_cols otherwise.  Row i hashes
    as portable_hash((cols[0][i], ..., cols[-1][i]))."""
    cols = [np.ascontiguousarray(c, dtype=np.int64) for c in cols]
    lib = get_lib()
    if lib is not None and len(cols) >= 1:
        n = cols[0].size
        flat = np.concatenate([c.ravel() for c in cols]) \
            if len(cols) > 1 else cols[0].ravel()
        flat = np.ascontiguousarray(flat, dtype=np.int64)
        out = np.empty(n, dtype=np.uint32)
        lib.phash_i64_cols(flat.ctypes.data, len(cols), n,
                           out.ctypes.data)
        return out.reshape(cols[0].shape)
    from dpark_tpu.utils.phash import phash_np_cols
    return phash_np_cols(cols)


def crc32c(data, crc=0):
    lib = get_lib()
    if lib is not None:
        return lib.crc32c(bytes(data), len(data), crc)
    # pure-Python table fallback
    global _py_table
    if "_py_table" not in globals():
        t = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else (c >> 1)
            t.append(c)
        globals()["_py_table"] = t
    c = crc ^ 0xFFFFFFFF
    for b in bytes(data):
        c = _py_table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def split_lines(buf):
    """(starts, lens) int64 arrays for the lines of `buf` (bytes)."""
    lib = get_lib()
    n = len(buf)
    if lib is not None:
        max_lines = buf.count(b"\n") + 1
        starts = np.empty(max_lines, dtype=np.int64)
        lens = np.empty(max_lines, dtype=np.int64)
        cnt = lib.split_lines(buf, n, starts.ctypes.data,
                              lens.ctypes.data, max_lines)
        return starts[:cnt], lens[:cnt]
    starts, lens = [], []
    off = 0
    for line in buf.split(b"\n"):
        body = line[:-1] if line.endswith(b"\r") else line
        if off < n or body:
            starts.append(off)
            lens.append(len(body))
        off += len(line) + 1
    if buf.endswith(b"\n") and starts and lens[-1] == 0 \
            and starts[-1] >= n:
        starts.pop()
        lens.pop()
    return (np.array(starts, dtype=np.int64),
            np.array(lens, dtype=np.int64))


class TokenDict:
    """Exact string->dense-id dictionary encoder (C++ hashmap inside)."""

    def __init__(self):
        self._lib = get_lib()
        if self._lib is not None:
            self._h = self._lib.tokendict_new()
        else:
            self._h = None
            self._map = {}
            self._rev = []

    def __del__(self):
        if getattr(self, "_lib", None) is not None \
                and getattr(self, "_h", None):
            self._lib.tokendict_free(self._h)
            self._h = None

    def __len__(self):
        if self._h:
            return self._lib.tokendict_size(self._h)
        return len(self._rev)

    def encode(self, buf, sep=None):
        """Tokenize bytes -> int64 id array.

        sep=None: whitespace runs (str.split() over ASCII bytes).
        sep=<1-byte str/bytes>: per \\n-line (trailing \\r stripped,
        TextFileRDD's rule), split on EVERY separator occurrence —
        exact str.split(sep) semantics incl. empty fields."""
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        if sep is not None and isinstance(sep, str):
            sep = sep.encode("utf-8")
        if self._h:
            if sep is None:
                max_tokens = max(1, len(buf) // 2 + 1)
                out = np.empty(max_tokens, dtype=np.int64)
                cnt = self._lib.tokendict_encode(
                    self._h, buf, len(buf), out.ctypes.data,
                    max_tokens)
                return out[:cnt]
            # fields per line = seps + 1; lines <= \n count + 1
            max_tokens = buf.count(b"\n") + buf.count(sep) + 2
            out = np.empty(max_tokens, dtype=np.int64)
            cnt = self._lib.tokendict_encode_sep(
                self._h, buf, len(buf), sep[0], out.ctypes.data,
                max_tokens)
            return out[:cnt]
        ids = []
        if sep is None:
            toks = buf.split()
        else:
            toks = []
            lines = buf.split(b"\n")
            if lines and lines[-1] == b"":
                lines.pop()
            for ln in lines:
                toks.extend(ln.rstrip(b"\r").split(sep))
        for tok in toks:
            tid = self._map.get(tok)
            if tid is None:
                tid = len(self._rev)
                self._map[tok] = tid
                self._rev.append(tok)
            ids.append(tid)
        return np.array(ids, dtype=np.int64)

    def put(self, s):
        """Encode ONE exact string (it may contain whitespace) -> id."""
        if isinstance(s, str):
            s = s.encode("utf-8")
        if self._h:
            return self._lib.tokendict_put(self._h, s, len(s))
        tid = self._map.get(s)
        if tid is None:
            tid = len(self._rev)
            self._map[s] = tid
            self._rev.append(s)
        return tid

    def decode(self, tid):
        return self.raw(tid).decode("utf-8", "replace")

    def raw(self, tid):
        """The EXACT bytes of token `tid` (decode() lossily re-encodes
        invalid utf-8)."""
        if self._h:
            buf = ctypes.create_string_buffer(1 << 16)
            n = self._lib.tokendict_get(self._h, int(tid), buf, len(buf))
            if n < 0:
                raise KeyError(tid)
            return buf.raw[:n]
        return self._rev[tid]

    def merge_from(self, other):
        """Merge `other`'s vocabulary into this dict in other-id order;
        returns remap (np.int64, len(other)) with remap[i] = this
        dict's id for other's token i.  C++ loop when both dicts are
        native — the parallel-ingest merge must not walk tokens in
        Python."""
        m = len(other)
        remap = np.empty(m, dtype=np.int64)
        if self._h and other._h:
            self._lib.tokendict_merge(self._h, other._h,
                                      remap.ctypes.data)
            return remap
        for i in range(m):
            remap[i] = self.put(other.raw(i))
        return remap


class CsvScanner:
    """Incremental CSV record-boundary scanner (exact RFC4180-style
    state machine, C++ with a pure-Python fallback): feed byte chunks,
    collect record-start offsets >= a moving target stepped by `step`.
    A bare quote inside an unquoted field never flips state — the case
    where a quote-parity heuristic would corrupt records."""

    def __init__(self, step, quote=b'"', delim=b","):
        self.step = step
        self.quote = quote[0]
        self.delim = delim[0]
        self.state = 2                   # field_start at file start
        self.target = step
        self.pos = 0
        self.bounds = []
        self._lib = get_lib()

    def feed(self, chunk):
        if not chunk:
            return                       # state must survive empty reads
        if self._lib is not None:
            # exact upper bound: one boundary per newline, never capped
            max_out = chunk.count(b"\n") + 2
            out = np.empty(max_out, dtype=np.int64)
            st = ctypes.c_int64()
            tg = ctypes.c_int64()
            cnt = self._lib.csv_scan(
                chunk, len(chunk), self.quote, self.delim, self.state,
                ctypes.byref(st), self.pos, self.target, self.step,
                ctypes.byref(tg), out.ctypes.data, max_out)
            self.state = st.value
            self.target = tg.value
            self.bounds.extend(out[:cnt].tolist())
        else:
            in_q = bool(self.state & 1)
            fstart = bool(self.state & 2)
            pending = bool(self.state & 4)
            q, d = self.quote, self.delim
            if not in_q and not pending \
                    and bytes([q]) not in chunk:
                # vectorized fast path: no quotes in this chunk means
                # every newline ends a record
                npos = np.flatnonzero(
                    np.frombuffer(chunk, np.uint8) == 0x0A)
                for off in (npos + self.pos + 1).tolist():
                    if off >= self.target:
                        self.bounds.append(off)
                        self.target = off + self.step
                last = chunk[-1:]
                self.state = 2 if last in (b"\n", bytes([d])) else 0
                self.pos += len(chunk)
                return
            for i, c in enumerate(chunk):
                if pending:
                    pending = False
                    if c == q:
                        continue
                    in_q = False
                if in_q:
                    if c == q:
                        pending = True
                    continue
                if c == 0x0A:
                    off = self.pos + i + 1
                    if off >= self.target:
                        self.bounds.append(off)
                        self.target = off + self.step
                    fstart = True
                elif c == d:
                    fstart = True
                elif c == q and fstart:
                    in_q = True
                    fstart = False
                else:
                    fstart = False
            self.state = ((1 if in_q else 0) | (2 if fstart else 0)
                          | (4 if pending else 0))
        self.pos += len(chunk)
