"""EXACT algebraic classification of 2-arg merge callables — the ONE
shared implementation behind fuse.classify_merge (device monoid path)
and analysis.plan_rules (the monoid-multileaf lint rule), so the
linter and the executor can never drift on what counts as a classified
monoid (review finding: three divergent copies).

jax-free by design: the tpu backend registers its jnp callables via
register_direct() on import, so the linter classifies identically on
installs without jax (minus jnp identities that cannot occur there).

A classified monoid unlocks single-pass segment scatters instead of
the generic O(log n)-pass associative scan — but a wrong answer here
silently replaces the user's function, so only provable matches
qualify (round-1 advisor finding: the old 8-random-int-probe
classifier could mistake e.g. a saturating add for plain add):

* a known callable by identity (operator.add, min, np.maximum, ...);
* a closure-free 2-arg Python function whose bytecode equals one of
  the canonical forms ``a+b``, ``b+a``, ``a*b``, ``b*a``,
  ``min(a,b)``, ``max(a,b)`` — with any referenced global verified
  to still be the builtin;
* an explicit user hint: ``merge.__dpark_monoid__ = "add"`` (for
  functions that are equivalent to a monoid but written differently).

Everything else classifies as None and runs through the traced user
function (correct, just not single-pass).
"""

import operator

import numpy as np

from dpark_tpu.utils import builtin_globals_ok

KINDS = ("add", "min", "max", "mul")

_DIRECT = {operator.add: "add", operator.iadd: "add",
           operator.mul: "mul", operator.imul: "mul",
           min: "min", max: "max",
           np.add: "add", np.multiply: "mul",
           np.minimum: "min", np.maximum: "max"}

_TEMPLATES = None


def register_direct(mapping):
    """Backends register extra by-identity callables (e.g. jnp.add).
    Values must be KINDS names."""
    assert all(v in KINDS for v in mapping.values()), mapping
    _DIRECT.update(mapping)


def _templates():
    global _TEMPLATES
    if _TEMPLATES is None:
        tmpl = {
            "add": [lambda a, b: a + b, lambda a, b: b + a],
            "mul": [lambda a, b: a * b, lambda a, b: b * a],
            "min": [lambda a, b: min(a, b)],
            "max": [lambda a, b: max(a, b)],
        }
        _TEMPLATES = {}
        for name, fns in tmpl.items():
            for f in fns:
                c = f.__code__
                _TEMPLATES[(c.co_code, c.co_consts, c.co_names)] = name
    return _TEMPLATES


SEGAGG_KINDS = ("sum", "count", "min", "max", "mean")

_SEGAGG_DIRECT = {sum: "sum", len: "count", min: "min", max: "max",
                  np.sum: "sum", np.mean: "mean",
                  np.min: "min", np.max: "max"}

_SEGAGG_TEMPLATES = None


def _segagg_templates():
    global _SEGAGG_TEMPLATES
    if _SEGAGG_TEMPLATES is None:
        tmpl = {
            "sum": [lambda vs: sum(vs)],
            "count": [lambda vs: len(vs)],
            "min": [lambda vs: min(vs)],
            "max": [lambda vs: max(vs)],
            "mean": [lambda vs: sum(vs) / len(vs)],
        }
        _SEGAGG_TEMPLATES = {}
        for name, fns in tmpl.items():
            for f in fns:
                c = f.__code__
                _SEGAGG_TEMPLATES[(c.co_code, c.co_consts,
                                   c.co_names)] = name
    return _SEGAGG_TEMPLATES


def classify_segagg(f):
    """EXACT classification of a 1-arg function applied to a
    groupByKey value LIST as a per-group aggregate.  Same proof
    obligations as classify_merge — only provable matches qualify:

    * the builtins sum/len/min/max (or np.sum/np.mean/np.min/np.max)
      by identity;
    * a closure-free 1-arg function whose bytecode equals ``sum(vs)``,
      ``len(vs)``, ``min(vs)``, ``max(vs)`` or ``sum(vs)/len(vs)``,
      with referenced globals verified to still be the builtins;
    * an explicit hint: ``f.__dpark_segagg__ = "sum"``.

    Returns "sum" | "count" | "min" | "max" | "mean" | None."""
    hint = getattr(f, "__dpark_segagg__", None)
    if hint in SEGAGG_KINDS:
        return hint
    try:
        if f in _SEGAGG_DIRECT:
            return _SEGAGG_DIRECT[f]
    except TypeError:
        return None
    code = getattr(f, "__code__", None)
    if code is None or getattr(f, "__closure__", None):
        return None
    if code.co_argcount != 1 or code.co_flags & 0x0C:
        return None
    name = _segagg_templates().get((code.co_code, code.co_consts,
                                    code.co_names))
    if name is None or not builtin_globals_ok(f, code):
        return None
    return name


def classify_merge(merge):
    """"add" | "min" | "max" | "mul" | None — see module docstring for
    the proof obligations."""
    hint = getattr(merge, "__dpark_monoid__", None)
    if hint in KINDS:
        return hint
    try:
        if merge in _DIRECT:
            return _DIRECT[merge]
    except TypeError:
        return None                      # unhashable callable
    code = getattr(merge, "__code__", None)
    if code is None or getattr(merge, "__closure__", None):
        return None
    if code.co_argcount != 2 or code.co_flags & 0x0C:   # *args/**kwargs
        return None
    name = _templates().get((code.co_code, code.co_consts,
                             code.co_names))
    if name is None:
        return None
    if not builtin_globals_ok(merge, code):
        return None
    return name
