"""Streaming group-by with disk spill for oversized groups.

Reference parity: dpark/utils/nested_groupby.py (GroupByNestedIter) — when
one key's value list cannot fit in memory, values stream to a spill file
and the group iterates lazily from disk (SURVEY.md section 2.1; the
"external merge" family of 5.7).
"""

import os
import pickle
import tempfile


class NestedGroup:
    """Iterable over one group's values; transparently disk-backed."""

    def __init__(self, max_in_memory=100_000, spill_dir=None):
        self.values = []
        self.max_in_memory = max_in_memory
        self.spill_dir = spill_dir
        self.spill_file = None
        self.spilled = 0

    def append(self, v):
        self.values.append(v)
        if len(self.values) >= self.max_in_memory:
            self._spill()

    def _spill(self):
        if self.spill_file is None:
            d = self.spill_dir
            if d is None:
                from dpark_tpu.env import env
                d = os.path.join(env.workdir, "groupby")
            os.makedirs(d, exist_ok=True)
            fd, path = tempfile.mkstemp(dir=d, prefix="group-")
            self.spill_file = os.fdopen(fd, "w+b")
            os.unlink(path)              # anonymous: freed on close
        pickle.dump(self.values, self.spill_file, -1)
        self.spilled += len(self.values)
        self.values = []

    def __iter__(self):
        if self.spill_file is not None:
            self.spill_file.flush()
            self.spill_file.seek(0)
            remaining = self.spilled
            while remaining > 0:
                chunk = pickle.load(self.spill_file)
                remaining -= len(chunk)
                yield from chunk
            self.spill_file.seek(0, 2)
        yield from self.values

    def __len__(self):
        return self.spilled + len(self.values)

    def close(self):
        if self.spill_file is not None:
            self.spill_file.close()
            self.spill_file = None


def group_by_nested(iterator, key_fn, max_in_memory=100_000):
    """Group (already merge-compatible) records by key_fn with bounded
    memory per group; yields (key, NestedGroup)."""
    groups = {}
    for item in iterator:
        k = key_fn(item)
        g = groups.get(k)
        if g is None:
            g = groups[k] = NestedGroup(max_in_memory)
        g.append(item)
    yield from groups.items()
