"""Portable, cross-process, host/device-consistent hashing.

Reference parity: dpark/portable_hash.pyx (Cython) — a deterministic hash for
str/bytes/tuple/int/None used by HashPartitioner so partition assignment is
stable across interpreter processes (SURVEY.md section 2.6 item 1).

TPU-native twist: the same integer mix (murmur3 fmix32) is implemented three
ways and cross-checked by tests/test_phash.py:

  * pure Python  (`portable_hash`)      — host path, arbitrary objects
  * jax.numpy    (`phash_device`)       — device path, int32 key columns
  * C++          (dpark_tpu/native)     — bulk host path (ctypes), optional

For an int32 key k the partition is  fmix32(u32(k) ^ u32(k >> 31)) % n  on
every path, so a shuffle planned on host lands where device code expects.

COMPOSITE (tuple) keys reuse portable_hash's own tuple recipe —
  h = 0x345678; for item: h = (h ^ hash(item)) * 0x9E3779B1; fmix32(h ^ n)
— as a columnar combine over the per-column int hashes (`phash_np_cols`
/ `phash_device_cols` / C++ `phash_i64_cols`), so a ((u, i), v) record
hash-routes to the same partition on the host object path, the jnp
device path, and the bulk C++ path bit-for-bit.
"""

import struct

_M1 = 0x85EBCA6B
_M2 = 0xC2B2AE35
_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193
_MASK = 0xFFFFFFFF
TUPLE_SEED = 0x345678
TUPLE_MULT = 0x9E3779B1
_INF = float("inf")
_NINF = float("-inf")


def fmix32(h):
    """murmur3 finalizer on a uint32 (pure Python)."""
    h &= _MASK
    h ^= h >> 16
    h = (h * _M1) & _MASK
    h ^= h >> 13
    h = (h * _M2) & _MASK
    h ^= h >> 16
    return h


def _hash_int(x):
    lo = x & _MASK
    hi = (x >> 32) & _MASK
    return fmix32(lo ^ hi)


def _hash_bytes(b):
    h = _FNV_OFFSET
    for c in b:
        h = ((h ^ c) * _FNV_PRIME) & _MASK
    return fmix32(h)


def portable_hash(obj):
    """Deterministic uint32 hash, stable across processes and Python runs."""
    if obj is None:
        return 0x7F5F
    t = type(obj)
    if t is bool:
        return _hash_int(int(obj))
    if t is int:
        return _hash_int(obj)
    if t is float:
        # NaN/inf first: int(obj) raises on them (== int(obj) crashed
        # any NaN-keyed partition before this guard)
        if obj != obj or obj == _INF or obj == _NINF:
            return _hash_bytes(struct.pack("<d", obj))
        if obj == int(obj) and abs(obj) < 2 ** 62:
            return _hash_int(int(obj))     # hash(1.0) == hash(1)
        return _hash_bytes(struct.pack("<d", obj))
    if t is str:
        return _hash_bytes(obj.encode("utf-8"))
    if t is bytes:
        return _hash_bytes(obj)
    if t is tuple:
        h = TUPLE_SEED
        for item in obj:
            h = ((h ^ portable_hash(item)) * TUPLE_MULT) & _MASK
        return fmix32(h ^ len(obj))
    # subclasses and numpy scalars hash AS THEIR VALUE: dict/partition
    # semantics treat np.str_('w') == 'w' and np.int64(3) == 3 as the
    # same key, so the partitioner must agree — the exact-type pickle
    # fallback silently routed equal keys to different partitions
    # (found by the query parity fuzzer joining a tabular string
    # column against parallelize'd python strs)
    if isinstance(obj, str):
        return _hash_bytes(obj.encode("utf-8"))
    if isinstance(obj, bytes):
        return _hash_bytes(bytes(obj))
    if isinstance(obj, bool):
        return _hash_int(int(obj))
    if isinstance(obj, int):
        return _hash_int(int(obj))
    try:
        import numpy as _np
        if isinstance(obj, _np.bool_):
            return _hash_int(int(obj))
        if isinstance(obj, _np.integer):
            return _hash_int(int(obj))
        if isinstance(obj, _np.floating):
            return portable_hash(float(obj))
    except ImportError:
        pass
    if isinstance(obj, float):
        return portable_hash(float(obj))
    # fallback: structural hash via pickled bytes (deterministic for the
    # value types that reach partitioners in practice)
    import pickle
    return _hash_bytes(pickle.dumps(obj, 4))


def phash_np(keys):
    """NumPy twin of phash_device: bulk host-side hashing of an int array
    -> uint32 array, bit-identical to portable_hash/phash_device.  Used
    for host-side vertex partitioning (device Bagel setup) so state lands
    on the device that hash-routed messages will reach."""
    import numpy as np
    keys = np.asarray(keys)
    if keys.dtype == np.int64:
        lo = (keys & np.int64(0xFFFFFFFF)).astype(np.uint32)
        hi = ((keys >> 32) & np.int64(0xFFFFFFFF)).astype(np.uint32)
    else:
        k = keys.astype(np.int32)
        lo = k.astype(np.uint32)
        hi = (k >> 31).astype(np.uint32)       # 0 or 0xFFFFFFFF
    h = lo ^ hi
    h ^= h >> 16
    h = h * np.uint32(_M1)
    h ^= h >> 13
    h = h * np.uint32(_M2)
    h ^= h >> 16
    return h


def phash_device(keys):
    """Device-side portable hash of an int array -> uint32 array.

    Bit-exactly matches `portable_hash` for any int64 value: the host path
    computes lo = x & 0xFFFFFFFF, hi = (x >> 32) & 0xFFFFFFFF, and
    fmix32(lo ^ hi).  For int32 inputs the hi word is the sign extension,
    reproduced with an arithmetic shift.  Host/device agreement is what
    makes partition assignment identical across masters (lookup,
    partitionBy co-location).
    """
    import jax.numpy as jnp
    if keys.dtype == jnp.int64:
        lo = (keys & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = ((keys >> 32) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    else:
        k = keys.astype(jnp.int32)
        lo = k.astype(jnp.uint32)
        hi = (k >> 31).astype(jnp.uint32)      # 0 or 0xFFFFFFFF
    h = lo ^ hi
    h ^= h >> 16
    h = h * jnp.uint32(_M1)
    h ^= h >> 13
    h = h * jnp.uint32(_M2)
    h ^= h >> 16
    return h


def _fmix32_np(h):
    import numpy as np
    h = h.astype(np.uint32)
    h ^= h >> np.uint32(16)
    h = h * np.uint32(_M1)
    h ^= h >> np.uint32(13)
    h = h * np.uint32(_M2)
    h ^= h >> np.uint32(16)
    return h


def phash_np_cols(cols):
    """Composite (tuple-key) hash of N int column arrays -> uint32
    array, bit-identical to ``portable_hash((k1, ..., kn))`` per row
    when every element is a Python int.  The per-column hash is the
    scalar `phash_np`; columns combine with the tuple recipe."""
    import numpy as np
    cols = list(cols)
    if len(cols) == 1:
        return phash_np(cols[0])
    h = np.full(np.asarray(cols[0]).shape, TUPLE_SEED, np.uint32)
    for c in cols:
        h = (h ^ phash_np(c)) * np.uint32(TUPLE_MULT)
    return _fmix32_np(h ^ np.uint32(len(cols)))


def phash_device_cols(cols):
    """Device twin of phash_np_cols: composite hash over int key
    COLUMNS, matching portable_hash(tuple) bit-for-bit — multi-column
    shuffle destinations agree across the pure-Python host partitioner,
    the jnp exchange, and the C++ bulk path (phash_i64_cols)."""
    import jax.numpy as jnp
    cols = list(cols)
    if len(cols) == 1:
        return phash_device(cols[0])
    h = jnp.full(cols[0].shape, TUPLE_SEED, jnp.uint32)
    for c in cols:
        h = (h ^ phash_device(c)) * jnp.uint32(TUPLE_MULT)
    h = h ^ jnp.uint32(len(cols))
    h ^= h >> 16
    h = h * jnp.uint32(_M1)
    h ^= h >> 13
    h = h * jnp.uint32(_M2)
    h ^= h >> 16
    return h
