"""Worker memory policing.

Reference parity: dpark/utils/memory.py (MemoryChecker) — psutil-based RSS
tracking inside executor workers; over-limit tasks are killed and retried
with more memory (SURVEY.md sections 2.1 and 5.3).  Works without psutil
by reading /proc/self/statm.
"""

import os
import threading

try:
    import psutil
except ImportError:
    psutil = None

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_mb(pid=None):
    """Resident set size of a process in MB."""
    if psutil is not None:
        p = psutil.Process(pid) if pid else psutil.Process()
        return p.memory_info().rss / (1 << 20)
    path = "/proc/%s/statm" % (pid or "self")
    try:
        with open(path) as f:
            fields = f.read().split()
        return int(fields[1]) * _PAGE / (1 << 20)
    except (OSError, IndexError, ValueError):
        return 0.0


class MemoryExceeded(MemoryError):
    def __init__(self, used_mb, limit_mb):
        super().__init__("task used %.0fMB > limit %.0fMB"
                         % (used_mb, limit_mb))
        self.used_mb = used_mb
        self.limit_mb = limit_mb


# process-wide checker installed by the worker bootstrap; hot loops call
# maybe_check() periodically (reference: executor-side RSS policing)
current_checker = None


def maybe_check():
    if current_checker is not None:
        current_checker.check()


class MemoryChecker:
    """Background sampler; raises in the worker (via a flag the task loop
    checks) or reports a peak.  The process master multiplies the limit by
    the retry count so OOM-killed tasks escalate (reference behavior)."""

    def __init__(self, limit_mb=None, interval=0.5):
        self.limit_mb = limit_mb
        self.interval = interval
        self.peak_mb = 0.0
        self.exceeded = None
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval):
            used = rss_mb()
            self.peak_mb = max(self.peak_mb, used)
            if self.limit_mb and used > self.limit_mb:
                self.exceeded = MemoryExceeded(used, self.limit_mb)
                return

    def check(self):
        if self.exceeded is not None:
            raise self.exceeded

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(1)
            self._thread = None
        return self.peak_mb
