"""Task profiling: --profile wraps task execution in cProfile; stats ship
back with results and the driver merges + prints the hottest functions.

Reference parity: dpark/utils/profile.py (SURVEY.md sections 2.1 and 5.1).
On the tpu master per-stage device profiling uses jax.profiler traces
instead (see backend/tpu/executor.py stage timings).
"""

import cProfile
import io
import marshal
import pstats


def profile_call(func, *args, **kwargs):
    """Run func under cProfile; returns (result, stats_bytes)."""
    prof = cProfile.Profile()
    result = prof.runcall(func, *args, **kwargs)
    prof.create_stats()
    return result, marshal.dumps(prof.stats)


class MergedProfile:
    def __init__(self):
        self.stats = None

    def add(self, stats_bytes):
        stats = _StatsCarrier(marshal.loads(stats_bytes))
        if self.stats is None:
            self.stats = pstats.Stats(stats)
        else:
            self.stats.add(stats)

    def summary(self, top=20, sort="cumulative"):
        if self.stats is None:
            return "(no profile data)"
        buf = io.StringIO()
        self.stats.stream = buf
        self.stats.sort_stats(sort).print_stats(top)
        return buf.getvalue()


class _StatsCarrier:
    """Duck-typed object pstats.Stats accepts (has create_stats/stats)."""

    def __init__(self, stats):
        self.stats = stats

    def create_stats(self):
        pass
