"""Shared helpers: compression codec selection, atomic file writes, call-site
extraction for job naming.

Reference parity: dpark/utils/__init__.py (codec selection lz4-else-zlib),
dpark/utils/atomic_file.py (tmp+rename), dpark/utils/frame.py (call-site
scope names).  SURVEY.md section 2.1.
"""

import os
import sys
import zlib
import tempfile
import contextlib

try:
    import lz4.frame as _lz4

    def compress(data):
        return _lz4.compress(data)

    def decompress(data):
        return _lz4.decompress(data)

    CODEC = "lz4"
except ImportError:
    def compress(data):
        return zlib.compress(data, 1)

    def decompress(data):
        return zlib.decompress(data)

    CODEC = "zlib"


@contextlib.contextmanager
def atomic_file(path, mode="wb", fsync=True):
    """Write to a temp file in the same dir, fsync, rename over `path`.

    Reference parity: dpark/utils/atomic_file.py.

    `fsync=False` keeps the no-partial-file guarantee (tmp+rename)
    but skips the durability barrier — for outputs that are
    recomputable through lineage anyway (shuffle bucket files), where
    the per-file fsync dominates the bucket write on slow filesystems.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-" + os.path.basename(path))
    f = os.fdopen(fd, mode)
    try:
        yield f
        f.flush()
        if fsync:
            os.fsync(f.fileno())
        f.close()
        os.rename(tmp, path)
    except BaseException:
        f.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def apply_platform_override():
    """Honor DPARK_TPU_PLATFORM before the first jax backend init (a
    wedged device tunnel must not hang CPU-only work).  The config API
    is the only reliable route: the axon sitecustomize overrides the
    JAX_PLATFORMS env var."""
    plat = os.environ.get("DPARK_TPU_PLATFORM")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass


def user_call_site(depth_limit=12):
    """Return 'file:lineno' of the first stack frame outside dpark_tpu.

    Used for job/stage naming so progress lines read like user code.
    Reference parity: dpark/utils/frame.py.
    """
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    frame = sys._getframe(1)
    for _ in range(depth_limit):
        if frame is None:
            break
        fn = frame.f_code.co_filename
        if not os.path.abspath(fn).startswith(pkg_dir):
            return "%s:%d" % (os.path.basename(fn), frame.f_lineno)
        frame = frame.f_back
    return "<unknown>"


def izip(*its):
    return zip(*its)


def builtin_globals_ok(f, code=None):
    """Every global `f`'s bytecode references still resolves to the
    builtin of that name — the proof obligation shared by all the
    bytecode-template classifiers (fuse.classify_merge/classify_segagg,
    dstream's state-update idiom): a local `sum` shadowing the builtin
    defeats template equality."""
    import builtins
    code = code if code is not None else f.__code__
    fglobals = f.__globals__
    fbuiltins = fglobals.get("__builtins__", builtins)
    for g in code.co_names:
        expected = getattr(builtins, g, None)
        if expected is None:
            return False
        if g in fglobals:
            if fglobals[g] is not expected:
                return False
        elif isinstance(fbuiltins, dict):
            if fbuiltins.get(g) is not expected:
                return False
        elif getattr(fbuiltins, g, None) is not expected:
            return False
    return True


# ---------------------------------------------------------------------------
# crc-framed JSON lines — the shared on-disk format of the adapt store
# and the trace spool: each line is b"<crc32 hex> <canonical json>\n",
# appended with a single O_APPEND write so concurrent processes
# interleave whole lines, and a torn/corrupt line skips at load.
# ---------------------------------------------------------------------------

def frame_jsonl(rec):
    """One record -> one framed line (newline included)."""
    import json
    from dpark_tpu.shuffle import spill_crc
    payload = json.dumps(rec, sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    return b"%08x %s\n" % (spill_crc(payload), payload)


def unframe_jsonl(raw):
    """Framed bytes -> ([dict records], skipped line count).  Lines
    failing the crc, the JSON parse, or the dict shape skip — never an
    error (a torn concurrent append must not poison the load)."""
    import json
    from dpark_tpu.shuffle import spill_crc
    recs, skipped = [], 0
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        head, _, payload = line.partition(b" ")
        try:
            if int(head, 16) != spill_crc(payload):
                raise ValueError("crc mismatch")
            rec = json.loads(payload.decode("utf-8"))
            if not isinstance(rec, dict):
                raise ValueError("non-dict record")
        except Exception:
            skipped += 1
            continue
        recs.append(rec)
    return recs, skipped
