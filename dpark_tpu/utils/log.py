"""Colored logging + per-stage progress reporting.

Reference parity: dpark/utils/log.py (init_dpark_logger, tty progress bar).
SURVEY.md section 2.1 / 5.5.
"""

import os
import sys
import logging

_COLORS = {
    logging.DEBUG: "\x1b[36m",
    logging.INFO: "\x1b[32m",
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[35m",
}
_RESET = "\x1b[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        s = super().format(record)
        if sys.stderr.isatty():
            c = _COLORS.get(record.levelno, "")
            return c + s + _RESET
        return s


_initialized = False


def init_dpark_logger(level=None):
    global _initialized
    if _initialized:
        return
    _initialized = True
    if level is None:
        level = os.environ.get("DPARK_LOG_LEVEL", "WARNING")
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(_ColorFormatter(
        "%(asctime)s [%(levelname)s] [%(name)s] %(message)s", "%H:%M:%S"))
    root = logging.getLogger("dpark_tpu")
    root.addHandler(h)
    root.setLevel(level)


def get_logger(name):
    init_dpark_logger()
    return logging.getLogger("dpark_tpu." + name)


class Progress:
    """One-line tty progress bar per stage (reference: dpark/utils/log.py)."""

    def __init__(self, title, total):
        self.title = title
        self.total = max(total, 1)
        self.done = 0
        self.enabled = sys.stderr.isatty() and os.environ.get(
            "DPARK_PROGRESS", "1") != "0"

    def tick(self, n=1):
        self.done += n
        if not self.enabled:
            return
        width = 30
        filled = int(width * self.done / self.total)
        bar = "=" * filled + " " * (width - filled)
        sys.stderr.write("\r%s [%s] %d/%d" %
                         (self.title, bar, self.done, self.total))
        if self.done >= self.total:
            sys.stderr.write("\n")
        sys.stderr.flush()
