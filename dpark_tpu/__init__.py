"""dpark_tpu — a TPU-native distributed dataset framework with the
capabilities of douban/dpark.

Same semantic contract as the reference (lazy partitioned RDDs, DAG
scheduler cutting stages at shuffle boundaries, local/process masters) with
a TPU master where stages compile to jitted SPMD programs over a jax device
mesh and shuffles run as ICI collectives (see SURVEY.md and backend/tpu/).
"""

from dpark_tpu.utils import apply_platform_override

# honor DPARK_TPU_PLATFORM for EVERY master before any jax backend
# init: user code may call jnp on the local/process masters too, and
# without the override their first jnp call dials the real device
# backend — which hangs forever on a wedged tunnel.  No-op unless the
# env var is set.
apply_platform_override()

from dpark_tpu.context import DparkContext, optParser, parse_options
from dpark_tpu.rdd import Columns

__version__ = "0.1.0"

_default_ctx = None


def _ctx():
    global _default_ctx
    if _default_ctx is None:
        _default_ctx = DparkContext()
    return _default_ctx


def parallelize(seq, numSlices=None):
    return _ctx().parallelize(seq, numSlices)


def makeRDD(seq, numSlices=None):
    return _ctx().makeRDD(seq, numSlices)


def textFile(path, **kw):
    return _ctx().textFile(path, **kw)


def accumulator(init=0, param=None):
    return _ctx().accumulator(init, param)


def broadcast(value):
    return _ctx().broadcast(value)


__all__ = ["DparkContext", "optParser", "parse_options", "parallelize",
           "makeRDD", "textFile", "accumulator", "broadcast"]
