"""Dependency lattice, partitioners, and the combineByKey aggregator.

Reference parity: dpark/dependency.py — Dependency/NarrowDependency/
OneToOneDependency/RangeDependency/ShuffleDependency, Partitioner/
HashPartitioner/RangePartitioner, Aggregator(createCombiner, mergeValue,
mergeCombiners) (SURVEY.md section 2.1; the DAG scheduler cuts stages on
ShuffleDependency edges).
"""

import bisect

from dpark_tpu.utils.phash import portable_hash


class Dependency:
    def __init__(self, rdd):
        self.rdd = rdd

    @property
    def is_shuffle(self):
        return isinstance(self, ShuffleDependency)


class NarrowDependency(Dependency):
    """Child partition depends on a statically known set of parent parts."""

    def get_parents(self, partition_id):
        raise NotImplementedError


class OneToOneDependency(NarrowDependency):
    def get_parents(self, pid):
        return [pid]


class RangeDependency(NarrowDependency):
    """Used by UnionRDD: child partitions [out_start, out_start+length) map
    1:1 onto parent partitions [in_start, in_start+length)."""

    def __init__(self, rdd, in_start, out_start, length):
        super().__init__(rdd)
        self.in_start = in_start
        self.out_start = out_start
        self.length = length

    def get_parents(self, pid):
        if self.out_start <= pid < self.out_start + self.length:
            return [pid - self.out_start + self.in_start]
        return []


class CartesianDependency(NarrowDependency):
    def __init__(self, rdd, which, num_other):
        super().__init__(rdd)
        self.which = which          # 0 = row side, 1 = column side
        self.num_other = num_other

    def get_parents(self, pid):
        if self.which == 0:
            return [pid // self.num_other]
        return [pid % self.num_other]


# itertools.count: atomic under the GIL — concurrent drivers on a
# resident job server (ISSUE 9) build graphs from their own threads,
# and two shuffles sharing an id would cross their map outputs
import itertools

_next_shuffle_id = itertools.count(1)


def new_shuffle_id():
    return next(_next_shuffle_id)


class ShuffleDependency(Dependency):
    """A wide edge: child partitions need a repartitioning of all parent
    partitions.  Stage boundary for the DAG scheduler; collective boundary
    for the TPU backend."""

    def __init__(self, rdd, aggregator, partitioner):
        super().__init__(rdd)
        self.shuffle_id = new_shuffle_id()
        self.aggregator = aggregator
        self.partitioner = partitioner


class Aggregator:
    """combineByKey triple (reference: dpark Aggregator)."""

    def __init__(self, create_combiner, merge_value, merge_combiners):
        self.create_combiner = create_combiner
        self.merge_value = merge_value
        self.merge_combiners = merge_combiners

    # camelCase aliases for API parity
    @property
    def createCombiner(self):
        return self.create_combiner

    @property
    def mergeValue(self):
        return self.merge_value

    @property
    def mergeCombiners(self):
        return self.merge_combiners


class Partitioner:
    @property
    def num_partitions(self):
        raise NotImplementedError

    def get_partition(self, key):
        raise NotImplementedError

    # camelCase parity aliases — delegate so subclass overrides of the
    # snake_case methods are honored
    @property
    def numPartitions(self):
        return self.num_partitions

    def getPartition(self, key):
        return self.get_partition(key)


class HashPartitioner(Partitioner):
    def __init__(self, partitions):
        self.partitions = max(1, int(partitions))

    @property
    def num_partitions(self):
        return self.partitions

    def get_partition(self, key):
        return portable_hash(key) % self.partitions

    def __eq__(self, other):
        return (isinstance(other, HashPartitioner)
                and other.partitions == self.partitions)

    def __hash__(self):
        return self.partitions


class SaltedHashPartitioner(Partitioner):
    """Hash partitioner over ``(salt, key)`` — the mid-job re-plan's
    re-split target (ISSUE 19).  A workload whose keys collide under
    ``portable_hash(key) % n`` (one dominant bucket, many distinct
    keys) re-spreads under the salted tuple hash WITHOUT changing the
    reduce width, so a running job's fixed output_parts stay valid.

    Deliberately NOT a HashPartitioner subclass: the device path's
    ``partitioner_spec`` hashes raw keys and a cogroup treats equal
    HashPartitioners as copartitioned — both would silently
    mis-bucket a salted exchange, so this class compares equal only
    to an identically-salted peer and the device path declines it."""

    def __init__(self, partitions, salt=1):
        self.partitions = max(1, int(partitions))
        self.salt = int(salt)

    @property
    def num_partitions(self):
        return self.partitions

    def get_partition(self, key):
        return portable_hash((self.salt, key)) % self.partitions

    def __eq__(self, other):
        return (isinstance(other, SaltedHashPartitioner)
                and other.partitions == self.partitions
                and other.salt == self.salt)

    def __hash__(self):
        return hash((self.partitions, self.salt))


class RangePartitioner(Partitioner):
    """Sorted-sample range partitioner backing sortByKey (reference:
    dpark RangePartitioner — bounds from a sample, bisect per key)."""

    def __init__(self, bounds, ascending=True):
        self.bounds = list(bounds)
        self.ascending = ascending

    @property
    def num_partitions(self):
        return len(self.bounds) + 1

    def get_partition(self, key):
        idx = bisect.bisect_left(self.bounds, key)
        return idx if self.ascending else len(self.bounds) - idx

    def __eq__(self, other):
        return (isinstance(other, RangePartitioner)
                and other.bounds == self.bounds
                and other.ascending == self.ascending)

    def __hash__(self):
        return hash((tuple(self.bounds), self.ascending))
