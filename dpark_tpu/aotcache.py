"""Persistent AOT executable cache (ISSUE 17 tentpole; ROADMAP item 2).

PR 9 made compile amortization a property of one resident process:
`_ProgramCache` means a re-submitted DAG compiles nothing — until the
process dies.  A JobServer restart (deploy, crash, autoscale) is a
cold-start storm: every replica re-pays every compile, exactly the
restart-latency tail the reference dpark's resident-worker design was
meant to hide.  This module is the second tier: compiled executables
serialize through jax's AOT export path (``jax.experimental.
serialize_executable``) into an on-disk cache a FRESH process loads
instead of compiling.

Entry format (one file per program, ``<disk_key>.aot``)::

    <crc32 hex> <canonical json header>\\n      # utils.frame_jsonl
    <crc32 hex> <payload length hex>\\n
    <pickled (payload, in_tree, out_tree)>      # serialize() triple

The header carries the full identity — disk key, adapt signature,
jax/jaxlib versions, backend platform/device topology, x64 flag — and
is RE-VERIFIED at load: a mismatch on any field skips the entry
silently (a cache dir surviving a jax upgrade must never feed a stale
executable to the wrong runtime).  Files are written tmp+rename
(``utils.atomic_file``) and an ``index.jsonl`` of crc-framed lines is
appended with single O_APPEND writes — the adapt-store idioms — so
ONE cache directory is safely shared across service replicas and
concurrent writers: readers see whole entries or no entry, torn index
lines skip at load, and corruption always means "fall back to
compile", never an error.

Modes (``DPARK_AOT_CACHE`` / conf.AOT_CACHE):

  off   no plane installed.  The program-cache seam costs exactly one
        module-global load + ``is None`` check — the same off-mode
        contract as the faults/trace/health/ledger/lockcheck planes,
        machine-checked by the ``plane-contract`` dlint rule.
  read  memory misses consult the disk tier but never write — a
        replica trusting a cache directory it does not own.
  on    read + newly compiled programs store back, and eviction under
        DPARK_PROGRAM_CACHE_MAX writes back before dropping.

Boot warming: a starting JobServer ranks the index by the adapt
store's observed cost profiles (compile ms x hit count — the same
observed-cost-steers-work framing the coded-shuffle plane uses) and
deserializes the hottest entries into a preload map under a
``DPARK_AOT_WARM_BUDGET_MS`` deadline, so the first submission after
a restart starts from loaded executables: zero backend compiles.
"""

import os
import pickle
import threading
import time

from dpark_tpu import conf, locks
from dpark_tpu.utils import atomic_file, frame_jsonl, unframe_jsonl
from dpark_tpu.utils.log import get_logger

logger = get_logger("aotcache")

__all__ = ["MODES", "AotCachePlane", "AotProgram", "configure",
           "active", "plane", "stats", "set_current_sig",
           "version_key"]

MODES = ("off", "read", "on")

# entry-format generation: bump on any layout change so old dirs skip
FORMAT = "dpark-aot-1"

INDEX_FILE = "index.jsonl"

COUNTERS = ("loads", "load_misses", "load_errors", "version_skips",
            "stores", "store_errors", "evict_writebacks", "warmed",
            "warm_hits", "fallbacks")

_PLANE = None
_tls = threading.local()


def _crc(data):
    from dpark_tpu.shuffle import spill_crc
    return spill_crc(data)


def version_key():
    """The compatibility half of an entry's identity: a serialized
    executable is machine code, only as portable as the stack that
    produced it.  jax/jaxlib versions, backend platform, device count
    and kinds, and the x64 flag — any drift invalidates (by missing
    the keyed filename AND by the header re-check at load)."""
    import jax
    try:
        import jaxlib
        jl = str(getattr(jaxlib, "__version__", "?"))
    except Exception:
        jl = "?"
    devs = jax.devices()
    return {
        "fmt": FORMAT,
        "jax": str(jax.__version__),
        "jaxlib": jl,
        "platform": str(devs[0].platform) if devs else "?",
        "ndev": len(devs),
        "kinds": ",".join(sorted({str(getattr(d, "device_kind", "?"))
                                  for d in devs})),
        "x64": bool(jax.config.jax_enable_x64),
    }


class AotCachePlane:
    """One process's handle on a shared on-disk executable cache."""

    def __init__(self, mode, cache_dir):
        self.mode = mode
        self.dir = cache_dir
        self._mu = locks.named_lock("aot.store")
        self._counters = {k: 0 for k in COUNTERS}
        self._warm = {}          # disk_key -> preloaded Compiled
        self._ver = None         # version_key(), computed lazily (the
        #                          first use may be the first jax
        #                          backend touch of the process)

    # -- identity --------------------------------------------------------
    def _version(self):
        ver = self._ver
        if ver is None:
            ver = self._ver = version_key()
        return ver

    def disk_key(self, mem_key):
        """Entry filename stem: the cross-process-stable hash of the
        executor's full program-cache key tuple combined with the
        version/topology key (adapt.stable_key strips transient
        ``at 0x...`` addresses, hashes code objects by bytecode)."""
        from dpark_tpu import adapt
        ver = self._version()
        return adapt.stable_key((mem_key, tuple(sorted(ver.items()))))

    def _entry_path(self, dk):
        return os.path.join(self.dir, dk + ".aot")

    def _bump(self, name, n=1):
        with self._mu:
            self._counters[name] += n

    # -- store -----------------------------------------------------------
    def store(self, dk, compiled, sig=None, compile_ms=0.0,
              reason="store"):
        """Serialize one compiled executable to ``<dk>.aot`` (tmp +
        rename) and append its index line.  Mode-gated; never raises
        (a program jax cannot serialize simply stays memory-only)."""
        if self.mode != "on" or dk is None:
            return False
        from dpark_tpu import trace
        try:
            with trace.span("aot.store", "aot", key=dk, sig=sig,
                            reason=reason):
                from jax.experimental import serialize_executable
                payload, in_tree, out_tree = \
                    serialize_executable.serialize(compiled)
                blob = pickle.dumps((payload, in_tree, out_tree),
                                    protocol=pickle.HIGHEST_PROTOCOL)
                header = dict(self._version())
                header.update(key=dk, sig=sig,
                              compile_ms=round(float(compile_ms), 3),
                              nbytes=len(blob),
                              created=round(time.time(), 3))
                with atomic_file(self._entry_path(dk)) as f:
                    f.write(frame_jsonl(header))
                    f.write(b"%08x %08x\n" % (_crc(blob), len(blob)))
                    f.write(blob)
                self._append_index({"k": dk, "sig": sig,
                                    "compile_ms": round(
                                        float(compile_ms), 3),
                                    "nbytes": len(blob)})
            self._bump("stores")
            if reason == "evict":
                self._bump("evict_writebacks")
            return True
        except Exception as e:
            logger.debug("aot store failed for %s: %s", dk, e)
            self._bump("store_errors")
            return False

    def _append_index(self, rec):
        """One crc-framed line, one O_APPEND write: concurrent
        replicas interleave whole lines (the adapt-store idiom)."""
        line = frame_jsonl(rec)
        fd = os.open(os.path.join(self.dir, INDEX_FILE),
                     os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)

    def index(self):
        """{disk_key: latest index record}.  Torn/corrupt lines skip;
        duplicate keys (same program re-stored by another replica)
        fold latest-wins."""
        try:
            with open(os.path.join(self.dir, INDEX_FILE), "rb") as f:
                raw = f.read()
        except OSError:
            return {}
        recs, _ = unframe_jsonl(raw)
        out = {}
        for r in recs:
            dk = r.get("k")
            if dk:
                out[str(dk)] = r
        return out

    # -- load ------------------------------------------------------------
    def load(self, dk, sig=None):
        """The disk tier: a boot-warm preload if one is pending for
        this key, else read + verify + deserialize the entry file.
        None on any miss or defect — the caller compiles."""
        with self._mu:
            exe = self._warm.pop(dk, None)
            if exe is not None:
                self._counters["warm_hits"] += 1
        if exe is not None:
            return exe
        from dpark_tpu import trace
        with trace.span("aot.load", "aot", key=dk, sig=sig):
            exe = self._load_entry(dk)
        self._bump("loads" if exe is not None else "load_misses")
        return exe

    def _load_entry(self, dk):
        """Read one entry file; None on ANY defect — missing file,
        torn header, version/topology drift, payload crc or length
        mismatch, unpicklable blob, deserialize failure.  Corruption
        means recompute, never an error (the adapt-store contract)."""
        try:
            with open(self._entry_path(dk), "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            head, _, rest = raw.partition(b"\n")
            recs, skipped = unframe_jsonl(head + b"\n")
            if skipped or not recs:
                raise ValueError("corrupt header")
            header = recs[0]
            for k, v in self._version().items():
                if header.get(k) != v:
                    self._bump("version_skips")
                    return None
            crcline, _, blob = rest.partition(b"\n")
            crc_hex, _, len_hex = crcline.partition(b" ")
            if len(blob) != int(len_hex, 16):
                raise ValueError("truncated payload")
            if int(crc_hex, 16) != _crc(blob):
                raise ValueError("payload crc mismatch")
            payload, in_tree, out_tree = pickle.loads(blob)
            from jax.experimental import serialize_executable
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:
            logger.debug("aot entry %s unusable: %s", dk, e)
            self._bump("load_errors")
            return None

    # -- boot warming ----------------------------------------------------
    def ranked_entries(self, idx=None, costs=None):
        """Index records, hottest first: score = the adapt store's
        observed compile ms x hit count for the entry's signature
        (ties and unprofiled entries fall back to the compile_ms the
        storing process measured)."""
        if idx is None:
            idx = self.index()
        if costs is None:
            from dpark_tpu import adapt
            costs = adapt.program_costs()

        def _score(rec):
            prof = costs.get(str(rec.get("sig"))) or {}
            ms = float(prof.get("compile_ms", 0.0) or 0.0)
            hits = float(prof.get("hits", 0.0) or 0.0)
            return (ms * max(hits, 1.0),
                    float(rec.get("compile_ms", 0.0) or 0.0))

        return sorted(idx.values(), key=_score, reverse=True)

    def warm(self, budget_ms=None, costs=None):
        """Deserialize the hottest entries into the preload map under
        a wall-clock deadline; the first proxy resolution for each key
        then starts from a loaded executable.  Returns a summary for
        the boot log / service stats."""
        t0 = time.time()
        if budget_ms is None:
            budget_ms = float(getattr(conf, "AOT_WARM_BUDGET_MS", 0.0)
                              or 0.0)
        ranked = self.ranked_entries(costs=costs)
        deadline = t0 + budget_ms / 1e3
        from dpark_tpu import trace
        warmed = 0
        for rec in ranked:
            if time.time() >= deadline:
                break
            dk = str(rec.get("k"))
            with self._mu:
                pending = dk in self._warm
            if pending:
                continue
            with trace.span("aot.warm", "aot", key=dk,
                            sig=rec.get("sig")):
                exe = self._load_entry(dk)
            if exe is None:
                continue
            with self._mu:
                self._warm[dk] = exe
                self._counters["warmed"] += 1
            warmed += 1
        return {"warmed": warmed, "entries": len(ranked),
                "ms": round((time.time() - t0) * 1e3, 1),
                "budget_ms": budget_ms}

    # -- the seam --------------------------------------------------------
    def wrap(self, key, jitted):
        """Wrap one freshly inserted program in the lazy two-tier
        proxy (idempotent: re-inserting an already-wrapped value keeps
        its resolved executable)."""
        if isinstance(jitted, AotProgram):
            return jitted
        return AotProgram(self, key, jitted,
                          getattr(_tls, "sig", None))

    def stats(self):
        with self._mu:
            out = dict(self._counters)
            out["mode"] = self.mode
            out["warm_pending"] = len(self._warm)
        return out


class AotProgram:
    """Lazy two-tier program handle the executor's ``_ProgramCache``
    stores instead of the raw ``jax.jit`` callable.

    The first call resolves the executable: boot-warm preload ->
    disk load -> (mode ``on``) AOT compile via ``jitted.lower(*args)
    .compile()`` with store-back.  The raw jitted callable rides
    along as the permanent fallback — any executable-level failure
    (arg shape/dtype drift vs. the serialized program, a backend that
    refuses the payload) drops the executable and falls back to the
    live jit path, bit-identical by construction.
    """

    __slots__ = ("_plane", "_key", "_jitted", "_sig", "_exe",
                 "_resolved", "_stored", "_dk", "_mu")

    def __init__(self, plane, key, jitted, sig=None):
        self._plane = plane
        self._key = key
        self._jitted = jitted
        self._sig = sig
        self._exe = None
        self._resolved = False
        self._stored = False
        self._dk = None
        self._mu = threading.Lock()

    def lower(self, *args, **kw):
        # the ledger's cost capture prices programs via .lower() — a
        # host-side re-trace of the LIVE jit, never the executable
        return self._jitted.lower(*args, **kw)

    def __call__(self, *args):
        exe = self._exe
        if exe is None and not self._resolved:
            exe = self._resolve(args)
        if exe is not None:
            try:
                return exe(*args)
            except Exception:
                # executable-level drift: fall back for good (the jit
                # path recompiles under its own cache and stays
                # correct for every later shape)
                self._exe = None
                self._plane._bump("fallbacks")
        return self._jitted(*args)

    def _resolve(self, args):
        with self._mu:
            if self._resolved:
                return self._exe
            plane = self._plane
            exe = None
            try:
                dk = self._dk = plane.disk_key(self._key)
                exe = plane.load(dk, self._sig)
                if exe is not None:
                    self._stored = True        # it came FROM disk
                    self._note(0.0)
                elif plane.mode == "on":
                    t0 = time.time()
                    exe = self._jitted.lower(*args).compile()
                    ms = (time.time() - t0) * 1e3
                    self._stored = plane.store(dk, exe, self._sig, ms)
                    self._note(ms)
                else:
                    self._note(0.0)
            except Exception as e:
                logger.debug("aot resolve failed for %r: %s",
                             self._sig or self._key, e)
                exe = None
            self._exe = exe
            self._resolved = True
            return exe

    def _note(self, compile_ms):
        """Fold this resolution into the adapt store's program profile
        (hits accumulate, compile_ms smooths) — the observed-cost
        signal boot warming ranks by."""
        if not self._sig:
            return
        from dpark_tpu import adapt
        prof = {"hits": 1}
        if compile_ms:
            prof["compile_ms"] = round(compile_ms, 3)
        adapt.record_program_cost(self._sig, prof)

    def writeback(self):
        """Eviction hook: persist a resolved-but-unstored executable
        before the memory tier drops it (a later re-insert then loads
        instead of compiling).  store() carries the mode gate."""
        exe = self._exe
        if exe is None or self._stored:
            return False
        ok = self._plane.store(self._dk, exe, self._sig, 0.0,
                               reason="evict")
        self._stored = bool(ok)
        return ok


# ---------------------------------------------------------------------------
# module seams (plane-contract shapes, registered in
# analysis/concurrency.py PLANE_SEAMS)
# ---------------------------------------------------------------------------

def set_current_sig(sig):
    """Stamp the adapt signature tuple (progid, shapeclass) program
    insertions on THIS thread belong to (None clears) — the executor
    calls this where it stamps trace.set_compile_sig.  One global
    load + ``is None`` check when the plane is off."""
    if _PLANE is None:
        return None
    _tls.sig = "%s|%s" % (sig[0], sig[1]) if sig else None


def stats():
    """Hot counters + mode for /metrics and the web UI; None when the
    plane is off."""
    plane = _PLANE
    if plane is None:
        return None
    return plane.stats()


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(mode=None, cache_dir=None):
    """Install (read/on) or clear (off) the process plane.  None
    reads conf.AOT_CACHE.  Returns the installed plane or None."""
    global _PLANE
    if mode is None:
        mode = str(getattr(conf, "AOT_CACHE", "off") or "off")
    mode = str(mode).strip().lower()
    if mode in ("", "0", "none", "disable", "disabled"):
        mode = "off"
    if mode not in MODES:
        raise ValueError("DPARK_AOT_CACHE=%r (expected off|read|on)"
                         % mode)
    if mode == "off":
        _PLANE = None
        return None
    _PLANE = AotCachePlane(mode, cache_dir or conf.AOT_CACHE_DIR)
    return _PLANE


def active():
    return _PLANE is not None


def plane():
    return _PLANE


def _init_from_conf():
    m = str(getattr(conf, "AOT_CACHE", "off") or "off")
    if m not in ("off", ""):
        configure(m)


_init_from_conf()
