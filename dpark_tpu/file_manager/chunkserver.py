"""Chunk-server filesystem: a minimal but real DFS protocol client.

Reference parity: dpark/moosefs/ (SURVEY.md section 2.4) — the reference
carries a full MooseFS master+chunkserver protocol client delivering
three capabilities: real preferredLocations per chunk, direct chunk
reads bypassing FUSE, and fast tree walks.  MooseFS itself is
Douban-infrastructure-specific, so this module keeps the protocol shape
(stat / walk / per-chunk locations / crc-verified ranged reads over TCP)
against a self-contained chunk server — proving the file_manager scheme
registry with a network filesystem, and serving as the template for a
production DFS client.

Paths look like  cfs://host:port/abs/path ; `register()` installs the
client under the "cfs" scheme.  Reads are ranged requests verified with
crc32c per response (the reference checks 64KB-block crc32c on its
chunkserver read path).
"""

import io
import json
import os
import socket
import struct

from dpark_tpu.dcn import FramedServer, fetch
from dpark_tpu.file_manager import FileSystem, register_filesystem
from dpark_tpu.native import crc32c
from dpark_tpu.utils.log import get_logger

logger = get_logger("chunkserver")

CHUNK = 64 << 20                  # locality granularity (64MB chunks)
READ_BLOCK = 1 << 20              # client read-ahead per request


def _call(addr, req, timeout=30):
    """One request/response against a chunk server.  Responses are
    never pickled (the peer is untrusted network input): "read" frames
    are 4-byte crc32c + raw bytes, everything else is JSON."""
    payload = fetch("tcp://" + addr, req, timeout)
    if req[0] == "read":
        (crc,) = struct.unpack("!I", payload[:4])
        return payload[4:], crc
    return json.loads(payload.decode("utf-8"))


class ChunkServer(FramedServer):
    """Serves one directory tree: metadata (stat/walk/locations) and
    crc-verified ranged reads.  `host_map(path, chunk_index) -> [hosts]`
    supplies per-chunk locality (tests fake it; a real deployment
    reports which servers replicate the chunk)."""

    def __init__(self, root, host="127.0.0.1", port=0, host_map=None,
                 corrupt_reads=False):
        self.root = os.path.realpath(root)
        self.host_map = host_map or (
            lambda path, idx: [socket.gethostname()])
        self.corrupt_reads = corrupt_reads       # test hook: bad payload
        super().__init__(self._encode, host, port,
                         name="dpark-chunk-server")

    def _encode(self, req):
        out = self._serve(req)
        if req[0] == "read":
            data, crc = out
            return struct.pack("!I", crc) + data
        return json.dumps(out).encode()

    @property
    def addr(self):
        return "%s:%d" % self.bind_address

    def start(self):
        super().start()
        logger.debug("chunk server for %s on %s", self.root, self.addr)
        return self

    def _resolve(self, path):
        # realpath, not abspath: containment must hold after symlink
        # resolution, or a link inside the root escapes it
        full = os.path.realpath(os.path.join(self.root,
                                             path.lstrip("/")))
        if not (full == self.root
                or full.startswith(self.root + os.sep)):
            raise PermissionError("outside served root: %s" % path)
        return full

    def _serve(self, req):
        kind = req[0]
        if kind == "stat":
            return os.path.getsize(self._resolve(req[1]))
        if kind == "walk":
            out = []
            full = self._resolve(req[1])
            if os.path.isfile(full):
                return [(req[1], os.path.getsize(full))]
            for root, _, names in os.walk(full):
                for n in sorted(names):
                    if n.startswith("."):
                        continue
                    p = os.path.join(root, n)
                    if not os.path.isfile(p):
                        continue          # dangling symlink / fifo
                    rel = "/" + os.path.relpath(p, self.root)
                    out.append((rel, os.path.getsize(p)))
            return out
        if kind == "locations":
            _, path, offset, length = req
            self._resolve(path)          # existence/containment check
            first = offset // CHUNK
            last = (offset + max(0, (length or 1) - 1)) // CHUNK
            hosts = []
            for idx in range(first, last + 1):
                for h in self.host_map(path, idx):
                    if h not in hosts:
                        hosts.append(h)
            return hosts
        if kind == "read":
            _, path, offset, length = req
            with open(self._resolve(path), "rb") as f:
                f.seek(offset)
                data = f.read(length)
            if self.corrupt_reads and data:
                data = bytes([data[0] ^ 0xFF]) + data[1:]
                return (data, crc32c(b""))       # stale checksum
            return (data, crc32c(data))
        raise ValueError("unknown request %r" % (kind,))


class _RangedRaw(io.RawIOBase):
    """Seekable raw stream over ranged chunk-server reads with per-read
    crc32c verification; io.BufferedReader on top provides read/readline
    exactly like a local file."""

    def __init__(self, addr, path, size):
        self.addr = addr
        self.path = path
        self.size = size
        self.pos = 0

    def readable(self):
        return True

    def seekable(self):
        return True

    def seek(self, off, whence=0):
        if whence == 0:
            self.pos = off
        elif whence == 1:
            self.pos += off
        else:
            self.pos = self.size + off
        return self.pos

    def tell(self):
        return self.pos

    def readinto(self, b):
        n = min(len(b), self.size - self.pos)
        if n <= 0:
            return 0
        data, crc = _call(self.addr,
                          ("read", self.path, self.pos, n))
        if crc32c(data) != crc:
            raise IOError("crc32c mismatch reading %s @%d"
                          % (self.path, self.pos))
        b[:len(data)] = data
        self.pos += len(data)
        return len(data)


class ChunkServerFileSystem(FileSystem):
    """file_manager client for cfs://host:port/path."""

    scheme = "cfs"

    @staticmethod
    def _parse(path):
        addr, _, rest = path.partition("/")
        return addr, "/" + rest

    def exists(self, path):
        addr, p = self._parse(path)
        try:
            _call(addr, ("stat", p))
            return True
        except IOError:
            return False

    def size(self, path):
        addr, p = self._parse(path)
        return _call(addr, ("stat", p))

    def open(self, path, mode="rb"):
        if mode not in ("rb", "r"):
            raise ValueError("chunk server files are read-only")
        addr, p = self._parse(path)
        size = _call(addr, ("stat", p))
        return io.BufferedReader(_RangedRaw(addr, p, size),
                                 buffer_size=READ_BLOCK)

    def walk(self, path):
        addr, p = self._parse(path)
        for rel, size in _call(addr, ("walk", p)):
            yield addr + rel, size

    def locations(self, path, offset=0, length=None):
        addr, p = self._parse(path)
        return _call(addr, ("locations", p, offset, length or 1))


def register():
    register_filesystem("cfs", ChunkServerFileSystem())


register()
