"""File manager: pluggable filesystem layer with chunk locality.

Reference parity: dpark/moosefs/ and its later refactor dpark/file_manager/
(SURVEY.md section 2.4) — the reference speaks the MooseFS master/chunk
server protocols to (1) supply preferredLocations for file RDD splits,
(2) read chunks directly bypassing FUSE, and (3) walk directory trees
fast.  MooseFS is Douban-infrastructure-specific; the TPU-native design
keeps the same three capabilities behind a scheme registry:

  * LocalFileSystem — POSIX files, locality = this host;
  * any distributed filesystem mounts by registering a FileSystem
    subclass for its scheme (`register_filesystem("mfs", MfsFS())`) and
    reporting real chunk hosts from `locations()`.

TextFileRDD and friends consult this layer for walking and locality so a
DFS plugs in without touching the RDD code.
"""

import os
import socket

from dpark_tpu.native import crc32c

CHUNK_SIZE = 64 << 20          # the reference's 64MB chunk granularity


class FileSystem:
    scheme = None

    def exists(self, path):
        raise NotImplementedError

    def size(self, path):
        raise NotImplementedError

    def open(self, path, mode="rb"):
        raise NotImplementedError

    def walk(self, path):
        """Yield (file_path, size) for every regular file under path."""
        raise NotImplementedError

    def locations(self, path, offset=0, length=None):
        """Hosts holding the chunk(s) covering [offset, offset+length)."""
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    scheme = "file"

    def exists(self, path):
        return os.path.exists(path)

    def size(self, path):
        return os.path.getsize(path)

    def open(self, path, mode="rb"):
        return open(path, mode)

    def walk(self, path):
        if os.path.isfile(path):
            yield path, os.path.getsize(path)
            return
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        for root, _, names in os.walk(path):
            for n in sorted(names):
                if n.startswith("."):
                    continue
                p = os.path.join(root, n)
                if os.path.isfile(p):
                    yield p, os.path.getsize(p)

    def locations(self, path, offset=0, length=None):
        return [socket.gethostname()]


_registry = {}


def register_filesystem(scheme, fs):
    _registry[scheme] = fs


register_filesystem("file", LocalFileSystem())


def _split_scheme(path):
    if "://" in path:
        scheme, _, rest = path.partition("://")
        return scheme, rest
    return "file", path


def get_filesystem(path):
    scheme, rest = _split_scheme(path)
    fs = _registry.get(scheme)
    if fs is None:
        raise ValueError("no filesystem registered for scheme %r" % scheme)
    return fs, rest


def exists(path):
    fs, p = get_filesystem(path)
    return fs.exists(p)


def open_file(path, mode="rb"):
    fs, p = get_filesystem(path)
    return fs.open(p, mode)


def walk(path):
    """Yield (path, size); non-local paths are re-qualified with their
    scheme so every later per-file call routes back to the same fs."""
    scheme, _ = _split_scheme(path)
    fs, p = get_filesystem(path)
    prefix = "" if scheme == "file" else scheme + "://"
    for fp, size in fs.walk(p):
        yield prefix + fp, size


def file_size(path):
    fs, p = get_filesystem(path)
    return fs.size(p)


def locations(path, offset=0, length=None):
    fs, p = get_filesystem(path)
    return fs.locations(p, offset, length)


def chunks_of(path):
    """(offset, length) pairs at CHUNK_SIZE granularity (reference: 64MB
    MooseFS chunks, the natural split size for file RDDs)."""
    size = file_size(path)
    out = []
    off = 0
    while off < size:
        out.append((off, min(CHUNK_SIZE, size - off)))
        off += CHUNK_SIZE
    return out or [(0, 0)]


class VerifyingReader:
    """Block reader with crc32c verification per block (reference: the
    chunkserver read path checks 64KB-block crc32c)."""

    BLOCK = 64 << 10

    def __init__(self, path, checksums=None):
        self.f = open_file(path)
        self.checksums = checksums
        self.index = 0

    def read_block(self):
        data = self.f.read(self.BLOCK)
        if not data:
            return b""
        if self.checksums is not None:
            expect = self.checksums[self.index]
            got = crc32c(data)
            if got != expect:
                raise IOError("crc32c mismatch at block %d" % self.index)
        self.index += 1
        return data

    def close(self):
        self.f.close()


# chunk-server DFS client registers the "cfs" scheme on import
from dpark_tpu.file_manager import chunkserver as _chunkserver  # noqa: E402,F401
