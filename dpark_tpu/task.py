"""Serializable task units shipped to executors.

Reference parity: dpark/task.py — Task base, ResultTask (runs
func(rdd.iterator(split)) and returns the value), ShuffleMapTask (partitions
and pre-combines its input, writes one bucket per reducer, returns the map
output location) (SURVEY.md sections 2.1 and 3.1).
"""

from dpark_tpu.shuffle import LocalFileShuffle


class Task:
    _next_id = [0]

    def __init__(self, stage_id, partition):
        Task._next_id[0] += 1
        self.id = Task._next_id[0]
        self.stage_id = stage_id
        self.partition = partition
        self.tried = 0

    def run(self, attempt_id):
        raise NotImplementedError

    def retry_copy(self):
        """A fresh attempt of the same work with its own task id; the
        retry counter carries over (memory-limit escalation keys on
        it)."""
        import copy
        t = copy.copy(self)
        Task._next_id[0] += 1
        t.id = Task._next_id[0]
        t.tried = self.tried + 1
        return t

    def preferred_locations(self):
        return []


class ResultTask(Task):
    def __init__(self, stage_id, rdd, func, partition, output_id):
        super().__init__(stage_id, partition)
        self.rdd = rdd
        self.func = func
        self.split = rdd.splits[partition]
        self.output_id = output_id

    def run(self, attempt_id):
        return self.func(self.rdd.iterator(self.split))

    def preferred_locations(self):
        return self.rdd.preferred_locations(self.split)

    def __repr__(self):
        return "<ResultTask(%d) of %r part%d>" % (
            self.id, self.rdd, self.partition)


class ShuffleMapTask(Task):
    def __init__(self, stage_id, rdd, shuffle_dep, partition):
        super().__init__(stage_id, partition)
        self.rdd = rdd
        self.shuffle_dep = shuffle_dep
        self.split = rdd.splits[partition]

    def run(self, attempt_id):
        dep = self.shuffle_dep
        # per-exchange code choice (ISSUE 19) travels on the dep and is
        # registered process-locally so write_buckets resolves it even
        # in a worker process that never saw the driver's registry
        spec = getattr(dep, "code_spec", None)
        if spec is not None:
            from dpark_tpu import coding
            coding.set_shuffle_code(dep.shuffle_id, spec)
        agg = dep.aggregator
        get_partition = dep.partitioner.get_partition
        n = dep.partitioner.num_partitions
        buckets = [{} for _ in range(n)]
        create, merge = agg.create_combiner, agg.merge_value
        from dpark_tpu.utils.memory import maybe_check
        i = 0
        # HOT LOOP (reference 3.1 #2): per-record hash + dict combine.  On
        # the TPU backend this loop is replaced by device-side
        # sort+segment_sum (backend/tpu/), this path serves local/process.
        for k, v in self.rdd.iterator(self.split):
            b = buckets[get_partition(k)]
            if k in b:
                b[k] = merge(b[k], v)
            else:
                b[k] = create(v)
            i += 1
            if not (i & 0x3FFF):
                maybe_check()        # RSS limit (process master policing)
        return LocalFileShuffle.write_buckets(
            dep.shuffle_id, self.partition, buckets)

    def preferred_locations(self):
        return self.rdd.preferred_locations(self.split)

    def __repr__(self):
        return "<ShuffleMapTask(%d) of %r part%d>" % (
            self.id, self.rdd, self.partition)
