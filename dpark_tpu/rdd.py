"""RDD graph: lazy, partitioned datasets and their ~50 transformations.

Reference parity: dpark/rdd.py — the RDD base class (six-method protocol:
splits / dependencies / compute / iterator / preferred_locations /
partitioner, SURVEY.md section 1), every narrow/wide/source/sink RDD type of
SURVEY.md section 2.2, and the action surface (collect/count/reduce/take/
saveAs*/...).

Design note (TPU): every compute() below is a Python generator — the object
path that the local/process masters run and the golden model for parity
tests.  The TPU backend does not call these; it records narrow chains as a
traceable op-IR and fuses them per stage into one jitted program
(backend/tpu/fuse.py).  compute() remains the semantic definition.
"""

import bz2 as _bz2
import contextlib
import csv as _csv
import gzip as _gzip
import heapq
import itertools
import os
import pickle
import random
import struct
import subprocess
from collections import Counter

from dpark_tpu import cache as _cache
from dpark_tpu.dependency import (
    Aggregator, CartesianDependency, HashPartitioner, OneToOneDependency,
    RangeDependency, RangePartitioner, SaltedHashPartitioner,
    ShuffleDependency)
from dpark_tpu.utils import atomic_file, user_call_site
from dpark_tpu.utils.log import get_logger

logger = get_logger("rdd")


class Split:
    def __init__(self, index):
        self.index = index


# --------------------------------------------------------------------------
# module-level helpers (picklable without closure shipping)
# --------------------------------------------------------------------------

def _fst(pair):
    return pair[0]


def _snd(pair):
    return pair[1]


def _identity(x):
    return x


def _mk_list(v):
    return [v]


def _append(l, v):
    l.append(v)
    return l


def _radd_zero(v):
    """sum()'s first accumulation step (0 + item): raises for exactly
    the value types sum() raises for — the group-aggregate rewrite
    must not widen what works (a string group must still TypeError)."""
    return 0 + v


def _one(v):
    return 1


def _count_merge(c, v):
    return c + 1


def _mean_create(v):
    return (0 + v, 1)


def _mean_merge_value(c, v):
    return (c[0] + v, c[1] + 1)


def _mean_merge(a, b):
    return (a[0] + b[0], a[1] + b[1])


def _mean_final(sc):
    return sc[0] / sc[1]


def _extend(l1, l2):
    l1.extend(l2)
    return l1


def _add(a, b):
    return a + b


def _keep_first(a, b):
    return a


class RDD:
    def __init__(self, ctx):
        self.ctx = ctx
        self.id = ctx.new_rdd_id()
        self._splits = None
        self.dependencies = []
        self.partitioner = None
        self.should_cache = False
        self._checkpoint_rdd = None
        self._checkpoint_path = None
        self.scope_name = "%s@%s" % (type(self).__name__, user_call_site())

    # -- the six-method protocol ----------------------------------------
    @property
    def splits(self):
        if self._checkpoint_rdd is None \
                and self._checkpoint_path is not None:
            # a marked-but-unpromoted checkpoint may have completed in
            # a previous job (or run): promote before planning
            if self._splits is None:
                self._splits = self._make_splits()
            self._maybe_promote_checkpoint()
        if self._checkpoint_rdd is not None:
            return self._checkpoint_rdd.splits
        if self._splits is None:
            self._splits = self._make_splits()
        return self._splits

    def _make_splits(self):
        raise NotImplementedError(
            "%s: splits unavailable (worker-side access?)" % type(self))

    def compute(self, split):
        raise NotImplementedError

    def iterator(self, split):
        if self._checkpoint_rdd is not None:
            return self._checkpoint_rdd.iterator(split)
        if self._checkpoint_path is not None:
            return self._checkpoint_iterator(split)
        if getattr(self, "_snapshot_path", None) is not None:
            return self._snapshot_iterator(split)
        if self.should_cache:
            return _cache.get_or_compute(self, split)
        return self.compute(split)

    def _checkpoint_iterator(self, split):
        """Lazy checkpoint (reference semantics, VERDICT r4 #8): each
        split materializes at its FIRST computation (atomic
        tmp+rename); once every part file exists the lineage truncates
        to a CheckpointRDD.  Until then a re-read of a written split
        comes from its file, never from recomputation."""
        path = os.path.join(self._checkpoint_path,
                            "part-%05d" % split.index)
        if os.path.exists(path):
            with open(path, "rb") as f:
                rows = pickle.load(f)
        else:
            if self.should_cache:
                rows = list(_cache.get_or_compute(self, split))
            else:
                rows = list(self.compute(split))
            from dpark_tpu import faults
            faults.hit("checkpoint.write")
            with atomic_file(path) as f:
                pickle.dump(rows, f, -1)
        self._maybe_promote_checkpoint()
        return iter(rows)

    def _maybe_promote_checkpoint(self):
        """Truncate lineage once every split's part file exists.  Safe
        mid-job: CheckpointRDD.compute maps foreign splits by index, so
        tasks planned before the promotion still read their files.

        DRIVER-ONLY in effect: a worker's deserialized copy has
        _splits stripped (__getstate__) and must not rebuild them
        (sources also strip their data, e.g. parallelize slices) — the
        driver promotes on its next splits access instead."""
        cp = self._checkpoint_path
        if cp is None or self._checkpoint_rdd is not None \
                or self._splits is None:
            return
        n = len(self._splits)
        try:
            files = {f for f in os.listdir(cp)
                     if f.startswith("part-") and not f.endswith(".tmp")}
        except OSError:
            return
        # exact-count match: a stale directory from a DIFFERENT split
        # layout must not silently supply data (review finding)
        if len(files) == n \
                and all(("part-%05d" % i) in files for i in range(n)):
            self._checkpoint_rdd = CheckpointRDD(self.ctx, cp)
            self.dependencies = []      # lineage truncation

    def _snapshot_iterator(self, split):
        """Read the split from its snapshot file, computing + writing it
        (atomic tmp+rename) on first touch.  Lineage stays intact —
        a vanished snapshot silently recomputes."""
        path = os.path.join(self._snapshot_path,
                            "part-%05d" % split.index)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return iter(pickle.load(f))
        rows = list(self.compute(split))
        from dpark_tpu import faults
        faults.hit("checkpoint.write")
        with atomic_file(path) as f:
            pickle.dump(rows, f, -1)
        return iter(rows)

    def preferred_locations(self, split):
        return []

    # -- serialization: splits stay driver-side; tasks carry their own
    #    split object (reference: dpark RDD.__getstate__)
    def __getstate__(self):
        d = dict(self.__dict__)
        d["_splits"] = None
        d["ctx"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)

    def __repr__(self):
        return "<%s %d>" % (type(self).__name__, self.id)

    def __len__(self):
        return len(self.splits)

    # ===================================================================
    # transformations (narrow)
    # ===================================================================
    def map(self, f):
        return MappedRDD(self, f)

    def flatMap(self, f):
        return FlatMappedRDD(self, f)

    def filter(self, f):
        return FilteredRDD(self, f)

    def glom(self):
        return GlommedRDD(self)

    def mapPartitions(self, f):
        return MapPartitionsRDD(self, f)

    mapPartition = mapPartitions

    def mapPartitionsWithIndex(self, f):
        return MapPartitionsRDD(self, f, with_index=True)

    mapPartitionWithIndex = mapPartitionsWithIndex

    def mapValue(self, f):
        rewritten = self._group_agg_rewrite(f)
        if rewritten is not None:
            return rewritten
        return MappedValuesRDD(self, f)

    mapValues = mapValue

    def _group_agg_rewrite(self, f):
        """groupByKey().mapValue(provable aggregate) -> combineByKey:
        the classic combiner optimization, applied at graph-build time
        so EVERY master benefits — map-side pre-aggregation cuts
        exchange volume to O(distinct keys) instead of shipping every
        row to its group (reference: what dpark users hand-write as
        combineByKey; SURVEY.md 3.1 combiner note).

        Applies only when `self` IS a bare groupByKey output (a
        no-combine hash ShuffledRDD — partitionBy's flat rows sit
        behind a FlatMappedValues(identity) and never reach here), the
        aggregate is provable (fuse.classify_segagg: sum/len/min/max/
        mean or a __dpark_segagg__ hint — NOT the np twins, which
        flatten array values), no cache/snapshot/checkpoint pins the
        grouped RDD, and the grouping's shuffle outputs do not already
        exist (then reuse beats re-scanning the parent).  A grouped RDD
        aggregated SEVERAL times rewrites each aggregate into its own
        combining shuffle — cache() the group to keep one shared
        grouping instead.  Error behavior is preserved:
        the sum rewrites start from ``0 + v`` exactly like sum()'s
        accumulator, so non-numeric values raise on every master the
        way they always did.  conf.GROUP_AGG_REWRITE=0 disables (the
        device SegAggOp path then serves these chains).

        FLOAT CAVEAT: the rewrite REASSOCIATES the fold.  sum/mean over
        float values pre-combine map-side and merge per partition, so
        the result's low-order bits depend on partitioning and combine
        order on EVERY master (including the local golden model) —
        where the un-rewritten chain summed each group's list in row
        order.  Integer aggregates and min/max are exact either way;
        float-exact reproduction of the reference's list-order sum
        needs GROUP_AGG_REWRITE=0."""
        from dpark_tpu import conf
        if not conf.GROUP_AGG_REWRITE:
            return None
        if not (isinstance(self, ShuffledRDD)
                and self.aggregator.create_combiner is _mk_list
                and self.aggregator.merge_value is _append
                and self.aggregator.merge_combiners is _extend
                and type(self.partitioner) is HashPartitioner
                and not self.should_cache
                and self._checkpoint_path is None
                and self._checkpoint_rdd is None
                and getattr(self, "_snapshot_path", None) is None):
            return None
        from dpark_tpu.env import env
        if env.map_output_tracker.get_outputs(
                self.dep.shuffle_id) is not None:
            # the grouping's map outputs already exist (an earlier job
            # computed this grouped RDD): reuse them instead of
            # re-scanning the parent through a fresh combining shuffle
            return None
        try:
            from dpark_tpu.backend.tpu.fuse import classify_segagg
        except Exception:
            return None
        # np.sum/np.mean/np.min/np.max are NOT rewrite-safe: np
        # flattens a list of array values where the pairwise builtins
        # work elementwise (or raise) — only the builtins, the bytecode
        # templates, and explicit hints rewrite (review finding).  The
        # builtins themselves ARE pairwise-equal for array values
        # (sum == chained +, min/max raise ambiguous-truth both ways).
        import numpy as _np
        try:
            if f in (_np.sum, _np.mean, _np.min, _np.max):
                return None
        except TypeError:
            return None
        kind = classify_segagg(f)
        if kind is None:
            return None
        # adaptive execution (ISSUE 7 decision point 4): the rewrite is
        # PRICED from the observed combine ratio of this grouping site
        # (distinct keys / input rows, recorded by every combining
        # shuffle write and by the segment path's bucket histogram).  A
        # ratio near 1 means nearly every key is distinct: map-side
        # pre-aggregation costs a combine pass and saves no exchange
        # bytes, so the rewrite is declined and the device SegAggOp
        # serves the chain — the PR-1 linter's `group-agg` advisory as
        # an actual optimizer choice.  Static default (no history, or
        # DPARK_ADAPT != on): rewrite.
        from dpark_tpu import adapt
        group_site = getattr(self.dep, "adapt_site", None)
        if not adapt.map_side_combine(group_site, kind):
            return None
        n = self.partitioner.num_partitions
        parent = self.parent
        if kind == "sum":
            rewritten = parent.combineByKey(_radd_zero, _add, _add, n)
        elif kind == "count":
            rewritten = parent.combineByKey(_one, _count_merge, _add, n)
        elif kind == "min":
            rewritten = parent.combineByKey(_identity, min, min, n)
        elif kind == "max":
            rewritten = parent.combineByKey(_identity, max, max, n)
        elif kind == "mean":
            rewritten = parent.combineByKey(
                _mean_create, _mean_merge_value, _mean_merge, n)
        else:
            return None
        # the combining shuffle's observed combine ratio must key back
        # to the GROUPING site the next pricing consults (the rewrite's
        # own combineByKey call resolves to the user's mapValue line)
        rewritten.dep.adapt_combine_site = group_site
        if kind == "mean":
            rewritten = rewritten.mapValue(_mean_final)
        return rewritten

    def flatMapValue(self, f):
        return FlatMappedValuesRDD(self, f)

    flatMapValues = flatMapValue

    def keyBy(self, f):
        return KeyedRDD(self, f)

    def pipe(self, command, quiet=True):
        return PipedRDD(self, command, quiet)

    def sample(self, withReplacement=False, fraction=0.1, seed=12345):
        return SampleRDD(self, withReplacement, fraction, seed)

    def union(self, *others):
        # flatten unions on BOTH sides: a.union(b).union(c) must build
        # one flat UnionRDD (nested unions defeat the array path's
        # union-source analysis, and flat is equivalent row-wise).
        # Never flatten THROUGH a checkpointed/snapshotted/cached union
        # — reading its .rdds would resurrect the truncated lineage
        def flat(r):
            if (isinstance(r, UnionRDD)
                    and r._checkpoint_rdd is None
                    and r._checkpoint_path is None
                    and getattr(r, "_snapshot_path", None) is None
                    and not r.should_cache):
                return list(r.rdds)
            return [r]
        rdds = flat(self)
        for o in others:
            rdds.extend(flat(o))
        return UnionRDD(self.ctx, rdds)

    def __add__(self, other):
        return self.union(other)

    def zip(self, other):
        return ZippedRDD(self.ctx, [self, other])

    def zipWithIndex(self):
        counts = list(self.ctx.runJob(self, _count_iter))
        offsets = [0]
        for c in counts[:-1]:
            offsets.append(offsets[-1] + c)
        return MapPartitionsRDD(self, _ZipWithIndexFn(offsets),
                                with_index=True)

    def cartesian(self, other):
        return CartesianRDD(self, other)

    def mergeSplit(self, splitSize=None, numSplits=None):
        """N:1 partition coalescing (reference: MergedRDD / mergeSplit)."""
        n = len(self.splits)
        if splitSize is None:
            splitSize = max(1, (n + (numSplits or 1) - 1) // (numSplits or 1))
        return MergedRDD(self, splitSize)

    coalesce = mergeSplit

    def distinct(self, numSplits=None):
        return (self.map(_pair_none)
                .reduceByKey(_keep_first, numSplits)
                .map(_fst))

    uniq = distinct

    def groupBy(self, f, numSplits=None):
        return self.keyBy(f).groupByKey(numSplits)

    # ===================================================================
    # transformations (wide — key/value)
    # ===================================================================
    def combineByKey(self, createCombiner, mergeValue, mergeCombiners,
                     numSplits=None):
        # adaptive execution (ISSUE 7): the grouping/combining call
        # site keys the persistent skew + combine-ratio observations,
        # and a caller that took the DEFAULT parallelism lets the
        # store widen the reduce side when the last recorded histogram
        # for this site showed one dominant key group.  An explicit
        # numSplits is never overridden, and outside DPARK_ADAPT=on
        # suggest_partitions returns the default unchanged.
        from dpark_tpu import adapt
        site = user_call_site() if adapt.enabled() else None
        if numSplits:
            numSplits = int(numSplits)
        else:
            numSplits = adapt.suggest_partitions(
                site, self.ctx.default_parallelism)
        agg = Aggregator(createCombiner, mergeValue, mergeCombiners)
        # mid-job re-plan memory (ISSUE 19): a site the scheduler
        # already re-keyed pre-salts at plan time, so the run-2 probe
        # finds a balanced histogram and skips the re-split stage
        salt = adapt.suggest_salt(site)
        if salt:
            part = SaltedHashPartitioner(numSplits, salt)
        else:
            part = HashPartitioner(numSplits)
        shuffled = ShuffledRDD(self, agg, part)
        shuffled.dep.adapt_site = site
        return shuffled

    def reduceByKey(self, func, numSplits=None):
        return self.combineByKey(_identity, func, func, numSplits)

    def groupByKey(self, numSplits=None):
        return self.combineByKey(_mk_list, _append, _extend, numSplits)

    def partitionBy(self, partitioner):
        """Repartition preserving duplicate keys; output partitioner is
        retained so later cogroups are narrow."""
        if isinstance(partitioner, int):
            partitioner = HashPartitioner(partitioner)
        if self.partitioner == partitioner:
            return self
        agg = Aggregator(_mk_list, _append, _extend)
        shuffled = ShuffledRDD(self, agg, partitioner)
        return FlatMappedValuesRDD(shuffled, _identity)

    def sort(self, key=None, reverse=False, numSplits=None):
        """Sort arbitrary records by key function (reference: rdd.sort)."""
        keyed = self.keyBy(key) if key else self.map(_pair_self)
        s = keyed.sortByKey(ascending=not reverse, numSplits=numSplits)
        return s.map(_snd)

    def sortByKey(self, ascending=True, numSplits=None, sampleSize=2000):
        numSplits = numSplits or len(self.splits)
        if len(self.splits) <= 1:
            return self.mapPartitions(
                _SortPartFn(ascending))
        per_part = max(20, sampleSize // max(1, len(self.splits)))
        sampled = []
        for part in self.ctx.runJob(
                self, _TakeSampleKeys(per_part)):
            sampled.extend(part)
        sampled.sort()
        bounds = [sampled[len(sampled) * (i + 1) // numSplits]
                  for i in range(numSplits - 1)] if sampled else []
        # dedup bounds (heavy skew collapses ranges)
        bounds = sorted(set(bounds))
        part = RangePartitioner(bounds, ascending=ascending)
        repartitioned = self.partitionBy(part)
        return repartitioned.mapPartitions(_SortPartFn(ascending))

    def cogroup(self, *others, **kw):
        numSplits = kw.get("numSplits") or self.ctx.default_parallelism
        rdds = [self] + list(others)
        for p in [r.partitioner for r in rdds]:
            if p is not None and p.num_partitions >= numSplits:
                partitioner = p
                break
        else:
            partitioner = HashPartitioner(numSplits)
        return CoGroupedRDD(rdds, partitioner)

    groupWith = cogroup

    def join(self, other, numSplits=None):
        return self.cogroup(other, numSplits=numSplits).flatMapValue(
            _join_values)

    def leftOuterJoin(self, other, numSplits=None):
        return self.cogroup(other, numSplits=numSplits).flatMapValue(
            _left_join_values)

    def rightOuterJoin(self, other, numSplits=None):
        return self.cogroup(other, numSplits=numSplits).flatMapValue(
            _right_join_values)

    def outerJoin(self, other, numSplits=None):
        return self.cogroup(other, numSplits=numSplits).flatMapValue(
            _outer_join_values)

    innerJoin = join

    # ===================================================================
    # caching / checkpoint
    # ===================================================================
    def cache(self):
        self.should_cache = True
        return self

    persist = cache

    def unpersist(self):
        self.should_cache = False
        from dpark_tpu.env import env
        if env.cache is not None and self._splits is not None:
            env.cache.drop(self.id, len(self._splits))
        for drop in list(_cache.DEVICE_CACHES.values()):
            drop(self.id)
        return self

    def snapshot(self, path=None):
        """Disk-materialize each partition at FIRST computation and read
        it back on every later one — checkpoint's little sibling
        (reference: dpark/rdd.py RDD.snapshot [L], SURVEY.md section
        2.2): no lineage truncation, no eager job; a snapshot directory
        that survives across runs short-circuits recomputation, and a
        vanished one silently recomputes from lineage."""
        if getattr(self, "_snapshot_path", None) is not None:
            return self
        if path is None:
            base = self.ctx.checkpoint_dir
            if base is None:
                raise ValueError("no snapshot dir: pass path or call "
                                 "ctx.setCheckpointDir")
            path = os.path.join(base, "snapshot-rdd-%d" % self.id)
        os.makedirs(path, exist_ok=True)
        self._snapshot_path = path
        return self

    def checkpoint(self, path=None):
        """Mark for checkpoint: NO job runs now (reference semantics,
        dpark/rdd.py checkpoint [M]; rounds 1-4 materialized eagerly at
        call time).  Each split materializes at its first computation,
        and once every part file exists the lineage truncates to a
        CheckpointRDD.  A checkpoint directory that survives across
        runs short-circuits recomputation entirely.  snapshot() is the
        eager-read/no-truncation sibling."""
        if self._checkpoint_rdd is not None \
                or self._checkpoint_path is not None:
            return self
        if path is None:
            base = self.ctx.checkpoint_dir
            if base is None:
                raise ValueError("no checkpoint dir: pass path or call "
                                 "ctx.setCheckpointDir")
            path = os.path.join(base, "rdd-%d" % self.id)
        os.makedirs(path, exist_ok=True)
        # provenance marker: reusing a directory written for a
        # DIFFERENT split layout would silently serve wrong data —
        # wipe incompatible part files instead (review finding)
        n = len(self.splits)
        marker = os.path.join(path, "nparts")
        existing = None
        try:
            with open(marker) as f:
                existing = int(f.read().strip())
        except (OSError, ValueError):
            pass
        parts = [f for f in os.listdir(path) if f.startswith("part-")]
        if parts and existing != n:
            from dpark_tpu.utils.log import get_logger
            logger = get_logger("rdd")
            logger.warning(
                "checkpoint dir %s holds %s-split data (this RDD has "
                "%d): discarding the stale parts", path, existing, n)
            for f in parts:
                try:
                    os.unlink(os.path.join(path, f))
                except OSError:
                    pass
        if existing != n:
            with atomic_file(marker, "wb") as f:
                f.write(str(n).encode())
        self._checkpoint_path = path
        self._maybe_promote_checkpoint()    # surviving full directory
        return self

    # ===================================================================
    # actions
    # ===================================================================
    def collect(self):
        return list(itertools.chain.from_iterable(
            self.ctx.runJob(self, _listify)))

    def collectAsMap(self):
        return dict(itertools.chain.from_iterable(
            self.ctx.runJob(self, _listify)))

    def iterate(self):
        """Stream results partition-by-partition without materializing all
        (generator action)."""
        for part in self.ctx.runJob(self, _listify):
            yield from part

    def count(self):
        return sum(self.ctx.runJob(self, _count_iter))

    def reduce(self, f):
        parts = [r for r in self.ctx.runJob(self, _PartReduce(f))
                 if r is not _EMPTY]
        if not parts:
            raise ValueError("reduce of empty RDD")
        out = parts[0]
        for p in parts[1:]:
            out = f(out, p)
        return out

    def fold(self, zero, f):
        out = zero
        for p in self.ctx.runJob(self, _PartFold(zero, f)):
            out = f(out, p)
        return out

    def aggregate(self, zero, seqOp, combOp):
        out = zero
        for p in self.ctx.runJob(self, _PartAggregate(zero, seqOp)):
            out = combOp(out, p)
        return out

    def sum(self):
        return sum(self.ctx.runJob(self, _sum_iter))

    def take(self, n):
        if n <= 0:
            return []
        out = []
        nsplits = len(self.splits)
        p = 0
        while len(out) < n and p < nsplits:
            # geometric ramp-up of partitions per round (reference: take)
            batch = list(range(p, min(nsplits, p + max(1, p))))
            need = n - len(out)
            for part in self.ctx.runJob(self, _TakeN(need), batch,
                                        allow_local=(p == 0)):
                out.extend(part[:n - len(out)])
                if len(out) >= n:
                    break
            p = batch[-1] + 1
        return out

    def first(self):
        items = self.take(1)
        if not items:
            raise ValueError("empty RDD")
        return items[0]

    def top(self, n=10, key=None, reverse=False):
        parts = list(self.ctx.runJob(
            self, _TopN(n, key, smallest=reverse)))
        allv = list(itertools.chain.from_iterable(parts))
        if reverse:
            return heapq.nsmallest(n, allv, key)
        return heapq.nlargest(n, allv, key)

    def hot(self, n=10, numSplits=None):
        """Top-n (value, count) pairs (reference: rdd.hot via HotCounter)."""
        return (self.map(_pair_one)
                .reduceByKey(_add, numSplits)
                .top(n, key=_snd))

    def countByValue(self):
        out = Counter()
        for c in self.ctx.runJob(self, _count_by_value):
            out.update(c)
        return dict(out)

    def countByKey(self):
        return self.map(_fst).countByValue()

    def lookup(self, key):
        if self.partitioner is not None:
            pid = self.partitioner.get_partition(key)
            results = list(self.ctx.runJob(
                self, _LookupKey(key), [pid], allow_local=True))
            return results[0] if results else []
        return self.filter(_KeyEquals(key)).map(_snd).collect()

    def foreach(self, f):
        for _ in self.ctx.runJob(self, _ForeachFn(f)):
            pass

    def foreachPartition(self, f):
        for _ in self.ctx.runJob(self, f):
            pass

    def enumeratePartition(self):
        return self.mapPartitionsWithIndex(_enum_partition)

    # -- output sinks ----------------------------------------------------
    def saveAsTextFile(self, path, ext="", overwrite=True, compress=False):
        return OutputTextFileRDD(self, path, ext, overwrite,
                                 compress).collect()

    def saveAsTextFileByKey(self, path, ext="", overwrite=True):
        """Records are (key, line); each key gets its own subdirectory
        (reference: MultiOutputTextFileRDD)."""
        return MultiOutputTextFileRDD(self, path, overwrite, ext).collect()

    def saveAsCSVFile(self, path, overwrite=True, dialect="excel"):
        return OutputCSVFileRDD(self, path, overwrite, dialect).collect()

    def saveAsBinaryFile(self, path, fmt, overwrite=True):
        return OutputBinaryFileRDD(self, path, fmt, overwrite).collect()

    def saveAsPickleFile(self, path, overwrite=True):
        return OutputPickleFileRDD(self, path, overwrite).collect()

    def saveAsTableFile(self, path, overwrite=True):
        return OutputPickleFileRDD(self, path, overwrite).collect()

    def saveAsBeansdb(self, path, overwrite=True):
        """Write (key, value) pairs as beansdb .data files (reference:
        saveAsBeansdb, dpark/utils/beansdb.py)."""
        from dpark_tpu.beansdb import OutputBeansdbRDD
        return OutputBeansdbRDD(self, path, overwrite).collect()

    def saveAsTabular(self, path, fields, overwrite=True):
        """Write tuple rows as the columnar tabular format (reference:
        OutputTabularRDD, dpark/tabular.py)."""
        from dpark_tpu.tabular import OutputTabularRDD
        return OutputTabularRDD(self, path, fields, overwrite).collect()

    def asTable(self, fields, name="table"):
        """Wrap this RDD of tuples as a schema'd TableRDD (reference:
        rdd.asTable, dpark/table.py)."""
        from dpark_tpu.table import TableRDD
        return TableRDD(self, fields, name)

    def adcount(self, p=12):
        """Approximate distinct count via HyperLogLog merge."""
        from dpark_tpu.hyperloglog import HyperLogLog
        parts = self.ctx.runJob(self, _HLLPartition(p))
        h = HyperLogLog(p)
        for part in parts:
            if part is not None:
                h.update(part)
        return len(h)


_EMPTY = object()


# --------------------------------------------------------------------------
# picklable per-partition functors used by actions
# --------------------------------------------------------------------------

def _listify(it):
    return list(it)


def _count_iter(it):
    n = 0
    for _ in it:
        n += 1
    return n


def _sum_iter(it):
    return sum(it)


def _count_by_value(it):
    return Counter(it)


def _pair_none(x):
    return (x, None)


def _pair_one(x):
    return (x, 1)


def _pair_self(x):
    return (x, x)


def _enum_partition(i, it):
    for x in it:
        yield (i, x)


def _join_values(groups):
    a, b = groups
    return [(x, y) for x in a for y in b]


def _left_join_values(groups):
    a, b = groups
    return [(x, y) for x in a for y in (b or [None])]


def _right_join_values(groups):
    a, b = groups
    return [(x, y) for x in (a or [None]) for y in b]


def _outer_join_values(groups):
    a, b = groups
    return [(x, y) for x in (a or [None]) for y in (b or [None])]


class _PartReduce:
    def __init__(self, f):
        self.f = f

    def __call__(self, it):
        out = _EMPTY
        for x in it:
            out = x if out is _EMPTY else self.f(out, x)
        return out


class _PartFold:
    def __init__(self, zero, f):
        self.zero = zero
        self.f = f

    def __call__(self, it):
        out = pickle.loads(pickle.dumps(self.zero, -1))
        for x in it:
            out = self.f(out, x)
        return out


class _PartAggregate(_PartFold):
    pass


class _TakeN:
    def __init__(self, n):
        self.n = n

    def __call__(self, it):
        return list(itertools.islice(it, self.n))


class _TopN:
    def __init__(self, n, key, smallest=False):
        self.n = n
        self.key = key
        self.smallest = smallest

    def __call__(self, it):
        if self.smallest:
            return heapq.nsmallest(self.n, it, self.key)
        return heapq.nlargest(self.n, it, self.key)


class _LookupKey:
    def __init__(self, key):
        self.key = key

    def __call__(self, it):
        return [v for k, v in it if k == self.key]


class _KeyEquals:
    def __init__(self, key):
        self.key = key

    def __call__(self, kv):
        return kv[0] == self.key


class _ForeachFn:
    def __init__(self, f):
        self.f = f

    def __call__(self, it):
        for x in it:
            self.f(x)


class _SortPartFn:
    def __init__(self, ascending):
        self.ascending = ascending

    def __call__(self, it):
        return iter(sorted(it, key=_fst, reverse=not self.ascending))


class _TakeSampleKeys:
    def __init__(self, n):
        self.n = n

    def __call__(self, it):
        return [k for k, _ in itertools.islice(it, self.n)]


class _ZipWithIndexFn:
    def __init__(self, offsets):
        self.offsets = offsets

    def __call__(self, i, it):
        return ((x, j) for j, x in enumerate(it, self.offsets[i]))


class _HLLPartition:
    def __init__(self, p):
        self.p = p

    def __call__(self, it):
        from dpark_tpu.hyperloglog import HyperLogLog
        h = HyperLogLog(self.p)
        for x in it:
            h.add(x)
        return h


# --------------------------------------------------------------------------
# narrow RDDs
# --------------------------------------------------------------------------

class DerivedRDD(RDD):
    """One-parent narrow RDD; shares the parent's splits."""

    def __init__(self, prev):
        super().__init__(prev.ctx)
        self.prev = prev
        self.dependencies = [OneToOneDependency(prev)]

    def _make_splits(self):
        return self.prev.splits

    def preferred_locations(self, split):
        return self.prev.preferred_locations(split)


class MappedRDD(DerivedRDD):
    def __init__(self, prev, f):
        super().__init__(prev)
        self.f = f

    def compute(self, split):
        return map(self.f, self.prev.iterator(split))


class FlatMappedRDD(DerivedRDD):
    def __init__(self, prev, f):
        super().__init__(prev)
        self.f = f

    def compute(self, split):
        for x in self.prev.iterator(split):
            yield from self.f(x)


class FilteredRDD(DerivedRDD):
    def __init__(self, prev, f):
        super().__init__(prev)
        self.f = f

    def compute(self, split):
        return filter(self.f, self.prev.iterator(split))


class GlommedRDD(DerivedRDD):
    def compute(self, split):
        yield list(self.prev.iterator(split))


class MapPartitionsRDD(DerivedRDD):
    def __init__(self, prev, f, with_index=False):
        super().__init__(prev)
        self.f = f
        self.with_index = with_index

    def compute(self, split):
        if self.with_index:
            return self.f(split.index, self.prev.iterator(split))
        return self.f(self.prev.iterator(split))


class MappedValuesRDD(DerivedRDD):
    def __init__(self, prev, f):
        super().__init__(prev)
        self.f = f
        self.partitioner = prev.partitioner

    def compute(self, split):
        f = self.f
        return ((k, f(v)) for k, v in self.prev.iterator(split))


class FlatMappedValuesRDD(DerivedRDD):
    def __init__(self, prev, f):
        super().__init__(prev)
        self.f = f
        self.partitioner = prev.partitioner

    def compute(self, split):
        for k, v in self.prev.iterator(split):
            for vv in self.f(v):
                yield (k, vv)


class KeyedRDD(DerivedRDD):
    def __init__(self, prev, f):
        super().__init__(prev)
        self.f = f

    def compute(self, split):
        f = self.f
        return ((f(x), x) for x in self.prev.iterator(split))


class PipedRDD(DerivedRDD):
    """Bridge each partition through a shell command's stdin/stdout
    (reference: PipedRDD)."""

    def __init__(self, prev, command, quiet=True):
        super().__init__(prev)
        self.command = command
        self.quiet = quiet

    def compute(self, split):
        cmd = self.command
        shell = isinstance(cmd, str)
        proc = subprocess.Popen(
            cmd, shell=shell, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if self.quiet else None)

        import threading

        def feed():
            try:
                for line in self.prev.iterator(split):
                    if not isinstance(line, (bytes, bytearray)):
                        line = str(line).encode()
                    if not line.endswith(b"\n"):
                        line += b"\n"
                    proc.stdin.write(line)
                proc.stdin.close()
            except (BrokenPipeError, ValueError):
                pass

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        try:
            for line in proc.stdout:
                yield line.rstrip(b"\n").decode("utf-8", "replace")
            rc = proc.wait()
            if rc != 0:
                raise RuntimeError("piped command %r exited with %d"
                                   % (cmd, rc))
            t.join()
        finally:
            # abandoned generator (e.g. take): reap the child and unblock
            # the feeder regardless of how far the consumer read
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            try:
                proc.stdin.close()
            except OSError:
                pass
            proc.stdout.close()


class SampleRDD(DerivedRDD):
    def __init__(self, prev, withReplacement, fraction, seed):
        super().__init__(prev)
        self.withReplacement = withReplacement
        self.fraction = fraction
        self.seed = seed

    def compute(self, split):
        rng = random.Random(self.seed ^ split.index)
        if self.withReplacement:
            items = list(self.prev.iterator(split))
            n = int(len(items) * self.fraction + 0.5)
            for _ in range(n):
                yield rng.choice(items) if items else None
        else:
            frac = self.fraction
            for x in self.prev.iterator(split):
                if rng.random() < frac:
                    yield x


class UnionSplit(Split):
    def __init__(self, index, rdd_index, parent_split):
        super().__init__(index)
        self.rdd_index = rdd_index
        self.parent_split = parent_split


class UnionRDD(RDD):
    def __init__(self, ctx, rdds):
        super().__init__(ctx)
        self.rdds = rdds
        pos = 0
        for r in rdds:
            self.dependencies.append(
                RangeDependency(r, 0, pos, len(r.splits)))
            pos += len(r.splits)

    def _make_splits(self):
        out = []
        for ri, r in enumerate(self.rdds):
            for sp in r.splits:
                out.append(UnionSplit(len(out), ri, sp))
        return out

    def compute(self, split):
        return self.rdds[split.rdd_index].iterator(split.parent_split)

    def preferred_locations(self, split):
        return self.rdds[split.rdd_index].preferred_locations(
            split.parent_split)


class SliceSplit(Split):
    def __init__(self, index, parent_split):
        super().__init__(index)
        self.parent_split = parent_split


class SliceRDD(RDD):
    """A contiguous subset of the parent's partitions (backs take)."""

    def __init__(self, prev, start, end):
        super().__init__(prev.ctx)
        self.prev = prev
        self.start = start
        self.end = end
        self.dependencies = [RangeDependency(prev, start, 0, end - start)]

    def _make_splits(self):
        return [SliceSplit(i, sp) for i, sp in
                enumerate(self.prev.splits[self.start:self.end])]

    def compute(self, split):
        return self.prev.iterator(split.parent_split)


class MergedSplit(Split):
    def __init__(self, index, parent_splits):
        super().__init__(index)
        self.parent_splits = parent_splits


class MergedRDD(RDD):
    """Coalesce `split_size` parent partitions into one (no shuffle)."""

    def __init__(self, prev, split_size):
        super().__init__(prev.ctx)
        self.prev = prev
        self.split_size = split_size
        n = len(prev.splits)
        self._n_out = (n + split_size - 1) // split_size
        self.dependencies = [_MergedDependency(prev, split_size, n)]

    def _make_splits(self):
        ss = self.split_size
        ps = self.prev.splits
        return [MergedSplit(i, ps[i * ss:(i + 1) * ss])
                for i in range(self._n_out)]

    def compute(self, split):
        for sp in split.parent_splits:
            yield from self.prev.iterator(sp)


class _MergedDependency(RangeDependency):
    def __init__(self, rdd, split_size, n_parent):
        super().__init__(rdd, 0, 0, n_parent)
        self.split_size = split_size

    def get_parents(self, pid):
        return list(range(pid * self.split_size,
                          min((pid + 1) * self.split_size, self.length)))


class ZippedSplit(Split):
    def __init__(self, index, parent_splits):
        super().__init__(index)
        self.parent_splits = parent_splits


class ZippedRDD(RDD):
    def __init__(self, ctx, rdds):
        if len({len(r.splits) for r in rdds}) != 1:
            raise ValueError("zip: all RDDs must have the same number of "
                             "splits")
        super().__init__(ctx)
        self.rdds = rdds
        self.dependencies = [OneToOneDependency(r) for r in rdds]

    def _make_splits(self):
        return [ZippedSplit(i, [r.splits[i] for r in self.rdds])
                for i in range(len(self.rdds[0].splits))]

    def compute(self, split):
        return zip(*[r.iterator(sp)
                     for r, sp in zip(self.rdds, split.parent_splits)])


class CartesianSplit(Split):
    def __init__(self, index, s1, s2):
        super().__init__(index)
        self.s1 = s1
        self.s2 = s2


class CartesianRDD(RDD):
    def __init__(self, rdd1, rdd2):
        super().__init__(rdd1.ctx)
        self.rdd1 = rdd1
        self.rdd2 = rdd2
        self.n2 = len(rdd2.splits)
        self.dependencies = [CartesianDependency(rdd1, 0, self.n2),
                             CartesianDependency(rdd2, 1, self.n2)]

    def _make_splits(self):
        out = []
        for s1 in self.rdd1.splits:
            for s2 in self.rdd2.splits:
                out.append(CartesianSplit(len(out), s1, s2))
        return out

    def compute(self, split):
        right = list(self.rdd2.iterator(split.s2))
        for x in self.rdd1.iterator(split.s1):
            for y in right:
                yield (x, y)


# --------------------------------------------------------------------------
# wide RDDs
# --------------------------------------------------------------------------

class ShuffledSplit(Split):
    pass


class ShuffledRDD(RDD):
    """Reduce side of a hash shuffle (reference: ShuffledRDD).  compute()
    fetches every map output bucket for its partition and merges combiners;
    the TPU backend replaces this with all_to_all + segment-reduce."""

    def __init__(self, parent, aggregator, partitioner):
        super().__init__(parent.ctx)
        self.parent = parent
        self.aggregator = aggregator
        self.partitioner = partitioner
        self.dep = ShuffleDependency(parent, aggregator, partitioner)
        self.dependencies = [self.dep]

    def _make_splits(self):
        return [ShuffledSplit(i)
                for i in range(self.partitioner.num_partitions)]

    def compute(self, split):
        from dpark_tpu import coding, conf
        from dpark_tpu.env import env
        from dpark_tpu.shuffle import DiskSpillMerger, SortMerger
        # the per-exchange code choice travels on the dep (ISSUE 19) —
        # register before fetching so the reader and the writer agree
        spec = getattr(self.dep, "code_spec", None)
        if spec is not None:
            coding.set_shuffle_code(self.dep.shuffle_id, spec)
        if conf.SORT_SHUFFLE:
            merger = SortMerger(self.aggregator)
        else:
            # shuffle/reduce tags route a corrupted-spill FetchFailed
            # back through lineage recovery (see DiskSpillMerger)
            merger = DiskSpillMerger(self.aggregator,
                                     shuffle_id=self.dep.shuffle_id,
                                     reduce_id=split.index)
        env.shuffle_fetcher.fetch(self.dep.shuffle_id, split.index,
                                  merger.merge)
        return iter(merger)


class CoGroupSplit(Split):
    def __init__(self, index, narrow_splits):
        super().__init__(index)
        # narrow_splits: list of (src_index, parent_split) for co-partitioned
        # parents; shuffled parents are identified by dep order
        self.narrow_splits = narrow_splits


class CoGroupedRDD(RDD):
    """key -> tuple of value-lists, one per parent (reference:
    CoGroupedRDD + CoGroupSplit; backs cogroup/join/groupWith)."""

    def __init__(self, rdds, partitioner):
        super().__init__(rdds[0].ctx)
        self.rdds = rdds
        self.partitioner = partitioner
        self._dep_kinds = []        # ("narrow", rdd) | ("shuffle", dep)
        agg = Aggregator(_mk_list, _append, _extend)
        for r in rdds:
            if r.partitioner == partitioner:
                self.dependencies.append(OneToOneDependency(r))
                self._dep_kinds.append(("narrow", r))
            else:
                dep = ShuffleDependency(r, agg, partitioner)
                self.dependencies.append(dep)
                self._dep_kinds.append(("shuffle", dep))

    def _make_splits(self):
        out = []
        for i in range(self.partitioner.num_partitions):
            narrow = []
            for si, (kind, obj) in enumerate(self._dep_kinds):
                if kind == "narrow":
                    narrow.append((si, obj.splits[i]))
            out.append(CoGroupSplit(i, narrow))
        return out

    def compute(self, split):
        from dpark_tpu import coding
        from dpark_tpu.env import env
        from dpark_tpu.shuffle import CoGroupMerger
        merger = CoGroupMerger(len(self.rdds))
        narrow = dict((si, sp) for si, sp in split.narrow_splits)
        for si, (kind, obj) in enumerate(self._dep_kinds):
            if kind == "narrow":
                merger.append(si, self.rdds[si].iterator(narrow[si]))
            else:
                spec = getattr(obj, "code_spec", None)
                if spec is not None:
                    coding.set_shuffle_code(obj.shuffle_id, spec)
                env.shuffle_fetcher.fetch(
                    obj.shuffle_id, split.index,
                    _CoGroupExtend(merger, si))
        return iter(merger)


class _CoGroupExtend:
    def __init__(self, merger, si):
        self.merger = merger
        self.si = si

    def __call__(self, items):
        self.merger.extend(self.si, items)


class _ResplitSplit(Split):
    pass


class ResplitReaderRDD(RDD):
    """Mid-job re-plan bridge (ISSUE 19): reads the already-written
    buckets of a finished shuffle map stage, one split per
    (map_id, old_reduce_id) pair, so a skewed exchange can be re-keyed
    through a second (salted) shuffle WITHOUT recomputing a single map
    task.  Splits are map-id-major — the downstream passthrough
    aggregator then merges each key's combiners in map-id order,
    byte-identical to what the original reduce side would have built.

    Dependencies carry the ORIGINAL ShuffleDependency: the DAG
    scheduler wires the finished map stage as this stage's parent (a
    no-op while its outputs live), and a missing bucket surfaces as a
    plain FetchFailed that lineage recovery resubmits upstream —
    re-planning adds no new failure modes."""

    def __init__(self, src_dep):
        super().__init__(src_dep.rdd.ctx)
        self.src_dep = src_dep
        self.n_src_maps = len(src_dep.rdd.splits)
        self.n_src_reduces = src_dep.partitioner.num_partitions
        self.dependencies = [src_dep]

    def _make_splits(self):
        return [_ResplitSplit(i)
                for i in range(self.n_src_maps * self.n_src_reduces)]

    def compute(self, split):
        from dpark_tpu.env import env
        from dpark_tpu.shuffle import FetchFailed, read_bucket_any
        map_id = split.index // self.n_src_reduces
        reduce_id = split.index % self.n_src_reduces
        sid = self.src_dep.shuffle_id
        locs = env.map_output_tracker.get_outputs(sid)
        uri = locs[map_id] if locs else None
        if uri is None:
            raise FetchFailed(None, sid, map_id, reduce_id)
        return iter(read_bucket_any(uri, sid, map_id, reduce_id))

    def __repr__(self):
        return "<ResplitReaderRDD of shuffle %d>" % \
            self.src_dep.shuffle_id


# --------------------------------------------------------------------------
# source RDDs
# --------------------------------------------------------------------------

class ParallelSplit(Split):
    def __init__(self, index, values):
        super().__init__(index)
        self.values = values


class _ColumnarSlice:
    """One partition's data held as numpy column arrays (zero-copy ingest
    to the device path; row tuples materialize lazily on the object path).
    """

    def __init__(self, columns):
        self.columns = columns

    def __len__(self):
        return len(self.columns[0]) if self.columns else 0

    def __bool__(self):
        return len(self) > 0

    def __getitem__(self, i):
        row = tuple(c[i] for c in self.columns)
        return row[0] if len(row) == 1 else row

    def __iter__(self):
        # tolist() in bounded chunks: a take/sample over a huge column
        # must not materialize the whole slice as Python objects
        chunk = 1 << 16
        n = len(self)
        if len(self.columns) == 1:
            col = self.columns[0]
            for off in range(0, n, chunk):
                yield from col[off:off + chunk].tolist()
            return
        for off in range(0, n, chunk):
            yield from zip(*(c[off:off + chunk].tolist()
                             for c in self.columns))


class Columns:
    """Explicit columnar input marker for parallelize: each argument is
    one column array; records are row tuples across the columns.

        ctx.parallelize(Columns(keys, values), n)

    Explicit so ordinary parallelize semantics (a 2D array = rows of
    arrays; a list of arrays = RDD of array elements) stay untouched."""

    def __init__(self, *arrays):
        import numpy as _np
        self.arrays = [
            _np.ascontiguousarray(a) for a in arrays]
        if not self.arrays:
            raise ValueError("Columns needs at least one array")
        if any(a.ndim != 1 for a in self.arrays):
            raise ValueError("Columns arrays must be 1-D")
        if len({len(a) for a in self.arrays}) != 1:
            raise ValueError("Columns arrays must have equal length")


def _as_columns(seq):
    """Columnar input only via the explicit Columns marker (plus a bare
    1-D numpy array, whose row semantics are identical either way)."""
    import numpy as _np
    if isinstance(seq, Columns):
        return list(seq.arrays)
    if isinstance(seq, _np.ndarray) and seq.ndim == 1:
        return [seq]
    return None


class ParallelCollection(RDD):
    """In-memory sequence split into `num_slices` (reference:
    ParallelCollection from ctx.parallelize).

    TPU-native extension: numpy input (a 2D array, or a tuple of 1D
    column arrays) is kept columnar — the tpu master ingests it into HBM
    without materializing Python row objects."""

    def __init__(self, ctx, seq, num_slices=None):
        super().__init__(ctx)
        cols = _as_columns(seq)
        if cols is not None:
            total = len(cols[0])
            n = num_slices or ctx.default_parallelism
            n = max(1, min(n, total) if total else 1)
            self._slices = [
                _ColumnarSlice([c[total * i // n: total * (i + 1) // n]
                                for c in cols])
                for i in range(n)]
            return
        seq = list(seq)
        n = num_slices or ctx.default_parallelism
        n = max(1, min(n, len(seq)) if seq else 1)
        self._slices = [seq[len(seq) * i // n: len(seq) * (i + 1) // n]
                        for i in range(n)]

    def _make_splits(self):
        return [ParallelSplit(i, s) for i, s in enumerate(self._slices)]

    def __getstate__(self):
        d = super().__getstate__()
        d["_slices"] = None         # data rides in each task's split
        return d

    def compute(self, split):
        return iter(split.values)


class TextSplit(Split):
    def __init__(self, index, path, begin, end):
        super().__init__(index)
        self.path = path
        self.begin = begin
        self.end = end


DEFAULT_BLOCK = 64 << 20


class TextFileRDD(RDD):
    """Newline-aligned byte-range splits of one file or a directory tree
    (reference: TextFileRDD, 64MB blocks)."""

    def __init__(self, ctx, path, numSplits=None, splitSize=None):
        super().__init__(ctx)
        self.path = path
        files = self._expand(path)
        total = sum(sz for _, sz in files)
        if splitSize is None:
            if numSplits:
                splitSize = max(1, total // numSplits) or 1
            else:
                splitSize = DEFAULT_BLOCK
        self._file_splits = []
        for p, sz in files:
            off = 0
            while off < sz or (sz == 0 and off == 0):
                end = min(off + splitSize, sz)
                self._file_splits.append((p, off, end))
                off = end
                if sz == 0:
                    break

    @staticmethod
    def _expand(path):
        """Walk via the file_manager layer so DFS schemes (the MooseFS
        analog) plug in transparently (SURVEY.md section 2.4)."""
        from dpark_tpu import file_manager
        return list(file_manager.walk(path))

    def _make_splits(self):
        return [TextSplit(i, p, b, e)
                for i, (p, b, e) in enumerate(self._file_splits)]

    def preferred_locations(self, split):
        from dpark_tpu import file_manager
        return file_manager.locations(split.path, split.begin,
                                      split.end - split.begin)

    def compute(self, split):
        from dpark_tpu import file_manager
        with file_manager.open_file(split.path) as f:
            if split.begin > 0:
                f.seek(split.begin - 1)
                byte = f.read(1)
                if byte != b"\n":
                    f.readline()        # skip the partial first line
            while f.tell() <= split.end:
                line = f.readline()
                if not line:
                    break
                start = f.tell() - len(line)
                if start >= split.end:
                    break
                yield line.rstrip(b"\r\n").decode("utf-8", "replace")


class PartialTextFileRDD(TextFileRDD):
    """Byte-range restricted text file (reference: partialTextFile)."""

    def __init__(self, ctx, path, begin, end, splitSize=None):
        RDD.__init__(self, ctx)
        self.path = path
        splitSize = splitSize or DEFAULT_BLOCK
        self._file_splits = []
        off = begin
        while off < end:
            e = min(off + splitSize, end)
            self._file_splits.append((path, off, e))
            off = e


class WholeFileSplit(Split):
    def __init__(self, index, path):
        super().__init__(index)
        self.path = path


def _scan_magic_offsets(path, prefix, magic_at, validate):
    """Byte offsets of validated stream/member starts inside one file.

    `prefix` narrows candidates cheaply; magic_at(buf, j) -> bool checks
    the full magic at buf[j:]; validate(path, off) -> bool confirms by
    test-decompressing a prefix.  Used for intra-file gzip member and
    bz2 stream splitting (reference: GZipFileRDD/BZip2FileRDD scan
    compressed block magics [M], SURVEY.md section 2.2)."""
    from dpark_tpu import file_manager
    offsets = [0]
    candidates = []
    chunk_size = 4 << 20
    overlap = 16
    with file_manager.open_file(path) as f:
        pos = 0
        tail = b""
        while True:
            data = f.read(chunk_size)
            if not data:
                break
            buf = tail + data
            base = pos - len(tail)
            j = 0
            while True:
                j = buf.find(prefix, j)
                if j < 0 or j > len(buf) - overlap:
                    break
                off = base + j
                if off > 0 and magic_at(buf, j):
                    candidates.append(off)
                j += 1
            tail = buf[-(overlap - 1):]
            pos += len(data)
    for off in candidates:
        if validate(path, off):
            offsets.append(off)
    return offsets


def _gzip_magic(buf, j):
    # \x1f\x8b, deflate method, sane flag byte
    return (buf[j:j + 3] == b"\x1f\x8b\x08" and buf[j + 3] < 0x20)


def _bzip2_magic(buf, j):
    # BZh<level> + block magic (BCD pi)
    return (buf[j:j + 3] == b"BZh" and 0x31 <= buf[j + 3] <= 0x39
            and buf[j + 4:j + 10] == b"\x31\x41\x59\x26\x53\x59")


def _gzip_valid(path, off):
    import zlib
    from dpark_tpu import file_manager
    with file_manager.open_file(path) as f:
        f.seek(off)
        blob = f.read(8192)
    try:
        zlib.decompressobj(wbits=31).decompress(blob)
        return True
    except zlib.error:
        return False


def _bzip2_valid(path, off):
    from dpark_tpu import file_manager
    with file_manager.open_file(path) as f:
        f.seek(off)
        blob = f.read(1 << 16)
    try:
        _bz2.BZ2Decompressor().decompress(blob)
        return True
    except OSError:
        return False


class GZipFileRDD(RDD):
    """Intra-file splitting at gzip MEMBER boundaries: the raw bytes are
    scanned for validated member magics and consecutive members group
    into ~splitSize compressed splits, each decompressed independently
    (reference: GZipFileRDD block scanning [M]).  A single-member file
    still yields one split — gzip streams aren't block-splittable
    without an index."""

    def __init__(self, ctx, path, splitSize=None, numSplits=None):
        super().__init__(ctx)
        files = list(TextFileRDD._expand(path))
        self.paths = [p for p, _ in files]
        if splitSize is None:
            total = sum(sz for _, sz in files)
            splitSize = (max(1, total // numSplits) if numSplits
                         else DEFAULT_BLOCK)
        self.split_size = splitSize

    def _magic(self):
        return b"\x1f\x8b", _gzip_magic, _gzip_valid

    def _stream_splits(self, p, base_index):
        """Group one file's validated stream/member starts into
        ~splitSize byte-aligned splits (shared with the bz2 stream
        fallback)."""
        from dpark_tpu import file_manager
        prefix, magic, valid = self._magic()
        size = file_manager.file_size(p)
        offs = _scan_magic_offsets(p, prefix, magic, valid) + [size]
        out = []
        begin = offs[0]
        for i in range(1, len(offs)):
            if offs[i] - begin >= self.split_size or offs[i] == size:
                if offs[i] > begin:
                    out.append(TextSplit(base_index + len(out), p,
                                         begin, offs[i]))
                begin = offs[i]
        return out

    def _make_splits(self):
        splits = []
        for p in self.paths:
            splits.extend(self._stream_splits(p, len(splits)))
        return splits

    def _open(self, raw):
        import io
        return _gzip.GzipFile(fileobj=io.BytesIO(raw))

    def compute(self, split):
        from dpark_tpu import file_manager
        with file_manager.open_file(split.path) as f:
            f.seek(split.begin)
            raw = f.read(split.end - split.begin)
        with self._open(raw) as f:
            for line in f:
                yield line.rstrip(b"\r\n").decode("utf-8", "replace")


# bz2 bit-level constants: blocks inside one stream start with the
# 48-bit BCD-pi magic at ARBITRARY bit offsets; the stream ends with the
# sqrt(pi) magic + a combined CRC folded from the per-block CRCs (each
# stored in the 32 bits right after a block magic)
_BZ2_BLOCK_MAGIC = 0x314159265359
_BZ2_EOS_MAGIC = 0x177245385090
# (path, size) -> per-stream block table; bounded FIFO — a long-lived
# driver reading many bz2 files must not accumulate a few MB of block
# triples per file forever, and a rewritten path (new size) supersedes
# its old entry at insert time
_BZ2_TABLE_CACHE = {}
_BZ2_TABLE_CACHE_MAX = 64
# evict+insert happens under this lock: _block_table is reachable from
# the text-ingest ThreadPoolExecutor (line-spans-past-lookahead rescan),
# and iterating the dict while another thread inserts raises
_BZ2_TABLE_CACHE_LOCK = __import__("threading").Lock()


def _bz2_scan_bit_magics(path):
    """All bit offsets of block and end-of-stream magics in the file,
    found by a vectorized 56-bit sliding-window scan at each of the 8
    bit phases (a 48-bit magic is specific enough that spurious matches
    are ~2^-48 per bit — the standard splittable-bzip2 assumption)."""
    import numpy as np

    from dpark_tpu import file_manager
    mask = np.uint64((1 << 48) - 1)
    blocks, eoss = set(), set()
    chunk_size = 4 << 20
    with file_manager.open_file(path) as f:
        pos = 0
        tail = b""
        while True:
            data = f.read(chunk_size)
            if not data:
                break
            buf = tail + data
            base = pos - len(tail)
            a = np.frombuffer(buf, np.uint8)
            if len(a) >= 7:
                w = np.zeros(len(a) - 6, np.uint64)
                for i in range(7):
                    w |= a[i:len(a) - 6 + i].astype(np.uint64) \
                        << np.uint64(8 * (6 - i))
                for s in range(8):
                    cand = (w >> np.uint64(8 - s)) & mask
                    for j in np.flatnonzero(
                            cand == np.uint64(_BZ2_BLOCK_MAGIC)):
                        blocks.add((base + int(j)) * 8 + s)
                    for j in np.flatnonzero(
                            cand == np.uint64(_BZ2_EOS_MAGIC)):
                        eoss.add((base + int(j)) * 8 + s)
            tail = buf[-6:]
            pos += len(data)
    return sorted(blocks), sorted(eoss)


def _bz2_read_bits(f, bit_off, nbits):
    """nbits at absolute bit offset bit_off of an open binary file."""
    byte0 = bit_off // 8
    nbytes = (bit_off % 8 + nbits + 7) // 8
    f.seek(byte0)
    raw = f.read(nbytes)
    val = int.from_bytes(raw, "big")
    return (val >> (len(raw) * 8 - bit_off % 8 - nbits)) \
        & ((1 << nbits) - 1)


def _bz2_block_bytes(path, level, bit_start, bit_end, crcs):
    """A synthetic, fully valid one-stream bz2 file holding the blocks
    in [bit_start, bit_end): header, the bit range shifted to byte
    alignment, the end-of-stream magic, and the combined CRC refolded
    from the contained blocks' stored CRCs — so the stock decompressor
    (including its CRC check) accepts a bit-aligned slice of someone
    else's stream."""
    from dpark_tpu import file_manager
    b0 = bit_start // 8
    b1 = (bit_end + 7) // 8
    with file_manager.open_file(path) as f:
        f.seek(b0)
        raw = f.read(b1 - b0)
    nbits = bit_end - bit_start
    val = int.from_bytes(raw, "big")
    val = (val >> (len(raw) * 8 - (bit_start - b0 * 8) - nbits)) \
        & ((1 << nbits) - 1)
    comb = 0
    for c in crcs:
        comb = ((((comb << 1) | (comb >> 31)) ^ c) & 0xFFFFFFFF)
    hdr = int.from_bytes(b"BZh" + b"%d" % level, "big")
    out = ((((hdr << nbits) | val) << 48) | _BZ2_EOS_MAGIC)
    out = (out << 32) | comb
    tbits = 32 + nbits + 48 + 32
    pad = (-tbits) % 8
    return (out << pad).to_bytes((tbits + pad) // 8, "big")


class Bz2BlockSplit:
    """`n` consecutive blocks starting at block `first` of stream
    `stream` in `path`.  Carries its own metadata — `level` and the
    (bit_start, bit_end, crc) triples for its blocks plus a small
    lookahead for the line-extension walk — so workers decompress
    without rebuilding the whole-file block table (the bit scan runs
    once, on the driver); `more` flags blocks past the lookahead, in
    which case only the pathological line-spans-many-blocks case
    rescans."""

    LOOKAHEAD = 8

    def __init__(self, index, path, stream, first, n, level, blocks,
                 look, more):
        self.index = index
        self.path = path
        self.stream = stream
        self.first = first
        self.n = n
        self.level = level
        self.blocks = blocks
        self.look = look
        self.more = more


class BZip2FileRDD(GZipFileRDD):
    """Intra-file splitting at bz2 BLOCK boundaries: the compressed
    bytes are scanned for the bit-aligned 48-bit block magics inside
    each stream (reference: BZip2FileRDD scans block magic [M],
    SURVEY.md section 2.2), consecutive blocks group into ~splitSize
    splits, and each split decompresses independently through a
    synthetic stream rebuilt around its bit range.  Line-boundary rule
    matches TextFileRDD: a split skips the partial first line (unless
    it starts its stream) and finishes its last line by decompressing
    following blocks.  Files whose bit scan looks inconsistent fall
    back to byte-aligned STREAM-start splitting."""

    def _magic(self):
        return b"BZh", _bzip2_magic, _bzip2_valid

    def _open(self, raw):
        import io
        return _bz2.BZ2File(io.BytesIO(raw))

    def _block_table(self, path):
        """[(level, [(bit_start, bit_end, crc), ...]), ...] per stream,
        or None when the bit scan doesn't line up (stream fallback).

        Cached at MODULE level keyed by file identity, NOT on the RDD:
        the RDD pickles into every task, and a big file's table (one
        entry per ~100KB block) must not ride each task's bytes.  Runs
        on the driver at split time; each split ships only its own
        slice (+lookahead), so workers reach here only for the
        line-spans-past-lookahead fallback (deterministic rescan)."""
        from dpark_tpu import file_manager
        try:
            key = (path, file_manager.file_size(path))
        except OSError:
            key = (path, -1)
        if key in _BZ2_TABLE_CACHE:
            return _BZ2_TABLE_CACHE[key]
        table = []
        try:
            size = file_manager.file_size(path)
            stream_offs = _scan_magic_offsets(
                path, b"BZh", _bzip2_magic, _bzip2_valid) + [size]
            block_bits, eos_bits = _bz2_scan_bit_magics(path)
            with file_manager.open_file(path) as f:
                for si in range(len(stream_offs) - 1):
                    s0 = stream_offs[si]
                    s1 = stream_offs[si + 1]
                    f.seek(s0 + 3)
                    level = f.read(1)[0] - 0x30
                    if not (1 <= level <= 9):
                        raise ValueError("bad bz2 level")
                    lo, hi = s0 * 8 + 32, s1 * 8
                    starts = [b for b in block_bits if lo <= b < hi]
                    eos = [e for e in eos_bits if lo <= e < hi]
                    if not starts or len(eos) != 1 \
                            or starts[0] != lo \
                            or eos[0] <= starts[-1]:
                        raise ValueError("bz2 bit scan inconsistent")
                    bounds = starts + [eos[0]]
                    blocks = []
                    for bi in range(len(starts)):
                        crc = _bz2_read_bits(f, bounds[bi] + 48, 32)
                        blocks.append((bounds[bi], bounds[bi + 1], crc))
                    table.append((level, blocks))
        except Exception as e:
            logger.debug("bz2 block scan fallback for %s: %s", path, e)
            table = None
        with _BZ2_TABLE_CACHE_LOCK:
            stale = [k for k in list(_BZ2_TABLE_CACHE) if k[0] == path]
            while stale or len(_BZ2_TABLE_CACHE) >= _BZ2_TABLE_CACHE_MAX:
                victim = stale.pop() if stale \
                    else next(iter(_BZ2_TABLE_CACHE))
                _BZ2_TABLE_CACHE.pop(victim, None)
            _BZ2_TABLE_CACHE[key] = table
        return table

    def _make_splits(self):
        splits = []
        for p in self.paths:
            table = self._block_table(p)
            if table is None:
                for sp in self._stream_splits(p, len(splits)):
                    splits.append(sp)
                continue
            for si, (level, blocks) in enumerate(table):
                first = 0
                acc = 0
                K = Bz2BlockSplit.LOOKAHEAD
                for bi, (b0, b1, _) in enumerate(blocks):
                    acc += (b1 - b0) // 8
                    if acc >= self.split_size or bi == len(blocks) - 1:
                        end = bi + 1
                        splits.append(Bz2BlockSplit(
                            len(splits), p, si, first, end - first,
                            level, blocks[first:end],
                            blocks[end:end + K],
                            len(blocks) > end + K))
                        first, acc = end, 0
        return splits

    def _tail_blocks(self, split):
        """Block metadata after `split`, for the line-extension walk:
        the shipped lookahead, then (pathological long-line case only)
        the rest of the stream from the full table."""
        yield from split.look
        if split.more:
            blocks = self._block_table(split.path)[split.stream][1]
            skip = split.first + split.n + len(split.look)
            yield from blocks[skip:]

    def compute(self, split):
        if not isinstance(split, Bz2BlockSplit):
            yield from super().compute(split)      # stream fallback
            return
        level, sel = split.level, split.blocks
        data = _bz2.decompress(_bz2_block_bytes(
            split.path, level, sel[0][0], sel[-1][1],
            [c for _, _, c in sel]))
        # line-boundary convention (Hadoop LineRecordReader): a split
        # with a predecessor discards through its first newline
        # UNCONDITIONALLY, and every split that found its start reads
        # one line PAST its end — consistent even when a boundary falls
        # exactly on a newline or a line spans whole splits
        extend = True
        if split.first > 0:
            nl = data.find(b"\n")
            if nl < 0:
                data = b""
                extend = False     # no line starts here: owned upstream
            else:
                data = data[nl + 1:]
        if extend:
            for b0, b1, crc in self._tail_blocks(split):
                nxt = _bz2.decompress(_bz2_block_bytes(
                    split.path, level, b0, b1, [crc]))
                nl = nxt.find(b"\n")
                if nl >= 0:
                    data += nxt[:nl + 1]
                    break
                data += nxt
        if data:
            body = data[:-1] if data.endswith(b"\n") else data
            for line in body.split(b"\n"):
                yield line.rstrip(b"\r").decode("utf-8", "replace")

def _scan_csv_boundaries(path, split_size, quotechar='"',
                         delimiter=","):
    """Record-aligned split offsets for a CSV file via an exact
    RFC4180-style state machine (native.CsvScanner, C++): a quote opens
    a field only at field start, doubled quotes are literals, and a
    bare quote inside an unquoted field never flips state — so a quoted
    field containing newlines never straddles two splits (reference:
    csv record handling, SURVEY.md section 2.2)."""
    from dpark_tpu import file_manager
    from dpark_tpu.native import CsvScanner
    scanner = CsvScanner(split_size, quotechar.encode("utf-8"),
                         delimiter.encode("utf-8"))
    with file_manager.open_file(path) as f:
        while True:
            chunk = f.read(8 << 20)
            if not chunk:
                break
            scanner.feed(chunk)
        size = f.tell()
    bounds = [0] + scanner.bounds
    if bounds[-1] >= size:
        bounds.pop()
    return bounds, size


import io as _io


class _RangeRaw(_io.RawIOBase):
    """A bounded window over an open file handle (owns and closes it):
    lets TextIOWrapper/csv stream a split without materializing it."""

    def __init__(self, f, remaining):
        self.f = f
        self.remaining = remaining

    def readable(self):
        return True

    def readinto(self, b):
        n = min(len(b), self.remaining)
        if n <= 0:
            return 0
        data = self.f.read(n)
        b[:len(data)] = data
        self.remaining -= len(data)
        return len(data)

    def close(self):
        try:
            self.f.close()
        finally:
            super().close()


class CSVFileRDD(RDD):
    """CSV with record-aware splits: boundaries come from an exact
    RFC4180-style scan (per the dialect's quotechar/delimiter), so a
    quoted field containing newlines never straddles two tasks
    (reference: csv reader [M])."""

    def __init__(self, ctx, path, dialect="excel", splitSize=None,
                 numSplits=None):
        super().__init__(ctx)
        files = list(TextFileRDD._expand(path))
        self.paths = [p for p, _ in files]
        self.dialect = dialect
        if splitSize is None:
            total = sum(sz for _, sz in files)
            splitSize = (max(1, total // numSplits) if numSplits
                         else DEFAULT_BLOCK)
        self.split_size = splitSize

    def _dialect_obj(self):
        return _csv.get_dialect(self.dialect) \
            if isinstance(self.dialect, str) else self.dialect

    def _make_splits(self):
        splits = []
        d = self._dialect_obj()
        qc = d.quotechar or '"'
        delim = d.delimiter or ","
        for p in self.paths:
            bounds, size = _scan_csv_boundaries(p, self.split_size, qc,
                                                delim)
            for i, b in enumerate(bounds):
                e = bounds[i + 1] if i + 1 < len(bounds) else size
                if e > b:
                    splits.append(TextSplit(len(splits), p, b, e))
        return splits

    def preferred_locations(self, split):
        from dpark_tpu import file_manager
        return file_manager.locations(split.path, split.begin,
                                      split.end - split.begin)

    def compute(self, split):
        import io
        from dpark_tpu import file_manager
        f = file_manager.open_file(split.path)
        try:
            f.seek(split.begin)
            # stream the bounded range: no split-sized buffers
            raw = _RangeRaw(f, split.end - split.begin)
            text = io.TextIOWrapper(io.BufferedReader(raw),
                                    encoding="utf-8", errors="replace",
                                    newline="")
        except BaseException:
            f.close()
            raise

        def rows():
            # generator wrapper: abandoning the iterator (take/first,
            # sampling) closes the handle deterministically
            try:
                yield from _csv.reader(text, self.dialect)
            finally:
                text.close()
        return rows()


class CSVReaderRDD(RDD):
    def __init__(self, text_rdd, dialect="excel"):
        super().__init__(text_rdd.ctx)
        self.prev = text_rdd
        self.dialect = dialect
        self.dependencies = [OneToOneDependency(text_rdd)]

    def _make_splits(self):
        return self.prev.splits

    def compute(self, split):
        return _csv.reader(self.prev.iterator(split), self.dialect)


class BinarySplit(Split):
    def __init__(self, index, path, begin, end):
        super().__init__(index)
        self.path = path
        self.begin = begin
        self.end = end


class BinaryFileRDD(RDD):
    """Fixed-size records via a struct format (reference: BinaryFileRDD)."""

    def __init__(self, ctx, path, fmt="I", length=None, numSplits=None):
        super().__init__(ctx)
        self.path = path
        self.fmt = fmt
        self.record_size = length or struct.calcsize(fmt)
        size = os.path.getsize(path)
        nrec = size // self.record_size
        n = numSplits or ctx.default_parallelism
        n = max(1, min(n, nrec) if nrec else 1)
        self._ranges = []
        per = (nrec + n - 1) // n if nrec else 0
        for i in range(n):
            b = i * per * self.record_size
            e = min((i + 1) * per, nrec) * self.record_size
            if b < e or (i == 0 and nrec == 0):
                self._ranges.append((b, e))

    def _make_splits(self):
        return [BinarySplit(i, self.path, b, e)
                for i, (b, e) in enumerate(self._ranges)]

    def compute(self, split):
        rs = self.record_size
        with open(split.path, "rb") as f:
            f.seek(split.begin)
            remaining = split.end - split.begin
            while remaining > 0:
                buf = f.read(min(remaining, rs * 4096))
                if not buf:
                    break
                remaining -= len(buf)
                for off in range(0, len(buf) - rs + 1, rs):
                    if self.fmt:
                        yield struct.unpack_from(self.fmt, buf, off)
                    else:
                        yield buf[off:off + rs]


class CheckpointSplit(Split):
    def __init__(self, index, path):
        super().__init__(index)
        self.path = path


class CheckpointRDD(RDD):
    """Reads materialized partitions; replaces lineage after checkpoint()
    (reference: CheckpointRDD)."""

    def __init__(self, ctx, path):
        super().__init__(ctx)
        self.path = path
        self.files = sorted(
            f for f in os.listdir(path) if f.startswith("part-"))

    def _make_splits(self):
        return [CheckpointSplit(i, os.path.join(self.path, f))
                for i, f in enumerate(self.files)]

    def compute(self, split):
        # a lazy checkpoint may promote MID-JOB: tasks planned before
        # the promotion still carry the original RDD's splits — map
        # them by index (same partition layout by construction).
        # Decide by TYPE, not by attribute: any foreign split class may
        # carry a .path (TextSplit, BinarySplit, a CheckpointSplit of a
        # DIFFERENT directory) and duck-typing it here made compute
        # unpickle the source text file after promotion (r5 advisor
        # finding — all retries failed with UnpicklingError)
        if isinstance(split, CheckpointSplit) \
                and os.path.dirname(split.path) == self.path:
            path = split.path
        else:
            path = os.path.join(self.path, self.files[split.index])
        with open(path, "rb") as f:
            return iter(pickle.load(f))


# --------------------------------------------------------------------------
# sink RDDs (atomic tmp+rename part files; reference: OutputTextFileRDD etc.)
# --------------------------------------------------------------------------

class OutputRDDBase(DerivedRDD):
    def __init__(self, prev, path, overwrite=True, ext=""):
        super().__init__(prev)
        path = os.path.abspath(path)
        if os.path.exists(path) and not os.path.isdir(path):
            raise ValueError("output path %s is a file" % path)
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.overwrite = overwrite
        self.ext = ext

    def _target(self, split):
        return os.path.join(self.path,
                            "part-%05d%s" % (split.index, self.ext))

    def compute(self, split):
        target = self._target(split)
        if os.path.exists(target) and not self.overwrite:
            yield target
            return
        have_data = False
        with atomic_file(target, self._mode()) as f:
            have_data = self._write(f, self.prev.iterator(split))
        if have_data:
            yield target
        else:
            os.unlink(target)

    def _mode(self):
        return "wb"

    def _write(self, f, it):
        raise NotImplementedError


class OutputTextFileRDD(OutputRDDBase):
    def __init__(self, prev, path, ext="", overwrite=True, compress=False):
        if compress and not ext:
            ext = ".gz"
        super().__init__(prev, path, overwrite, ext)
        self.compress = compress

    def _write(self, f, it):
        if self.compress:
            f = _gzip.GzipFile(fileobj=f, mode="wb")
        have = False
        for line in it:
            if not isinstance(line, (bytes, bytearray)):
                line = str(line).encode("utf-8")
            f.write(line)
            if not line.endswith(b"\n"):
                f.write(b"\n")
            have = True
        if self.compress:
            f.close()
        return have


class OutputCSVFileRDD(OutputRDDBase):
    def __init__(self, prev, path, overwrite=True, dialect="excel"):
        super().__init__(prev, path, overwrite, ".csv")
        self.dialect = dialect

    def _mode(self):
        return "w"

    def _write(self, f, it):
        w = _csv.writer(f, self.dialect)
        have = False
        for row in it:
            w.writerow(row if isinstance(row, (list, tuple)) else [row])
            have = True
        return have


class OutputBinaryFileRDD(OutputRDDBase):
    def __init__(self, prev, path, fmt, overwrite=True):
        super().__init__(prev, path, overwrite, ".bin")
        self.fmt = fmt

    def _write(self, f, it):
        have = False
        for rec in it:
            if isinstance(rec, tuple):
                f.write(struct.pack(self.fmt, *rec))
            else:
                f.write(struct.pack(self.fmt, rec))
            have = True
        return have


class OutputPickleFileRDD(OutputRDDBase):
    def _write(self, f, it):
        items = list(it)
        pickle.dump(items, f, -1)
        return True


class MultiOutputTextFileRDD(OutputRDDBase):
    """saveAsTextFileByKey: records are (key, line); each key gets its own
    subdirectory (reference: MultiOutputTextFileRDD [M]).

    Each part file is written tmp+rename like OutputRDDBase so a
    speculative duplicate task can never interleave with (or corrupt) the
    winner's output — last atomic rename wins (round-1 advisor fix)."""

    def compute(self, split):
        part = "part-%05d%s" % (split.index, self.ext)
        files = {}                      # key -> (file obj or None, target)
        with contextlib.ExitStack() as stack:
            for k, line in self.prev.iterator(split):
                ent = files.get(k)
                if ent is None:
                    target = os.path.join(self.path, str(k), part)
                    if os.path.exists(target) and not self.overwrite:
                        ent = (None, target)
                    else:
                        ent = (stack.enter_context(atomic_file(target)),
                               target)
                    files[k] = ent
                f = ent[0]
                if f is None:
                    continue            # exists and not overwrite: keep
                if not isinstance(line, (bytes, bytearray)):
                    line = str(line).encode("utf-8")
                f.write(line)
                if not line.endswith(b"\n"):
                    f.write(b"\n")
        yield from (target for _, target in files.values())
