"""Erasure-coded shuffle exchange (ISSUE 6 tentpole).

PR 5's chaos plane proved lineage recovery is CORRECT but expensive: a
lost ``hbm://`` bucket invalidates all of a device parent's outputs,
so one injected fetch fault costs a full stage resubmit round.  Coded
MapReduce / CAMR (PAPERS.md) show the alternative — pay a little
parity at MAP time so a failed or straggling fetch is *decoded* from
surviving shards instead of recomputed through lineage.

This module is the codec: systematic XOR (m=1) and Reed–Solomon over
GF(2^8) (Cauchy parity matrix, so every k-subset of the n=k+m shard
rows is invertible), numpy-vectorized with a pure-Python fallback.
Shuffle bucket payloads and spill runs split into k equal-padded data
chunks plus m parity chunks; any k of the n shards reconstruct the
payload exactly.

Mode grammar (the ``DPARK_SHUFFLE_CODE`` env var / conf knob)::

    off          no coding (the default; zero hot-path cost)
    xor          k=4 data shards + 1 XOR parity (survives any 1 loss)
    xor(k)       same with k data shards
    rs(k,m)      k data + m Reed–Solomon parity shards (any m losses)

One on-disk shape, two wire shapes, share the codec:

* **shard containers** — shuffle buckets and spill runs/chunks stay
  ONE file, but the body is n back-to-back framed shards with
  per-shard crc32c, so a corrupted region drops exactly the shards it
  touched and the reader decodes around them (a local ``file://``
  fetch reads the container once — no per-shard syscall cost);
* **shard frames** — REMOTE fetches (``tcp://`` peers, the ``hbm://``
  export bridge) stay per-shard units: the fetch side issues all n
  frame reads concurrently and decodes as soon as any k arrive
  (fastest-k also wins against stragglers, which speculation only
  partially covers).

Decode outcomes feed process-global counters (``repair`` — parity
replaced a FAILED shard; ``straggler_win`` — parity merely arrived
before a slow shard; ``decode_failures`` — fewer than k survived, so
the fetch fell back to lineage), attributed per shuffle id.  The
scheduler snapshots them into job records / ``recovery_summary()``
and the web UI shows them per stage.  Counters are per-process: the
multiprocess master's workers decode in their own processes, so their
counts don't surface on the driver (same contract as ``faults``).
"""

import re
import struct
import threading

__all__ = [
    "ALGO_XOR", "ALGO_RS", "Code", "ShardCorrupt", "ShardShortfall",
    "parse_code", "configure", "active", "active_code", "describe",
    "pack_shard", "unpack_shard", "encode_bucket_frames",
    "encode_container", "decode_container", "is_container",
    "parse_container", "extract_container_frame",
    "note", "counters_snapshot", "reset_counters", "stats",
    "set_shuffle_code", "shuffle_code", "clear_shuffle_codes",
    "note_parity_bytes", "parity_bytes", "choose_code",
    "record_choice", "code_history", "adaptive_enabled",
]

ALGO_XOR = 0
ALGO_RS = 1

SHARD_MAGIC = b"DSH1"
CONTAINER_MAGIC = b"DCC1"

# magic, algo, k, m, shard index, original payload length, shard
# length, crc32c of the shard payload.  8-byte lengths: one bucket of
# giant combiners must not overflow a 4 GiB prefix (same contract as
# the PR 5 spill chunk framing).
_SHARD_HDR = struct.Struct("<4sBBBBQQI")


def _crc(blob):
    """crc32c when the native library is loaded, else C-speed
    zlib.crc32 (the shuffle spill framing's exact policy — shards are
    written and read by the same installation, so the polynomial only
    needs in-process consistency)."""
    from dpark_tpu import native
    if native.get_lib() is not None:
        return native.crc32c(blob)
    import zlib
    return zlib.crc32(blob) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# GF(2^8) arithmetic (polynomial 0x11D)
# ---------------------------------------------------------------------------

_EXP = None            # 510-entry exp table (doubled: no mod in mul)
_LOG = None
_NP_MUL = None         # lazily built 256x256 uint8 product table
_MUL_ROWS = {}         # coefficient -> 256-byte row (pure-Python path)
_FORCE_PURE = False    # tests flip this to exercise the fallback


def _numpy():
    if _FORCE_PURE:
        return None
    try:
        import numpy
        return numpy
    except ImportError:
        return None


def _tables():
    global _EXP, _LOG
    if _EXP is None:
        exp = [0] * 510
        log = [0] * 256
        x = 1
        for i in range(255):
            exp[i] = x
            log[x] = i
            x <<= 1
            if x & 0x100:
                x ^= 0x11D
        for i in range(255, 510):
            exp[i] = exp[i - 255]
        _EXP, _LOG = exp, log
    return _EXP, _LOG


def gf_mul(a, b):
    if not a or not b:
        return 0
    exp, log = _tables()
    return exp[log[a] + log[b]]


def gf_inv(a):
    exp, log = _tables()
    return exp[255 - log[a]]


def _np_mul_table():
    global _NP_MUL
    if _NP_MUL is None:
        np = _numpy()
        exp, log = _tables()
        le = np.array(exp, dtype=np.int32)
        ll = np.array(log, dtype=np.int32)
        t = np.zeros((256, 256), dtype=np.uint8)
        for c in range(1, 256):
            t[c, 1:] = le[ll[c] + ll[1:]].astype(np.uint8)
        _NP_MUL = t
    return _NP_MUL


def _xor_bytes(a, b):
    np = _numpy()
    if np is not None:
        return (np.frombuffer(a, np.uint8)
                ^ np.frombuffer(b, np.uint8)).tobytes()
    return bytes(x ^ y for x, y in zip(a, b))


def _mul_bytes(c, buf):
    """GF product of scalar coefficient `c` with every byte of `buf`."""
    if c == 0:
        return b"\0" * len(buf)
    if c == 1:
        return bytes(buf)
    np = _numpy()
    if np is not None:
        return _np_mul_table()[c][np.frombuffer(buf, np.uint8)].tobytes()
    row = _MUL_ROWS.get(c)
    if row is None:
        row = bytes(gf_mul(c, b) for b in range(256))
        _MUL_ROWS[c] = row
    return bytes(row[b] for b in buf)


def _gf_invert_matrix(rows):
    """Gauss-Jordan inverse of a k x k matrix over GF(2^8).  The Cauchy
    construction guarantees invertibility for every survivor subset;
    the pivot assert is a corruption tripwire, not a reachable path."""
    k = len(rows)
    a = [list(r) + [1 if j == i else 0 for j in range(k)]
         for i, r in enumerate(rows)]
    for col in range(k):
        piv = next((r for r in range(col, k) if a[r][col]), None)
        assert piv is not None, "singular survivor matrix"
        a[col], a[piv] = a[piv], a[col]
        pv = gf_inv(a[col][col])
        a[col] = [gf_mul(pv, v) for v in a[col]]
        for r in range(k):
            if r != col and a[r][col]:
                f = a[r][col]
                a[r] = [v ^ gf_mul(f, a[col][j])
                        for j, v in enumerate(a[r])]
    return [r[k:] for r in a]


# ---------------------------------------------------------------------------
# the code itself
# ---------------------------------------------------------------------------

class ShardCorrupt(IOError):
    """A shard frame failed its crc32c / structural check.  The shard
    is dropped; decode proceeds from the survivors."""


class ShardShortfall(Exception):
    """Fewer than k shards survived: the payload is information-
    theoretically gone and only lineage recovery can help.  Carries
    the counts the FetchFailed translation reports."""

    def __init__(self, found, needed, total):
        super().__init__(
            "%d of %d shards survived; %d needed to decode"
            % (found, total, needed))
        self.found = found
        self.needed = needed
        self.total = total


class Code:
    """A systematic (k, m) erasure code: shards 0..k-1 are the data
    chunks verbatim, shards k..k+m-1 are parity."""

    def __init__(self, algo, k, m):
        if algo not in (ALGO_XOR, ALGO_RS):
            raise ValueError("unknown code algo %r" % (algo,))
        if k < 1 or m < 1:
            raise ValueError("code needs k >= 1 and m >= 1, got "
                             "k=%d m=%d" % (k, m))
        if algo == ALGO_XOR and m != 1:
            raise ValueError("xor parity is single-loss only (m=1)")
        if k + m > 255:
            raise ValueError("GF(2^8) supports at most 255 shards, "
                             "got k+m=%d" % (k + m))
        self.algo = algo
        self.k = k
        self.m = m
        self.n = k + m
        self._cauchy = None

    def describe(self):
        if self.algo == ALGO_XOR:
            return "xor(%d)" % self.k
        return "rs(%d,%d)" % (self.k, self.m)

    __repr__ = describe

    def _parity_rows(self):
        """m x k Cauchy matrix C[i][j] = 1/(x_i + y_j) with x_i = k+i,
        y_j = j (disjoint label sets, so every entry is defined).  The
        systematic generator [I; C] then has every k x k row-subset
        invertible — the MDS property the decoder relies on."""
        if self._cauchy is None:
            self._cauchy = [
                [gf_inv((self.k + i) ^ j) for j in range(self.k)]
                for i in range(self.m)]
        return self._cauchy

    def encode(self, data):
        """bytes -> n shard payloads (k data chunks zero-padded to a
        common length, then m parity chunks)."""
        k = self.k
        shard_len = max(1, -(-len(data) // k))
        padded = data.ljust(k * shard_len, b"\0")
        chunks = [bytes(padded[i * shard_len:(i + 1) * shard_len])
                  for i in range(k)]
        if self.algo == ALGO_XOR:
            parity = chunks[0]
            for c in chunks[1:]:
                parity = _xor_bytes(parity, c)
            return chunks + [parity]
        out = list(chunks)
        for row in self._parity_rows():
            acc = _mul_bytes(row[0], chunks[0])
            for j in range(1, k):
                acc = _xor_bytes(acc, _mul_bytes(row[j], chunks[j]))
            out.append(acc)
        return out

    def decode(self, shards, orig_len):
        """{shard index -> payload} (any >= k of them) -> the original
        bytes.  Raises ShardShortfall with fewer than k survivors."""
        k = self.k
        have = dict(shards)
        if len(have) < k:
            raise ShardShortfall(len(have), k, self.n)
        missing = [j for j in range(k) if j not in have]
        if not missing:
            return b"".join(have[j] for j in range(k))[:orig_len]
        if self.algo == ALGO_XOR:
            # one absent data chunk: it is the XOR of everything else
            acc = None
            for i in sorted(have):
                acc = have[i] if acc is None else _xor_bytes(acc,
                                                             have[i])
            have[missing[0]] = acc
            return b"".join(have[j] for j in range(k))[:orig_len]
        # RS: invert the survivor rows of the generator, then rebuild
        # only the MISSING data chunks (present ones ride verbatim)
        chosen = [i for i in sorted(have) if i < k]
        for i in sorted(have):
            if len(chosen) == k:
                break
            if i >= k:
                chosen.append(i)
        cau = self._parity_rows()
        rows = [[1 if t == s else 0 for t in range(k)] if s < k
                else list(cau[s - k]) for s in chosen]
        inv = _gf_invert_matrix(rows)
        shard_len = len(have[chosen[0]])
        for j in missing:
            acc = b"\0" * shard_len
            for t, s in enumerate(chosen):
                c = inv[j][t]
                if c:
                    acc = _xor_bytes(acc, _mul_bytes(c, have[s]))
            have[j] = acc
        return b"".join(have[j] for j in range(k))[:orig_len]


def parse_code(text):
    """``off|xor|xor(k)|rs(k,m)`` -> Code or None.  Malformed specs
    raise ValueError — a run with a typo'd mode silently writing
    uncoded buckets would "prove" a recovery path it never took."""
    t = (text or "").strip().lower()
    if t in ("", "off", "0", "none"):
        return None
    m = re.fullmatch(r"xor(?:\((\d+)\))?", t)
    if m:
        return Code(ALGO_XOR, int(m.group(1) or 4), 1)
    m = re.fullmatch(r"rs\((\d+)\s*,\s*(\d+)\)", t)
    if m:
        return Code(ALGO_RS, int(m.group(1)), int(m.group(2)))
    raise ValueError(
        "unknown shuffle code %r (one of: off, xor, xor(k), rs(k,m))"
        % (text,))


# ---------------------------------------------------------------------------
# shard frames + containers
# ---------------------------------------------------------------------------

class _Frame:
    __slots__ = ("algo", "k", "m", "idx", "orig_len", "crc",
                 "payload", "end")

    def __init__(self, algo, k, m, idx, orig_len, crc, payload, end):
        self.algo = algo
        self.k = k
        self.m = m
        self.idx = idx
        self.orig_len = orig_len
        self.crc = crc
        self.payload = payload
        self.end = end


def pack_shard(code, idx, orig_len, payload):
    """One self-describing shard frame: geometry + index + original
    length ride the header so reads never depend on reader config."""
    return _SHARD_HDR.pack(SHARD_MAGIC, code.algo, code.k, code.m,
                           idx, orig_len, len(payload),
                           _crc(payload)) + payload


def unpack_shard(buf, off=0, verify=True):
    """Parse one shard frame at `off`.  With verify the payload crc is
    checked here; container readers verify AFTER routing the payload
    through the spill_read chaos site instead."""
    if len(buf) < off + _SHARD_HDR.size:
        raise ShardCorrupt("short shard frame (%d bytes at %d)"
                           % (len(buf) - off, off))
    magic, algo, k, m, idx, orig_len, slen, crc = \
        _SHARD_HDR.unpack_from(buf, off)
    if magic != SHARD_MAGIC:
        raise ShardCorrupt("bad shard magic %r" % (magic,))
    end = off + _SHARD_HDR.size + slen
    if end > len(buf):
        raise ShardCorrupt("truncated shard payload")
    payload = bytes(buf[off + _SHARD_HDR.size:end])
    if verify and _crc(payload) != crc:
        raise ShardCorrupt("shard %d: crc32c mismatch" % idx)
    return _Frame(algo, k, m, idx, orig_len, crc, payload, end)


def encode_bucket_frames(blob, code):
    """Bucket payload -> n framed shard blobs, one per shard FILE /
    shard request (each an independent fetch unit)."""
    return [pack_shard(code, i, len(blob), p)
            for i, p in enumerate(code.encode(blob))]


def encode_container(blob, code, fault_site=None):
    """Single-file shard container for spill runs/chunks: the crc is
    computed over the TRUE shard bytes, then each payload routes
    through the write chaos site — injected corruption lands in
    exactly one shard and is caught (and decoded around) at read."""
    from dpark_tpu import faults
    parts = [CONTAINER_MAGIC, struct.pack("<B", code.n)]
    for idx, p in enumerate(code.encode(blob)):
        crc = _crc(p)
        if fault_site is not None:
            p = faults.hit(fault_site, p)
        parts.append(_SHARD_HDR.pack(SHARD_MAGIC, code.algo, code.k,
                                     code.m, idx, len(blob), len(p),
                                     crc))
        parts.append(p)
    return b"".join(parts)


def is_container(raw):
    return raw[:4] == CONTAINER_MAGIC


def parse_container(raw):
    """Container bytes -> list of _Frame, crc NOT yet verified (the
    caller owns chaos-site routing + verification per shard).  A lost
    frame boundary truncates the list — later shards are unreachable,
    which the decode treats as erasures."""
    if not is_container(raw):
        raise ShardCorrupt("not a shard container")
    (n,) = struct.unpack_from("<B", raw, 4)
    off = 5
    frames = []
    for _ in range(n):
        try:
            fr = unpack_shard(raw, off, verify=False)
        except ShardCorrupt:
            break
        off = fr.end
        frames.append(fr)
    return frames


def extract_container_frame(raw, idx):
    """The framed bytes of shard `idx` inside a container — what a
    bucket server returns for one shard request (the remote fetch
    unit).  Raises KeyError when the container holds no such shard."""
    for fr in parse_container(raw):
        if fr.idx == idx:
            start = fr.end - len(fr.payload) - _SHARD_HDR.size
            return bytes(raw[start:fr.end])
    raise KeyError(idx)


def decode_container(raw, fault_site=None, shuffle_id=None):
    """Read a shard container back, dropping shards whose crc fails
    (or whose read chaos-site hit raises) and decoding from the rest.
    Raises ShardShortfall when fewer than k survive — the caller
    translates that into SpillCorruption / FetchFailed."""
    from dpark_tpu import faults
    if not is_container(raw):
        raise ShardCorrupt("not a shard container")
    (n,) = struct.unpack_from("<B", raw, 4)
    good = {}
    geom = None
    orig_len = 0
    for fr in parse_container(raw):
        geom = (fr.algo, fr.k, fr.m)
        orig_len = fr.orig_len
        payload = fr.payload
        try:
            if fault_site is not None:
                payload = faults.hit(fault_site, payload)
            if _crc(payload) != fr.crc:
                raise ShardCorrupt("shard %d: crc32c mismatch"
                                   % fr.idx)
        except Exception:
            continue            # this shard is gone; decode around it
        good[fr.idx] = payload
    if geom is None:
        note("decode_failures", shuffle_id)
        raise ShardShortfall(0, 1, n)
    code = Code(*geom)
    if len(good) < code.k:
        note("decode_failures", shuffle_id)
        raise ShardShortfall(len(good), code.k, code.n)
    data = code.decode(good, orig_len)
    if any(j not in good for j in range(code.k)):
        # parity actually reconstructed data: a repair, free of lineage
        note("repair", shuffle_id)
    return data


# ---------------------------------------------------------------------------
# active-mode plumbing + decode counters
# ---------------------------------------------------------------------------

_CODE = None

_LOCK = threading.Lock()
_KINDS = ("repair", "straggler_win", "decode_failures",
          # peer-death masked by parity (ISSUE 20): a lease-expired
          # peer's shards were failed fast and the decode still closed
          # from live peers — the recovery path the liveness layer buys
          "peer_masked")
_TOTALS = {k: 0 for k in _KINDS}
_PER_SHUFFLE = {}
_PER_PEER = {}
_PARITY_BYTES = [0]

# per-shuffle code overrides (ISSUE 19): the straggler-adaptive policy
# prices (k,m) PER EXCHANGE, so one process can be writing rs(4,2)
# containers for a straggly exchange while a tight one stays plain.
# The registry maps shuffle_id -> Code (None = explicitly uncoded);
# unregistered shuffles use the global _CODE.  Both the map side
# (ShuffleMapTask.run) and the reduce side (ShuffledRDD /
# CoGroupedRDD.compute) register from the serialized dep before
# touching buckets, so worker processes see the driver's choice.
_SHUFFLE_CODES = {}
_SHUFFLE_CODES_CAP = 1024
_UNSET = object()


def configure(spec=None):
    """Install the shuffle code from a spec string (None/"" / "off"
    clears it).  Returns the installed Code or None."""
    global _CODE
    _CODE = parse_code(spec) if spec else None
    return _CODE


def active():
    return _CODE is not None


def active_code():
    return _CODE


def describe():
    return _CODE.describe() if _CODE is not None else "off"


def note(kind, shuffle_id=None, peer=None):
    """Count a decode outcome, attributed to `shuffle_id` when the
    caller knows it (bucket fetches do; spill-run decodes don't) and
    to the serving `peer` (ISSUE 19 satellite: /metrics and the health
    plane name WHICH peer's straggling triggered an escalation)."""
    with _LOCK:
        _TOTALS[kind] += 1
        if shuffle_id is not None:
            per = _PER_SHUFFLE.setdefault(
                shuffle_id, {k: 0 for k in _KINDS})
            per[kind] += 1
        if peer is not None:
            pp = _PER_PEER.setdefault(
                str(peer), {k: 0 for k in _KINDS})
            pp[kind] += 1
    from dpark_tpu import trace
    if trace._PLANE is not None:
        # timeline twin of the counter (ISSUE 8): each decode outcome
        # is an instant event on the fetching task's span context
        trace.event("decode." + kind, "coding", shuffle=shuffle_id,
                    peer=peer)


def note_parity_bytes(nbytes):
    """Count parity OVERHEAD bytes written (encoded container/frame
    bytes minus the original payload) — the adaptive-code bench grades
    itself on total parity bytes vs the static code."""
    if nbytes > 0:
        with _LOCK:
            _PARITY_BYTES[0] += int(nbytes)


def parity_bytes():
    with _LOCK:
        return _PARITY_BYTES[0]


def counters_snapshot():
    """Deep copy of the counters — the scheduler diffs two snapshots
    to attribute decode activity to one job record."""
    with _LOCK:
        return {"totals": dict(_TOTALS),
                "per_shuffle": {sid: dict(c)
                                for sid, c in _PER_SHUFFLE.items()},
                "per_peer": {p: dict(c)
                             for p, c in _PER_PEER.items()},
                "parity_bytes": _PARITY_BYTES[0]}


def reset_counters():
    with _LOCK:
        for k in _KINDS:
            _TOTALS[k] = 0
        _PER_SHUFFLE.clear()
        _PER_PEER.clear()
        _PARITY_BYTES[0] = 0


def stats():
    """{mode, repair, straggler_win, decode_failures, parity_bytes,
    per_peer} — the bench JSON's `decodes` section and
    recovery_summary()'s decode view (decode_failures stays distinct
    from plain fetch failures)."""
    with _LOCK:
        out = dict(_TOTALS)
        out["parity_bytes"] = _PARITY_BYTES[0]
        out["per_peer"] = {p: dict(c) for p, c in _PER_PEER.items()}
    out["mode"] = describe()
    return out


# ---------------------------------------------------------------------------
# straggler-adaptive per-exchange code selection (ISSUE 19 tentpole 1)
# ---------------------------------------------------------------------------

def set_shuffle_code(shuffle_id, spec):
    """Install a per-shuffle code override from a spec string.  "off"
    pins the exchange uncoded (overriding a global code); None clears
    the override (global code applies).  Malformed specs raise
    ValueError, same contract as configure()."""
    code = parse_code(spec) if spec is not None else _UNSET
    with _LOCK:
        if code is _UNSET:
            _SHUFFLE_CODES.pop(shuffle_id, None)
            return None
        if len(_SHUFFLE_CODES) >= _SHUFFLE_CODES_CAP \
                and shuffle_id not in _SHUFFLE_CODES:
            # bounded: a long-lived service mints shuffle ids forever
            _SHUFFLE_CODES.pop(next(iter(_SHUFFLE_CODES)))
        _SHUFFLE_CODES[shuffle_id] = code
    return code


def shuffle_code(shuffle_id):
    """The code governing one exchange: its registered override when
    the adaptive policy priced it, else the global active code.  Both
    the bucket writer and the fetch path resolve through here, so a
    mixed-code run stays self-consistent end to end."""
    with _LOCK:
        if shuffle_id in _SHUFFLE_CODES:
            return _SHUFFLE_CODES[shuffle_id]
    return _CODE


def clear_shuffle_codes():
    with _LOCK:
        _SHUFFLE_CODES.clear()


_CHOICES = []
_CHOICES_CAP = 256


def record_choice(site, spec, reason, applied, predicted_ms=None):
    """Append one (k,m) policy choice to the bounded in-process
    history — rides /api/health's executor evidence so an operator can
    see the chosen code tracking the observed tails."""
    with _LOCK:
        if len(_CHOICES) >= _CHOICES_CAP:
            del _CHOICES[0]
        _CHOICES.append({"site": site, "code": spec,
                         "reason": reason, "applied": bool(applied),
                         "predicted_ms": predicted_ms})


def code_history():
    with _LOCK:
        return [dict(c) for c in _CHOICES]


def adaptive_enabled():
    """True when the per-exchange policy is allowed to STEER: the
    conf gate is on and the adapt plane is in steering mode."""
    from dpark_tpu import adapt, conf
    return bool(getattr(conf, "CODE_ADAPT", False)) and adapt.steering()


def choose_code(peers, tails, fault_rates=None, static_spec=None):
    """Price (k,m) for one exchange from its recorded peers' fetch-tail
    sketches and observed decode/fault rates.  Pure policy — no store
    access, no side effects — so tests drive it with synthesized tails.

    `peers`: peer labels recorded for this exchange.
    `tails`: {peer: sketch digest (health.Sketch.to_dict shape)}.
    `fault_rates`: {peer or "*": {"repair"/"decode_failures": n}} —
    any observed repair or decode failure escalates (the exchange
    demonstrably consumed parity or lost shards).

    Returns (spec, reason, predicted_ms):
      spec None      -> no history worth acting on; keep the static
                        code (CODE_ADAPT's do-nothing outcome)
      spec "off"     -> all recorded peers tight: drop the parity tax
      spec escalated -> conf.CODE_ADAPT_ESCALATE for this exchange
    predicted_ms is the policy's own fetch-wall forecast (worst-peer
    p50 when escalating — fastest-k dodges the tail — else worst-peer
    p99), recorded against the observed wall by decision point 6."""
    from dpark_tpu import conf
    from dpark_tpu.health import Sketch
    ratio_bar = float(getattr(conf, "CODE_ADAPT_TAIL_RATIO", 3.0))
    min_n = int(getattr(conf, "CODE_ADAPT_MIN_SAMPLES", 8) or 1)
    worst = None                      # (ratio, p50_ms, p99_ms, peer)
    for peer in sorted(set(peers or ())):
        sk = Sketch.from_dict((tails or {}).get(peer) or {})
        if sk.n < min_n or sk.sum <= 0:
            continue
        p50 = sk.quantile(0.50) or 0.0
        p99 = sk.quantile(0.99) or 0.0
        ratio = (p99 / p50) if p50 > 0 else 0.0
        if worst is None or ratio > worst[0]:
            worst = (ratio, p50 * 1e3, p99 * 1e3, peer)
    decoded = sum(int(c.get(k, 0))
                  for c in (fault_rates or {}).values()
                  for k in ("repair", "decode_failures"))
    if worst is None:
        return (None, "no recorded tails for peers %s"
                % (sorted(set(peers or ())),), None)
    ratio, p50_ms, p99_ms, peer = worst
    if decoded or ratio >= ratio_bar:
        spec = getattr(conf, "CODE_ADAPT_ESCALATE", "rs(4,2)")
        why = ("%d decode(s) consumed parity here" % decoded
               if decoded else
               "peer %s tail p99/p50 %.1f >= %.1f" % (peer, ratio,
                                                      ratio_bar))
        return spec, "escalate: " + why, round(p50_ms, 3)
    return ("off", "tight tails: worst peer %s p99/p50 %.1f < %.1f"
            % (peer, ratio, ratio_bar), round(p99_ms, 3))


def _init_from_conf():
    from dpark_tpu import conf
    spec = getattr(conf, "DPARK_SHUFFLE_CODE", "")
    if spec and spec != "off":
        configure(spec)


_init_from_conf()
