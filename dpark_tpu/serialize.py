"""Closure shipping: serialize arbitrary user functions to bytes.

Reference parity: dpark/serialize.py (dump_func/load_func, dump_closure) — a
homegrown cloudpickle that marshals code objects and recursively pickles
closures, cells, globals and partials so any user lambda can be shipped to an
executor (SURVEY.md section 2.1).

Implementation here is an original Python-3.12 design built on
`pickle.Pickler.reducer_override` plus the 6-tuple reduce protocol so that
self-referential closures (f captured in f's own globals/cells) reconstruct
correctly: the function object is created empty first, memoized, then its
state (globals/defaults/cells) is applied by a state setter.
"""

import importlib
import io
import marshal
import pickle
import sys
import types

_BY_VALUE_MODULES = {"__main__", "__mp_main__", None}


def _is_importable(obj, name=None):
    """True if obj can be pickled by reference (module.qualname lookup)."""
    mod = getattr(obj, "__module__", None)
    if mod in _BY_VALUE_MODULES:
        return False
    qualname = name or getattr(obj, "__qualname__", None)
    if qualname is None or "<locals>" in qualname:
        return False
    m = sys.modules.get(mod)
    if m is None:
        return False
    try:
        found = m
        for part in qualname.split("."):
            found = getattr(found, part)
        return found is obj
    except AttributeError:
        return False


def _iter_code_names(code):
    """All global names referenced by a code object, including nested code."""
    names = set(code.co_names)
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            names |= _iter_code_names(const)
    return names


def _make_function(code_bytes, name, qualname, module, ncells):
    code = marshal.loads(code_bytes)
    cells = tuple(types.CellType() for _ in range(ncells))
    g = _shared_globals(module)
    f = types.FunctionType(code, g, name, None, cells or None)
    f.__qualname__ = qualname
    f.__module__ = module
    return f


_globals_registry = {}


def _shared_globals(module):
    """One globals dict per source module name, shared by all functions we
    reconstruct from it — mirrors normal module semantics (and the
    reference's behaviour of rebinding into a live module dict)."""
    if module in sys.modules and module not in _BY_VALUE_MODULES:
        return sys.modules[module].__dict__
    return _globals_registry.setdefault(module or "__dpark_anon__",
                                        {"__builtins__": __builtins__})


def _apply_function_state(f, state):
    (glbs, defaults, kwdefaults, cellvals, fdict, annotations) = state
    f.__globals__.update(glbs)
    f.__defaults__ = defaults
    f.__kwdefaults__ = kwdefaults
    if cellvals is not None and f.__closure__ is not None:
        for cell, (filled, v) in zip(f.__closure__, cellvals):
            if filled:
                cell.cell_contents = v
    if fdict:
        f.__dict__.update(fdict)
    if annotations:
        f.__annotations__ = annotations
    return f


def _import_module(name):
    return importlib.import_module(name)


class ClosurePickler(pickle.Pickler):
    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType) and not _is_importable(obj):
            return self._reduce_function(obj)
        if isinstance(obj, types.ModuleType):
            return (_import_module, (obj.__name__,))
        return NotImplemented

    def _reduce_function(self, f):
        code = f.__code__
        ncells = len(f.__closure__ or ())
        # globals subset actually referenced by the code (and nested code)
        names = _iter_code_names(code)
        glbs = {}
        for n in names:
            if n in f.__globals__:
                glbs[n] = f.__globals__[n]
        cellvals = None
        if f.__closure__:
            cellvals = []
            for cell in f.__closure__:
                try:
                    cellvals.append((True, cell.cell_contents))
                except ValueError:          # empty cell (recursive def)
                    cellvals.append((False, None))
        state = (glbs, f.__defaults__, f.__kwdefaults__, cellvals,
                 dict(f.__dict__), dict(getattr(f, "__annotations__", {})))
        args = (marshal.dumps(code), f.__name__, f.__qualname__,
                f.__module__ or "__dpark_anon__", ncells)
        return (_make_function, args, state, None, None,
                _apply_function_state)


def dumps(obj, protocol=pickle.HIGHEST_PROTOCOL):
    buf = io.BytesIO()
    ClosurePickler(buf, protocol).dump(obj)
    return buf.getvalue()


def loads(data):
    return pickle.loads(data)


# reference-parity aliases (dpark/serialize.py exports these names)
dump_func = dumps
load_func = loads


def dump_closure(f):
    return dumps(f)


def load_closure(data):
    return loads(data)
