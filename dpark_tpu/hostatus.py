"""Per-host task failure tracking with decay.

Reference parity: dpark/hostatus.py (TaskHostManager) — the scheduler
consults it to avoid repeatedly dispatching onto failing hosts (SURVEY.md
sections 2.1 and 5.3).  For the single-host masters this gates worker
*processes*; the multi-host DCN layer uses it per hostname.
"""

import time


class HostStatus:
    def __init__(self, host, purge_elapsed=60 * 3):
        self.host = host
        self.purge_elapsed = purge_elapsed
        self.failures = []            # timestamps
        self.successes = []

    def task_succeed(self, now=None):
        self.successes.append(now if now is not None else time.time())

    def task_failed(self, now=None):
        self.failures.append(now if now is not None else time.time())

    def purge_old(self, now=None):
        now = now if now is not None else time.time()
        horizon = now - self.purge_elapsed
        self.failures = [t for t in self.failures if t >= horizon]
        self.successes = [t for t in self.successes if t >= horizon]

    def recent_failure_rate(self, now=None):
        self.purge_old(now)
        total = len(self.failures) + len(self.successes)
        if not total:
            return 0.0
        return len(self.failures) / total

    def should_forbid(self, now=None, threshold=0.8, min_failures=3):
        self.purge_old(now)
        return (len(self.failures) >= min_failures
                and self.recent_failure_rate(now) >= threshold)


class TaskHostManager:
    def __init__(self, purge_elapsed=60 * 3):
        self.hosts = {}
        self.purge_elapsed = purge_elapsed

    def _host(self, host):
        st = self.hosts.get(host)
        if st is None:
            st = self.hosts[host] = HostStatus(host, self.purge_elapsed)
        return st

    def task_succeed_on(self, host, now=None):
        self._host(host).task_succeed(now)

    def task_failed_on(self, host, now=None):
        self._host(host).task_failed(now)

    def is_blacklisted(self, host, now=None):
        st = self.hosts.get(host)
        return st is not None and st.should_forbid(now)

    def offer_choice(self, hosts, now=None):
        """Pick the best host from candidates: never-blacklisted first,
        fewest recent failures next (reference: task_prefered_hosts)."""
        ranked = self.rank_hosts(hosts, now)
        return ranked[0] if ranked else None

    def rank_hosts(self, hosts, now=None):
        """All candidates, best first: healthy hosts by recent failure
        rate, then blacklisted ones (last resorts, still tried when
        nothing else is left — e.g. every replica of a shuffle bucket
        lives on flagged hosts)."""
        return self.rank_items(hosts, lambda h: h, now)

    def rank_items(self, items, host_of, now=None):
        """rank_hosts generalized to items CARRYING a host (shuffle
        replica uris): one ranking rule for placement and fetch."""
        def key(item):
            h = host_of(item)
            rate = (self.hosts[h].recent_failure_rate(now)
                    if h in self.hosts else 0.0)
            return (self.is_blacklisted(h, now), rate)
        return sorted(items, key=key)
