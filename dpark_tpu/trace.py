"""Trace plane (ISSUE 8 tentpole): low-overhead structured spans and
events across the scheduler, executor, shuffle, coding, dcn, and adapt
seams — per-job/stage/task timelines instead of (only) aggregate
counters, and the mechanism that finally surfaces WORKER-process
observations on the driver.

Modes (``DPARK_TRACE`` env var / conf knob):

    off     no plane installed — one ``is None`` check per site
            (mirroring faults.py; results are bit-identical to any
            traced run, asserted across the chaos matrix in
            tests/test_trace.py)
    ring    spans land in a bounded in-memory ring
            (conf.TRACE_RING_SPANS) — the web UI's /api/trace serves
            it live; nothing touches disk
    spool   ring PLUS per-process crc-framed JSON-lines spool files
            under conf.DPARK_TRACE_DIR (the adapt.py framing: each
            line is ``<crc32 hex> <json>`` appended with one O_APPEND
            write, so concurrent processes interleave whole lines and
            corrupt/truncated lines skip at load).  Worker processes
            spool into the same directory under their own
            ``trace-<host>-<pid>.jsonl``; their cumulative counter
            events land in a small sibling
            ``counters-<host>-<pid>.jsonl`` (so the per-job merge
            never re-parses the span spool), which is how
            multiprocess fault/decode counters merge back into the
            driver's job records (the per-process caveat of PRs 5-7).
            The health plane's per-site latency digests (ISSUE 14)
            get their own ``health-<host>-<pid>.jsonl`` — ONE record,
            atomically rewritten latest-wins, because the cumulative
            digests change with nearly every task and would grow the
            append-only counters file one full-digest line per task.

Span taxonomy (name / cat):

    job, stage, task         "sched"   driver-side lifecycle (job ->
                                       stage -> task parented by the
                                       job/stage/task fields)
    task.run                 "worker"  a task executing in whichever
                                       process ran it (the worker
                                       timeline of a multiproc run)
    stage.exec, wave         "exec"    device stage execution and the
                                       per-wave stream pipeline
    compile, dispatch        "exec"    program cache misses / program
                                       dispatches (instant events)
    phase.ingest_tokenize,   "phase"   per-stage phase totals emitted
    phase.narrow,                      from the SAME _StreamStats
    phase.exchange,                    snapshot scheduler.phase_table()
    phase.spill,                       reads, so the critical-path
    phase.export                       analyzer reconciles with it
    fetch.bucket             "shuffle" one reduce-side bucket fetch
    spill.write, spill.read  "shuffle" spill-run / spill-chunk I/O
    decode.*                 "coding"  erasure-decode outcomes
    dcn.connect,             "dcn"     peer connects / single-frame
    dcn.transfer                       request bytes (the pickled
                                       host bridge)
    dcn.bulk.fetch,          "dcn"     bulk data plane (ISSUE 12):
    dcn.bulk.serve                     chunk-framed streams, bytes +
                                       attempt count in args.  KEPT
                                       DISTINCT from dcn.transfer —
                                       the 2-process parity suite
                                       asserts the hot path emitted
                                       ONLY dcn.bulk.* spans (the
                                       pickled bridge never ran)
    adapt.decision           "adapt"   cost-model choices
    stream.batch             "stream"  one micro-batch tick of an
                                       output chain (driver side)
    stream.pane.build,       "stream"  pane-plane lifecycle (ISSUE
    stream.tree.merge,                 10): pane partials built, merge
    stream.late.patch,                 -tree nodes merged, late-data
    stream.window.emit                 pane patches, and the per-tick
                                       window emit with its branch
                                       count (instant events keyed by
                                       stream id + pane index)
    process.counters         "counters" cumulative per-process fault/
                                       decode counters (the merge
                                       substrate, see
                                       merged_worker_counters)
    aot.load, aot.store,     "aot"     persistent AOT executable
    aot.warm                           cache (ISSUE 17): disk-tier
                                       load/serialize per program and
                                       the boot-warm deserializations
                                       (warm passes run under the
                                       __boot__ pseudo-tenant ctx)
    journal.replay           "sched"   crash-journal replay (ISSUE
                                       20): one instant event per job
                                       whose completed stages were
                                       seeded from the journal, with
                                       resumed_stages and
                                       seeded_partitions in args —
                                       the chaos certification greps
                                       for this

Records are flat dicts: name, cat, ts (epoch seconds), dur (seconds),
pid, host, tid, optional job/stage/task ints, optional args.  The
job/stage/task fields inherit from a thread-local context installed by
the scheduler (``ctx()``), so deep callees (a shuffle fetch inside a
worker task) parent correctly without plumbing ids through every
signature.

On top: ``to_chrome()`` exports merged Chrome trace-event JSON (load
in Perfetto via chrome://tracing or ui.perfetto.dev), and
``critical_path()`` runs a longest-path analysis over the stage DAG
with per-phase blocked fractions.  ``tools/dtrace`` is the CLI.
"""

import json
import os
import socket
import threading
import time
from collections import deque

from dpark_tpu import conf
from dpark_tpu import locks
from dpark_tpu import health as _health
from dpark_tpu import ledger as _ledger

MODES = ("off", "ring", "spool")

# always-armed flight ring (ISSUE 14): warning-and-above events land
# here EVEN IN OFF MODE (a bounded in-memory deque — the cost is one
# append at failure sites, which are rare by definition), so a
# post-mortem flight dump has the recent warning context no matter
# what DPARK_TRACE was.  health.flight_dump snapshots it.
_FLIGHT = deque(maxlen=max(16, int(
    getattr(conf, "FLIGHT_RING_EVENTS", 512) or 512)))

# see TracePlane.run: disambiguates runs minted in the same millisecond
import itertools
_RUN_SEQ = itertools.count(1)

# phase-span names, in scheduler.phase_table() order — the critical
# path analyzer and the reconciliation test share this list
PHASES = ("ingest_tokenize", "narrow", "exchange", "spill", "export")

_PLANE = None
_tls = threading.local()


class _Noop:
    """Shared do-nothing context manager: span()/ctx() with no plane
    installed return this singleton — no allocation on the off path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


def _crc(blob):
    from dpark_tpu.shuffle import spill_crc
    return spill_crc(blob)


class TracePlane:
    def __init__(self, mode, trace_dir, run=None):
        self.mode = mode
        self.dir = trace_dir
        self.ring = deque(maxlen=max(16, int(
            getattr(conf, "TRACE_RING_SPANS", 4096))))
        self.lock = locks.named_lock("trace.plane")
        self.pid = os.getpid()
        self.host = socket.gethostname()
        # every record is stamped with a run id: job ids restart at 1
        # per scheduler, so a spool dir surviving across runs (the
        # default /tmp location) would otherwise merge two runs'
        # "job 1" spans into one bogus timeline.  The driver generates
        # it; workers inherit it through the shipped task environment.
        # A process-local sequence joins the pid+millis stamp: two
        # configure() calls inside one millisecond (fast boxes, tests)
        # must still mint DISTINCT runs.
        self.run = run or "%d-%x-%x" % (self.pid,
                                        int(time.time() * 1000),
                                        next(_RUN_SEQ))
        self.emitted = 0
        self.dropped = 0
        self.spool_path = None
        self.counters_path = None
        self._fd = None
        self._cfd = None
        self._spool_bytes = 0
        self._last_counters = None
        if mode == "spool":
            os.makedirs(trace_dir, exist_ok=True)
            self.spool_path = os.path.join(
                trace_dir, "trace-%s-%d.jsonl" % (self.host, self.pid))
            # counter events go to their own small file so the
            # per-job worker-counter merge never re-parses the span
            # spool (which can run to the DPARK_TRACE_SPOOL_MAX_BYTES
            # cap per process)
            self.counters_path = os.path.join(
                trace_dir, "counters-%s-%d.jsonl" % (self.host,
                                                     self.pid))

    def make(self, name, cat, ts, dur, args):
        """Build one record, folding in the thread-local context.
        job/stage/task may arrive via `args` (explicit wins)."""
        rec = {"name": name, "cat": cat, "ts": round(ts, 6),
               "dur": round(dur, 6), "pid": self.pid,
               "host": self.host, "run": self.run,
               "tid": threading.get_ident() & 0xFFFFFFFF}
        cur = getattr(_tls, "ctx", None)
        for field in ("job", "stage", "task"):
            v = args.pop(field, None)
            if v is None and cur is not None:
                v = cur.get(field)
            if v is not None:
                rec[field] = v
        if args:
            rec["args"] = args
        return rec

    def record(self, rec, always=False):
        """Land one record in the ring (and the spool in spool mode).
        Counter events (`cat == "counters"`) are the cross-process
        merge substrate: they route to the separate counters file,
        bypass the span byte cap, and must never be dropped."""
        sink = _health._SINK
        if sink is not None:
            # health plane (ISSUE 14): fold the record into the
            # streaming sketches as it is emitted — no spool
            # re-parsing, bounded memory, and a fold failure never
            # perturbs the traced job
            try:
                sink.fold(rec)
            except Exception:
                pass
        lsink = _ledger._SINK
        if lsink is not None:
            # resource attribution plane (ISSUE 15): the second record
            # sink — per-(tenant, job, stage, program) accounts fold
            # online under the same never-perturb contract
            try:
                lsink.fold(rec)
            except Exception:
                pass
        args = rec.get("args")
        if args is not None and "error" in args:
            # error-carrying spans mirror into the always-armed flight
            # ring so a later dump has the failure context
            _FLIGHT.append(rec)
        counters = always or rec.get("cat") == "counters"
        with self.lock:
            self.ring.append(rec)
            self.emitted += 1
            if self.spool_path is None:
                return
            if not counters:
                cap = int(getattr(conf, "TRACE_SPOOL_MAX_BYTES", 0)
                          or 0)
                if cap and self._spool_bytes >= cap:
                    self.dropped += 1
                    return
            try:
                from dpark_tpu.utils import frame_jsonl
                line = frame_jsonl(rec)
                if counters:
                    if self._cfd is None:
                        self._cfd = os.open(
                            self.counters_path,
                            os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                            0o644)
                    os.write(self._cfd, line)
                else:
                    if self._fd is None:
                        self._fd = os.open(
                            self.spool_path,
                            os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                            0o644)
                    os.write(self._fd, line)
                    self._spool_bytes += len(line)
            except Exception:
                self.dropped += 1

    def close(self):
        with self.lock:
            for attr in ("_fd", "_cfd"):
                fd = getattr(self, attr)
                if fd is not None:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                    setattr(self, attr, None)


class _Span:
    """Context manager emitting one complete span on exit (errors ride
    as an `error` arg so a failed fetch is visible on the timeline)."""
    __slots__ = ("plane", "name", "cat", "args", "t0")

    def __init__(self, plane, name, cat, args):
        self.plane = plane
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, etype, evalue, tb):
        args = self.args
        if etype is not None:
            args = dict(args, error=etype.__name__)
        self.plane.record(self.plane.make(
            self.name, self.cat, self.t0, time.time() - self.t0, args))
        return False


class _Ctx:
    """Thread-local job/stage/task defaults for nested spans."""
    __slots__ = ("fields", "prev")

    def __init__(self, fields):
        self.fields = fields

    def __enter__(self):
        self.prev = getattr(_tls, "ctx", None)
        merged = dict(self.prev) if self.prev else {}
        merged.update(self.fields)
        _tls.ctx = merged
        return self

    def __exit__(self, *exc):
        _tls.ctx = self.prev
        return False


# ---------------------------------------------------------------------------
# configuration / lifecycle
# ---------------------------------------------------------------------------

def configure(mode=None, trace_dir=None, run=None):
    """Install the trace plane ("off"/None/"" clears it).  Arguments
    fall back to conf.DPARK_TRACE / conf.DPARK_TRACE_DIR.  `run` pins
    the run id (worker processes pass the driver's, shipped via the
    task environment); None starts a fresh run.  Returns the installed
    TracePlane or None."""
    global _PLANE
    if mode is None:
        mode = str(getattr(conf, "DPARK_TRACE", "off") or "off")
    mode = str(mode).lower()
    if mode not in MODES:
        raise ValueError("DPARK_TRACE=%r (expected off|ring|spool)"
                         % mode)
    if _PLANE is not None:
        _PLANE.close()
    if mode == "off":
        _PLANE = None
        return None
    if trace_dir is None:
        trace_dir = getattr(conf, "DPARK_TRACE_DIR", None) \
            or os.path.join(conf.DPARK_WORK_DIR, "trace")
    _PLANE = TracePlane(mode, str(trace_dir), run=run)
    return _PLANE


def active():
    return _PLANE is not None


def mode():
    return _PLANE.mode if _PLANE is not None else "off"


def run_id():
    return _PLANE.run if _PLANE is not None else None


def trace_dir():
    return _PLANE.dir if _PLANE is not None else (
        getattr(conf, "DPARK_TRACE_DIR", None)
        or os.path.join(conf.DPARK_WORK_DIR, "trace"))


# ---------------------------------------------------------------------------
# emission (every entry point is one `is None` check when off)
# ---------------------------------------------------------------------------

def span(name, cat="", **args):
    """Context manager timing a block.  No-op singleton when off."""
    plane = _PLANE
    if plane is None:
        return _NOOP
    return _Span(plane, name, cat, args)


def event(name, cat="", **args):
    """Instant event (dur=0)."""
    plane = _PLANE
    if plane is None:
        return
    plane.record(plane.make(name, cat, time.time(), 0.0, args))


def emit(name, cat, ts, dur, **args):
    """Record a span RETROACTIVELY from measured start/duration (the
    scheduler's task spans are emitted at completion-event time)."""
    plane = _PLANE
    if plane is None:
        return
    plane.record(plane.make(name, cat, ts, dur, args))


def ctx(**fields):
    """Thread-local span context: spans inside the block inherit
    job/stage/task unless set explicitly."""
    if _PLANE is None:
        return _NOOP
    return _Ctx({k: v for k, v in fields.items() if v is not None})


def current_ctx():
    """The calling thread's span-context fields (job/stage/task), or
    None — pool-thread spawners capture this and re-install it in
    their workers so nested spans parent across the thread hop."""
    return getattr(_tls, "ctx", None)


def flight(name, cat="", **args):
    """Warning-and-above instant event: ALWAYS lands in the bounded
    flight ring (even with DPARK_TRACE=off — the ISSUE 14 flight
    recorder contract), and additionally rides the normal plane when
    one is installed.  Only failure sites call this (job abort, stage
    degrade, exhausted fetch replicas, bulk stream give-up), so the
    off-mode cost is one append per rare bad event."""
    plane = _PLANE
    if plane is not None:
        rec = plane.make(name, cat, time.time(), 0.0, dict(args))
        rec["sev"] = "warn"
        # record() already mirrors error-carrying records into the
        # flight ring — only append here when it won't, so one
        # failure never occupies two ring slots
        plane.record(rec)
        if "error" not in args:
            _FLIGHT.append(rec)
    else:
        rec = {"name": name, "cat": cat,
               "ts": round(time.time(), 6), "dur": 0.0,
               "pid": os.getpid(), "host": socket.gethostname(),
               "tid": threading.get_ident() & 0xFFFFFFFF,
               "sev": "warn"}
        if args:
            rec["args"] = args
        sink = _health._SINK
        if sink is not None:
            try:
                sink.fold(rec)
            except Exception:
                pass
        _FLIGHT.append(rec)


def flight_snapshot():
    """The always-armed warning ring's contents (oldest first)."""
    return list(_FLIGHT)


def emit_process_counters():
    """Append this process's CUMULATIVE fault/decode counters as a
    `counters` event (spool mode only).  Workers call this at task
    end; the driver merges the latest event per process — the
    mechanism that closes the multiprocess counter blindspot."""
    plane = _PLANE
    if plane is None or plane.mode != "spool":
        return
    try:
        from dpark_tpu import coding, faults
        snap = coding.counters_snapshot()
        args = {"faults": faults.stats(),
                "decodes": snap["totals"],
                "decodes_per_shuffle": snap["per_shuffle"]}
        _write_process_health(plane)
        _write_process_ledger(plane)
        # cumulative counters only change when a fault fires or a
        # decode happens — skip the write when nothing did, so a
        # long-lived worker running many tasks doesn't grow the
        # counters file one line per task
        key = json.dumps(args, sort_keys=True)
        if key == plane._last_counters:
            return
        rec = plane.make("process.counters", "counters", time.time(),
                         0.0, args)
        plane.record(rec, always=True)
        plane._last_counters = key
    except Exception:
        pass


def _write_process_health(plane):
    """Health plane (ISSUE 14): rewrite this process's per-site
    latency digests as ONE crc-framed record in its own
    ``health-<host>-<pid>.jsonl`` (tmp+rename, latest-wins), so the
    driver's merged tails include MULTIPROC fetches — the worker-tail
    half of the ROADMAP item 5 handoff.  Digests are cumulative and
    change with nearly every task, so they must NOT ride the
    append-only counters file (it would grow one full-digest line per
    task and is deliberately uncapped); an atomic rewrite keeps the
    on-disk cost O(1) per process no matter how many tasks run."""
    sink = _health._SINK
    if sink is None:
        return
    try:
        digests = sink.site_digests()
        if not digests:
            return
        key = json.dumps(digests, sort_keys=True)
        if key == getattr(plane, "_last_health", None):
            return
        from dpark_tpu.utils import frame_jsonl
        rec = plane.make("process.health", "counters", time.time(),
                         0.0, {"health": digests})
        path = os.path.join(plane.dir, "health-%s-%d.jsonl"
                            % (plane.host, plane.pid))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame_jsonl(rec))
        os.replace(tmp, path)
        plane._last_health = key
    except Exception:
        pass


def _write_process_ledger(plane):
    """Resource attribution plane (ISSUE 15): rewrite this process's
    per-account ledger digests as ONE crc-framed record in its own
    ``ledger-<host>-<pid>.jsonl`` (tmp+rename, latest-wins — the
    health-<host>-<pid>.jsonl idiom), so the driver's merged accounts
    include MULTIPROC workers' fetch/spill activity attributed to the
    jobs that caused it.  Cumulative digests change with nearly every
    task, so the on-disk cost stays O(1) per process."""
    sink = _ledger._SINK
    if sink is None:
        return
    try:
        digests = sink.account_digests()
        if not digests:
            return
        key = json.dumps(digests, sort_keys=True)
        if key == getattr(plane, "_last_ledger", None):
            return
        from dpark_tpu.utils import frame_jsonl
        rec = plane.make("process.ledger", "counters", time.time(),
                         0.0, {"ledger": digests})
        path = os.path.join(plane.dir, "ledger-%s-%d.jsonl"
                            % (plane.host, plane.pid))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame_jsonl(rec))
        os.replace(tmp, path)
        plane._last_ledger = key
    except Exception:
        pass


# jax backend-compile timing (ISSUE 15): jax.monitoring reports the
# REAL XLA compile wall per event — the executor installs this once
# per process and stamps the program signature it is dispatching for
# in a thread-local, so compile.backend spans attribute to the right
# (job, stage, program) account.  One predicate per compile when the
# plane is off; compiles are rare by definition.
_compile_listener_installed = False


def set_compile_sig(sig):
    """Stamp the program signature subsequent backend compiles on THIS
    thread should attribute to (None clears)."""
    _tls.compile_sig = sig


def suppress_compile_spans(flag):
    """Gate compile.backend emission on THIS thread: the ledger's
    cost-capture compile (DPARK_LEDGER_COST=compile) is plane
    overhead, not tenant consumption — emitting a span for it would
    double-bill the program's compile_ms."""
    _tls.no_compile_spans = bool(flag)


def install_compile_listener():
    """Register the jax.monitoring duration listener that turns
    backend compiles into measured ``compile.backend`` spans.  Safe to
    call repeatedly; a jax without the monitoring API is a no-op."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True
    try:
        from jax import monitoring

        def _on_duration(event, duration, **kw):
            plane = _PLANE
            if plane is None:
                return
            if getattr(_tls, "no_compile_spans", False):
                return           # ledger cost-capture compile
            if not str(event).endswith("backend_compile_duration"):
                return
            try:
                sig = getattr(_tls, "compile_sig", None)
                args = {"sig": sig} if sig else {}
                plane.record(plane.make(
                    "compile.backend", "exec",
                    time.time() - float(duration), float(duration),
                    args))
            except Exception:
                pass

        monitoring.register_event_duration_secs_listener(_on_duration)
        _compile_listener_installed = True
        return True
    except Exception:
        return False


def counts():
    """(emitted, dropped) for the installed plane, (0, 0) when off."""
    plane = _PLANE
    if plane is None:
        return (0, 0)
    return (plane.emitted, plane.dropped)


# ---------------------------------------------------------------------------
# reading back: ring snapshots, spool loads, worker-counter merges
# ---------------------------------------------------------------------------

def snapshot():
    """This process's ring contents (oldest first)."""
    plane = _PLANE
    if plane is None:
        return []
    with plane.lock:
        return list(plane.ring)


def _read_framed(path, out):
    """Append one crc-framed JSON-lines file's valid records to `out`,
    skipping corrupt/truncated lines — never an error."""
    from dpark_tpu.utils import unframe_jsonl
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return
    out.extend(unframe_jsonl(raw)[0])


def read_spool(trace_dir=None, prefixes=("trace-", "counters-")):
    """Load every spool file under `trace_dir` (default: the active
    plane's dir) whose name starts with one of `prefixes`, skipping
    corrupt/truncated lines — never an error.  Returns records sorted
    by ts."""
    d = trace_dir if trace_dir is not None \
        else globals()["trace_dir"]()
    out = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for fn in names:
        if not (fn.endswith(".jsonl") and fn.startswith(prefixes)):
            continue
        _read_framed(os.path.join(d, fn), out)
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def collected(job=None):
    """Everything this process can see: the merged spool (spool mode —
    includes worker processes) or the local ring, optionally filtered
    to one job id.  Restricted to the CURRENT run — a spool dir
    surviving from an earlier run (the default /tmp location) must not
    leak its same-numbered jobs into this run's timeline."""
    plane = _PLANE
    if plane is None:
        return []
    recs = read_spool(plane.dir) if plane.mode == "spool" \
        else snapshot()
    recs = [r for r in recs if r.get("run") == plane.run]
    if job is not None:
        recs = [r for r in recs if r.get("job") == job]
    return recs


def merged_worker_counters(trace_dir=None, include_self=False,
                           run=None):
    """Sum the LATEST `process.counters` event of every OTHER process
    in the spool: {"faults": {site: {hits, fired}}, "decodes":
    {kind: n}, "decodes_per_shuffle": {sid: {kind: n}},
    "processes": n}.  Counter events are cumulative per process, so
    the newest per (host, pid) is that process's total.  Reads ONLY
    the small per-process counters files, not the span spool — the
    merge runs at every job start/finish and must stay cheap no
    matter how many spans the workers wrote.  `run` restricts to one
    run id (default: the active plane's — dead pids from an earlier
    run sharing the spool dir must not contribute phantom counters);
    pass run=False to merge across runs."""
    if run is None and _PLANE is not None:
        run = _PLANE.run
    me = os.getpid()
    latest = {}
    latest_health = {}
    latest_ledger = {}
    for rec in read_spool(trace_dir, prefixes=("counters-",
                                               "health-",
                                               "ledger-")):
        if rec.get("cat") != "counters":
            continue
        if run and rec.get("run") != run:
            continue
        pid = rec.get("pid")
        if not include_self and pid == me \
                and rec.get("host") == socket.gethostname():
            continue
        args = rec.get("args") or {}
        if rec.get("name") == "process.health":
            # the per-process health digest file (latest-wins
            # rewrite, one record per process — see
            # _write_process_health)
            latest_health[(rec.get("host"), pid)] = \
                args.get("health") or {}
        elif rec.get("name") == "process.ledger":
            # the per-process ledger digest file (ISSUE 15; same
            # latest-wins O(1) idiom — see _write_process_ledger)
            latest_ledger[(rec.get("host"), pid)] = \
                args.get("ledger") or {}
        else:
            latest[(rec.get("host"), pid)] = args
    out = {"faults": {}, "decodes": {}, "decodes_per_shuffle": {},
           "health": {}, "ledger": {}, "processes": len(latest)}
    for digests in latest_health.values():
        for site, digest in digests.items():
            out["health"][site] = _health.merge_digests(
                out["health"].get(site), digest)
    for digests in latest_ledger.values():
        for key, digest in digests.items():
            out["ledger"][key] = _ledger.merge_account_digests(
                out["ledger"].get(key), digest)
    for args in latest.values():
        for site, st in (args.get("faults") or {}).items():
            ent = out["faults"].setdefault(site,
                                           {"hits": 0, "fired": 0})
            ent["hits"] += int(st.get("hits", 0))
            ent["fired"] += int(st.get("fired", 0))
        for kind, v in (args.get("decodes") or {}).items():
            if kind == "mode":
                continue
            out["decodes"][kind] = out["decodes"].get(kind, 0) + int(v)
        for sid, per in (args.get("decodes_per_shuffle")
                         or {}).items():
            try:
                sid = int(sid)        # JSON round-trips keys as str
            except (TypeError, ValueError):
                pass
            ent = out["decodes_per_shuffle"].setdefault(sid, {})
            for kind, v in per.items():
                ent[kind] = ent.get(kind, 0) + int(v)
    return out


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

def to_chrome(records):
    """Merged records -> Chrome trace-event JSON (dict; json.dump it).
    Complete spans become ph="X" with microsecond ts/dur; instant
    events ph="i"; each (host, pid) gets a process_name metadata row
    so worker processes are visually distinct."""
    events = []
    procs = {}
    for rec in records:
        pid = int(rec.get("pid", 0))
        host = rec.get("host", "")
        if (host, pid) not in procs:
            procs[(host, pid)] = True
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid, "tid": 0,
                           "args": {"name": "%s:%d" % (host, pid)}})
        args = dict(rec.get("args") or {})
        for field in ("job", "stage", "task"):
            if field in rec:
                args[field] = rec[field]
        ev = {"name": rec.get("name", "?"),
              "cat": rec.get("cat", "") or "misc",
              "pid": pid, "tid": int(rec.get("tid", 0)),
              "ts": round(float(rec.get("ts", 0.0)) * 1e6, 1),
              "args": args}
        dur = float(rec.get("dur", 0.0))
        if rec.get("cat") == "counters":
            continue                 # merge substrate, not timeline
        if dur > 0:
            ev["ph"] = "X"
            ev["dur"] = round(dur * 1e6, 1)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# critical-path analysis over the span DAG
# ---------------------------------------------------------------------------

def job_ids(records):
    return sorted({r["job"] for r in records
                   if r.get("name") == "job" and "job" in r})


def critical_path(records, job=None):
    """Longest stage chain bounding one job's wall clock, with the
    per-phase attribution of the chain's stages.

    The DAG: stage spans carry their `parents` (the scheduler's stage
    dependencies); cp(stage) = dur(stage) + max(cp(parent)); the chain
    is read off the argmax backpointers from the terminal stage.
    Phase totals come from the `phase` spans (emitted from the same
    _StreamStats snapshot scheduler.phase_table() reads, so the two
    reconcile) plus fetch spans; the remainder of a stage's wall is
    `other` (host/object work, scheduling).  Returns None when the
    job has no span."""
    if job is None:
        jobs = job_ids(records)
        if not jobs:
            return None
        job = jobs[-1]
    job_span = None
    stages = {}
    for rec in records:
        if rec.get("job") != job:
            continue
        name = rec.get("name")
        if name == "job":
            job_span = rec
        elif name == "stage" and "stage" in rec:
            stages[rec["stage"]] = rec
    if job_span is None and not stages:
        return None
    parents = {sid: [p for p in (rec.get("args", {}).get("parents")
                                 or []) if p in stages]
               for sid, rec in stages.items()}
    # longest path by stage duration (memoized DFS; the stage DAG is
    # acyclic by construction)
    memo = {}

    def cp(sid):
        if sid in memo:
            return memo[sid]
        memo[sid] = (0.0, None)         # cycle guard
        dur = float(stages[sid].get("dur", 0.0))
        best, back = dur, None
        for p in parents.get(sid, ()):
            c, _ = cp(p)
            if dur + c > best:
                best, back = dur + c, p
        memo[sid] = (best, back)
        return memo[sid]

    has_child = {p for ps in parents.values() for p in ps}
    terminals = [s for s in stages if s not in has_child] \
        or list(stages)
    chain = []
    if terminals:
        head = max(terminals, key=lambda s: cp(s)[0])
        while head is not None:
            chain.append(head)
            head = cp(chain[-1])[1]
        chain.reverse()
    # phase attribution over the chain's stages
    phases = {p: 0.0 for p in PHASES}
    phases["fetch"] = 0.0
    chain_set = set(chain)
    for rec in records:
        if rec.get("job") != job or rec.get("stage") not in chain_set:
            continue
        name = rec.get("name", "")
        if rec.get("cat") == "phase" and name.startswith("phase."):
            key = name[len("phase."):]
            phases[key] = phases.get(key, 0.0) \
                + float(rec.get("dur", 0.0))
        elif name == "fetch.bucket":
            phases["fetch"] += float(rec.get("dur", 0.0))
    chain_wall = sum(float(stages[s].get("dur", 0.0)) for s in chain)
    attributed = sum(phases.values())
    phases["other"] = max(0.0, chain_wall - attributed)
    total = max(sum(phases.values()), 1e-9)
    blocked = {k: round(v / total, 4) for k, v in phases.items() if v}
    bound = max(blocked, key=blocked.get) if blocked else None
    return {
        "job": job,
        "wall_s": round(float(job_span.get("dur", chain_wall)), 6)
        if job_span is not None else round(chain_wall, 6),
        "chain": chain,
        "chain_wall_s": round(chain_wall, 6),
        "phases_s": {k: round(v, 6) for k, v in phases.items()},
        "blocked_frac": blocked,
        "bound": bound,
        "spans": sum(1 for r in records if r.get("job") == job),
    }


def summary():
    """The `trace` section for bench artifacts: mode, span counts, and
    (when tracing) the critical-path summary of the longest-running
    traced job."""
    emitted, dropped = counts()
    out = {"mode": mode(), "spans": emitted, "dropped": dropped}
    plane = _PLANE
    if plane is None:
        return out
    if plane.mode == "spool":
        out["dir"] = plane.dir
    try:
        recs = collected()
        best = None
        for j in job_ids(recs):
            cp = critical_path(recs, j)
            if cp and (best is None or cp["wall_s"] > best["wall_s"]):
                best = cp
        out["critical_path"] = best
    except Exception:
        out["critical_path"] = None
    return out


def _init_from_conf():
    m = str(getattr(conf, "DPARK_TRACE", "off") or "off")
    if m != "off":
        configure(m)


_init_from_conf()
