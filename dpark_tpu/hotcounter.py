"""Space-saving top-k counter.

Reference parity: dpark/hotcounter.py (SURVEY.md section 2.1) — bounded-
memory heavy-hitters behind rdd.hot() for very high-cardinality streams.
The exact rdd.hot() path uses a full reduceByKey (dpark_tpu/rdd.py hot());
HotCounter is the approximate alternative for when the key space does not
fit (Metwally et al. space-saving algorithm).
"""


class HotCounter:
    def __init__(self, capacity=1000):
        self.capacity = capacity
        self.counts = {}          # value -> (count, error)

    def add(self, value, count=1):
        c = self.counts
        if value in c:
            cnt, err = c[value]
            c[value] = (cnt + count, err)
        elif len(c) < self.capacity:
            c[value] = (count, 0)
        else:
            # evict the minimum, inherit its count as error bound
            victim = min(c, key=lambda k: c[k][0])
            vcnt, _ = c.pop(victim)
            c[value] = (vcnt + count, vcnt)

    def update(self, other):
        for value, (cnt, err) in other.counts.items():
            self.add(value, cnt)
        return self

    def top(self, n=10):
        items = sorted(self.counts.items(), key=lambda kv: -kv[1][0])
        return [(v, cnt) for v, (cnt, err) in items[:n]]
