"""DAG scheduler + masters (local, process; tpu lives in backend/tpu).

Reference parity: dpark/schedule.py — Stage (cut at ShuffleDependency
edges), DAGScheduler.runJob as a generator yielding per-partition results,
newStage/getParentStages/getMissingParentStages/submitStage/
submitMissingTasks/taskEnded, FetchFailed -> parent stage resubmit;
LocalScheduler and MultiProcessScheduler masters (SURVEY.md sections 2.1,
3.1, 5.3).  The MesosScheduler has no TPU-era equivalent; multi-host
dispatch belongs to the DCN layer (see backend/).
"""

import multiprocessing
import pickle
import queue
import threading
import traceback

import sys

from dpark_tpu import conf, locks, serialize, trace


def _submodule(name):
    """Resolve a dpark_tpu submodule even when a convenience function in
    dpark_tpu/__init__ shadows the package attribute of the same name."""
    import importlib
    return importlib.import_module("dpark_tpu." + name)


accumulator = _submodule("accumulator")
from dpark_tpu.dependency import ShuffleDependency
from dpark_tpu.env import env
from dpark_tpu.shuffle import FetchFailed
from dpark_tpu.task import ResultTask, ShuffleMapTask
from dpark_tpu.utils.log import Progress, get_logger

logger = get_logger("schedule")

# /metrics phase-seconds histogram bucket edges (seconds); the web
# renderer cumulates these into Prometheus le= buckets
PHASE_BUCKETS = (0.05, 0.25, 1.0, 5.0, 30.0, 120.0)


class Stage:
    # itertools.count: atomic under the GIL — concurrent drivers on a
    # resident job server (ISSUE 9) mint stage ids from their own
    # threads, and a read-modify-write counter could hand two stages
    # one id
    _next_id = __import__("itertools").count(1)

    def __init__(self, rdd, shuffle_dep, parents):
        self.id = next(Stage._next_id)
        self.rdd = rdd
        self.shuffle_dep = shuffle_dep          # None for a result stage
        self.parents = parents
        self.num_partitions = len(rdd.splits)
        # per-map-partition output URI when this is a shuffle map stage
        self.output_locs = [None] * self.num_partitions

    @property
    def is_shuffle_map(self):
        return self.shuffle_dep is not None

    @property
    def is_available(self):
        if not self.is_shuffle_map:
            return False
        return all(loc is not None for loc in self.output_locs)

    def add_output_loc(self, partition, uri):
        self.output_locs[partition] = uri

    def remove_outputs_by_uri(self, uri):
        for i, loc in enumerate(self.output_locs):
            if loc == uri:
                self.output_locs[i] = None

    def __repr__(self):
        return "<Stage %d on %r>" % (self.id, self.rdd)


class DAGScheduler:
    """Walks the RDD dependency graph bottom-up, running stages whose
    parents are available; master-specific subclasses implement
    submit_tasks()."""

    def __init__(self):
        from dpark_tpu.env import env
        self.shuffle_to_stage = {}
        self.started = False
        self.profile = None            # MergedProfile when --profile
        # host health, SHARED with the shuffle fetcher's replica choice
        # through env (trivial on single-host masters; the multi-host
        # DCN paths consult is_blacklisted/offer_choice/rank_hosts);
        # env constructs it unconditionally
        self.host_manager = env.host_manager
        self.history = []              # job records for the web UI
        self._next_job_id = 0
        # guards history-list mutation vs the web server's /metrics
        # snapshot (ISSUE 8 satellite: a scrape mid-job must never
        # throw); per-record field mutation stays lock-free — the
        # snapshot copies defensively.  The archive keeps aggregates
        # of records trimmed out of the 100-job window so /metrics
        # counters never decrease.
        self._metrics_lock = locks.named_lock(
            "schedule.metrics", reentrant=True)
        self._metrics_archive = self._new_metrics()
        # resident job server (ISSUE 9): when attached, stage
        # execution routes through the server's fair dispatcher
        # instead of running inline — one `is None` check per submit
        # seam, so a service-less process pays nothing
        self._service = None
        # per-driver-thread state: with N drivers multiplexed onto one
        # scheduler, the "current" job record is whichever job THIS
        # thread is building/executing (the slot threads set it around
        # each stage execution); _last_record keeps the single-thread
        # fallback for embedders that read it from another thread
        self._tls = threading.local()
        self._last_record = None
        # guards the shared stage graph (shuffle_to_stage) against
        # concurrent run_job invocations from different driver threads
        self._graph_lock = locks.named_lock(
            "schedule.graph", reentrant=True)

    # -- lifecycle -------------------------------------------------------
    def start(self):
        self.started = True

    def stop(self):
        self.started = False

    # -- per-thread current record (ISSUE 9) -----------------------------
    # note_stage() and the executor's _stage_note callback attribute
    # to whichever job the CALLING thread is working on: driver
    # threads set it when they mint a record, the job server's slot
    # threads set it around each stage execution.  Single-threaded
    # schedulers see the exact pre-service behavior through the
    # _last_record fallback.
    @property
    def _current_record(self):
        rec = getattr(self._tls, "record", None)
        return rec if rec is not None else self._last_record

    @_current_record.setter
    def _current_record(self, rec):
        self._tls.record = rec
        self._last_record = rec

    # -- stage graph -----------------------------------------------------
    def new_stage(self, rdd, shuffle_dep):
        with self._graph_lock:
            return Stage(rdd, shuffle_dep, self.get_parent_stages(rdd))

    def get_shuffle_map_stage(self, dep):
        with self._graph_lock:
            stage = self.shuffle_to_stage.get(dep.shuffle_id)
            if stage is None:
                stage = self.new_stage(dep.rdd, dep)
                self.shuffle_to_stage[dep.shuffle_id] = stage
            return stage

    def get_parent_stages(self, rdd):
        with self._graph_lock:
            return self._get_parent_stages_locked(rdd)

    def _get_parent_stages_locked(self, rdd):
        parents = []
        visited = set()

        def visit(r):
            if r.id in visited:
                return
            visited.add(r.id)
            for dep in r.dependencies:
                if isinstance(dep, ShuffleDependency):
                    stage = self.get_shuffle_map_stage(dep)
                    if stage not in parents:
                        parents.append(stage)
                else:
                    visit(dep.rdd)
        visit(rdd)
        return parents

    def get_missing_parent_stages(self, stage):
        return [p for p in stage.parents if not p.is_available]

    def _needed_shuffles(self, rdd, acc=None, visited=None,
                         transitive=False):
        """Shuffle ids reachable through NARROW deps — exactly what a
        task over `rdd` fetches, the multiprocess master's per-task
        map-output snapshot.  `transitive=True` additionally walks
        PAST shuffle boundaries: the whole lineage's shuffle ids, for
        per-job decode attribution under concurrent jobs (ISSUE 9) —
        that set must not ride every task message."""
        acc = acc if acc is not None else set()
        visited = visited if visited is not None else set()
        if rdd.id in visited:
            return acc
        visited.add(rdd.id)
        for dep in rdd.dependencies:
            if isinstance(dep, ShuffleDependency):
                acc.add(dep.shuffle_id)
                if not transitive:
                    continue
            self._needed_shuffles(dep.rdd, acc, visited, transitive)
        return acc

    # -- the job loop ----------------------------------------------------
    def run_job(self, final_rdd, func, partitions=None, allow_local=False):
        """Generator yielding per-partition results IN PARTITION ORDER
        (buffering completions that arrive early)."""
        if partitions is None:
            partitions = list(range(len(final_rdd.splits)))
        if not partitions:
            return
        import time as _time
        # allowLocal fast path (reference: runJob allowLocal) — single
        # partition, no shuffle parents: compute inline, no tasks.
        final_stage = self.new_stage(final_rdd, None)
        if (allow_local and len(partitions) == 1 and not final_stage.parents):
            record = self._new_job_record(final_rdd, 1, stages=0)
            t0 = _time.time()
            try:
                yield func(final_rdd.iterator(
                    final_rdd.splits[partitions[0]]))
                record["finished"] = 1
                record["state"] = "done"
            except GeneratorExit:
                record["state"] = "partial"    # take/first stopped early
                raise
            except BaseException:
                record["state"] = "aborted"
                raise
            finally:
                record["seconds"] = round(_time.time() - t0, 3)
                record.pop("_t_submit", None)
                self._finalize_decodes(record)
                self._trace_job_span(record, t0)
                self._finalize_health(record)
                self._job_finished(record)
            return

        output_parts = list(partitions)
        part_index = {p: i for i, p in enumerate(output_parts)}
        finished = [False] * len(output_parts)
        results = [None] * len(output_parts)

        # job-scoped event queue: tasks submitted by THIS job report here,
        # so a generator abandoned mid-iteration (take/iterate) can never
        # leak its late completions into a subsequent job's loop
        events = queue.Queue()
        in_flight = [0]          # submitted tasks whose event hasn't arrived

        def report(task, status, payload):
            events.put((task, status, payload))

        waiting = set()         # stages blocked on parents
        running = set()         # stages with submitted tasks
        pending_tasks = {}      # stage -> set of partition ids not yet done
        failures = {}           # task partition retry counters per stage
        stage_failures = {}     # stage id -> lineage-recovery rounds
        #   (FetchFailed resubmits/recomputes), capped by
        #   conf.MAX_STAGE_FAILURES so a persistently failing shuffle
        #   source aborts with a chained error instead of looping
        progress = Progress(final_rdd.scope_name, len(output_parts))

        record = self._new_job_record(final_rdd, len(output_parts))
        job_t0 = _time.time()

        stage_of = {}

        def submit_stage(stage):
            stage_of[stage.id] = stage
            if stage in waiting or stage in running:
                return
            missing = self.get_missing_parent_stages(stage)
            if not missing:
                submit_missing_tasks(stage)
                running.add(stage)
            else:
                waiting.add(stage)
                for p in missing:
                    submit_stage(p)

        submitted_at = {}       # (stage_id, partition) -> last submit time

        def submit_missing_tasks(stage):
            tasks = []
            if stage.is_shuffle_map:
                self._maybe_choose_code(stage.shuffle_dep)
                for p in range(stage.num_partitions):
                    if stage.output_locs[p] is None:
                        tasks.append(ShuffleMapTask(
                            stage.id, stage.rdd, stage.shuffle_dep, p))
            else:
                for p in output_parts:
                    if not finished[part_index[p]]:
                        tasks.append(ResultTask(
                            stage.id, final_rdd, func, p, part_index[p]))
            pending_tasks.setdefault(stage, set()).update(
                t.partition for t in tasks)
            now = _time.time()
            for t in tasks:
                submitted_at[(stage.id, t.partition)] = now
            info = self._stage_info(record, stage.id)
            info.update({"rdd": type(stage.rdd).__name__,
                         "parts": stage.num_partitions,
                         "shuffle": stage.is_shuffle_map,
                         "parents": [p.id for p in stage.parents],
                         "started": now})
            # pane-plane attribution (ISSUE 10): windowed DStreams tag
            # the RDDs they build ({stream, role, pane} — pane-build /
            # tree-merge / late-patch / window-emit), so a stage's
            # cost lands on the pane-plane role that caused it in the
            # web UI and trace analysis
            stream_tag = getattr(stage.rdd, "_stream_tag", None)
            if stream_tag:
                info["stream"] = dict(stream_tag)
            logger.debug("submit stage %s with %d tasks", stage, len(tasks))
            in_flight[0] += len(tasks)
            if trace._PLANE is not None:
                # tasks carry the job id so worker-process task.run
                # spans parent correctly after the serialize trip
                for t in tasks:
                    t._trace_job = record["id"]
            with trace.ctx(job=record["id"], stage=stage.id):
                self._dispatch(stage, tasks, report, record)

        def spawn_duplicate(stage, p):
            """Speculative copy of a straggling task (first result wins)."""
            if stage.is_shuffle_map:
                t = ShuffleMapTask(stage.id, stage.rdd,
                                   stage.shuffle_dep, p)
            else:
                t = ResultTask(stage.id, final_rdd, func, p, part_index[p])
            in_flight[0] += 1
            record["speculated"] = record.get("speculated", 0) + 1
            logger.info("speculatively re-launching %r", t)
            if trace._PLANE is not None:
                t._trace_job = record["id"]
            with trace.ctx(job=record["id"], stage=stage.id):
                self._dispatch(stage, [t], report, record)

        # crash-consistent journal (ISSUE 20): write-ahead the job,
        # then seed any journaled stage completions whose outputs
        # survived a controller death — the submit below skips them
        from dpark_tpu import journal
        if journal._PLANE is not None:
            record["_jfp"] = journal.job_fingerprint(final_rdd,
                                                     output_parts)
            journal.append_job(record["_jfp"], final_rdd.scope_name)
            journal.seed_stages(self, final_stage, record,
                                record["_jfp"])

        submit_stage(final_stage)
        record["stages"] = len(stage_of)

        try:
            yield from self._event_loop(
                output_parts, finished, results, events, in_flight,
                waiting, running, pending_tasks, failures, progress,
                stage_of, submit_stage, submit_missing_tasks, record,
                report, submitted_at, spawn_duplicate, stage_failures)
        except GeneratorExit:
            # consumer stopped early (take/first/iterate) — by design
            record["state"] = "partial"
            raise
        finally:
            if record["state"] == "running":
                record["state"] = "done" if all(finished) else "aborted"
            record["seconds"] = round(_time.time() - job_t0, 3)
            record.pop("_t_submit", None)
            jfp = record.pop("_jfp", None)
            if jfp is not None and record["state"] == "done":
                journal.append_job_done(jfp)
            self._finalize_decodes(record)
            self._finalize_exchanges(record)
            self._finalize_adapt(record)
            self._trace_job_span(record, job_t0)
            self._finalize_health(record)
            self._job_finished(record)

    def _new_job_record(self, final_rdd, parts, stages=1):
        import time as _time
        with self._metrics_lock:
            self._next_job_id += 1
            job_id = self._next_job_id
        record = {"id": job_id, "scope": final_rdd.scope_name,
                  "parts": parts, "finished": 0, "stages": stages,
                  "seconds": 0.0, "state": "running", "stage_info": [],
                  # pre-flight lint findings (context.runJob stashes
                  # them on the final rdd) ride the job record so the
                  # web UI shows WHY a plan is suspect next to its
                  # per-stage timings
                  "lint": list(getattr(final_rdd, "_lint_findings",
                                       ()) or ())}
        # pane-plane job attribution (ISSUE 10): a job collecting a
        # windowed stream's emitted RDD carries that stream's tag
        stream_tag = getattr(final_rdd, "_stream_tag", None)
        if stream_tag:
            record["stream"] = dict(stream_tag)
        # coded-shuffle decode accounting (ISSUE 6): counters are
        # process-global, so each job snapshots a baseline at start
        # and takes the delta at finish (popped before the record
        # ships as JSON)
        from dpark_tpu import coding
        record["_decode_base"] = coding.counters_snapshot()
        # adaptive-execution accounting (ISSUE 7): the decision log is
        # process-global too — snapshot its position (and reset the
        # per-job de-dup epoch) so the decisions taken DURING this job
        # (steered or observe-mode would-be) ride this record as
        # record["adapt"], including choices repeated from a prior job
        from dpark_tpu import adapt
        try:
            record["_adapt_base"] = adapt.begin_job()
        except Exception:
            pass
        # worker-counter merge (ISSUE 8 satellite): with spool tracing
        # on, worker processes append cumulative fault/decode counters
        # to the trace spool — snapshot the merged view now so this
        # job's delta attributes only ITS decode activity
        if trace.mode() == "spool":
            try:
                record["_trace_decode_base"] = \
                    trace.merged_worker_counters()
            except Exception:
                pass
        # resident-service bookkeeping (ISSUE 9): tag the record with
        # the submitting client, stamp submit time (queue-wait and
        # first-wave latency measure from it), and pre-walk the
        # lineage's shuffle ids so decode attribution under CONCURRENT
        # jobs restricts to this job's own shuffles instead of the
        # overlapping process-global totals delta
        if self._service is not None:
            record["service"] = True
            record["_t_submit"] = _time.time()
            client = getattr(self._tls, "client", None)
            if client:
                record["client"] = client
            try:
                # a sorted list, not a set: /api/jobs may serialize
                # the record as JSON while the job is still running
                record["_sids"] = sorted(self._needed_shuffles(
                    final_rdd, transitive=True))
            except Exception:
                pass
        with self._metrics_lock:
            self.history.append(record)
            dropped = self.history[:-100]
            if dropped:
                self._archive_metrics(dropped)
            del self.history[:-100]
        self._current_record = record
        # resource attribution (ISSUE 15): register job -> tenant so
        # the ledger's accounts roll up per client (one `is None`
        # check when the plane is off; "local" on single-tenant
        # masters)
        from dpark_tpu import ledger
        if ledger._SINK is not None:
            ledger.note_job(record["id"], record.get("client"))
        self._job_started(record)
        return record

    def _job_started(self, record):
        """Hook: a job record was minted (the tpu master pins the
        job's HBM buckets and snapshots program-cache counters)."""

    def _job_finished(self, record):
        """Hook: the job finalized (counters attributed, pins
        released)."""

    def _finalize_health(self, record):
        """Health-plane job hook (ISSUE 14): per-tenant SLO accounting
        (resident service), flight-recorder dump on abort, throttled
        site-tail persistence into the adapt store.  One call per job;
        every branch inside is a cheap predicate and never raises."""
        from dpark_tpu import health
        health.job_finished(self, record)

    def _trace_job_span(self, record, t0):
        """Emit the job's span (trace plane, ISSUE 8) — the root of
        the per-job timeline tools/dtrace analyzes."""
        if trace._PLANE is None:
            return
        trace.emit("job", "sched", t0, record.get("seconds", 0.0),
                   job=record["id"], scope=record.get("scope"),
                   state=record.get("state"),
                   stages=record.get("stages"),
                   # tenant identity rides the span so the OFFLINE
                   # ledger twin (dtrace --ledger) resolves accounts
                   # to tenants from a spool alone (ISSUE 15)
                   client=record.get("client") or "local")

    def _finalize_decodes(self, record):
        """Attribute coded-shuffle decode activity since the job
        started to this job record (ISSUE 6): the totals delta rides
        as ``record["decodes"]`` (repair = parity replaced a FAILED
        shard, straggler_win = parity merely beat a slow one,
        decode_failures = fewer than k survived and lineage had to
        pay), and per-shuffle deltas land on the PARENT stage whose
        outputs were decoded — the web UI's per-stage decode
        column."""
        from dpark_tpu import coding
        base = record.pop("_decode_base", None)
        sids = record.pop("_sids", None)
        if base is None:
            return
        snap = coding.counters_snapshot()
        base_per = base.get("per_shuffle", {})
        per_deltas = {}
        for sid, counts in snap.get("per_shuffle", {}).items():
            prev = base_per.get(sid, {})
            delta = {k: v - prev.get(k, 0) for k, v in counts.items()}
            if any(delta.values()):
                per_deltas[sid] = delta
        if sids is not None:
            # concurrent jobs on a resident service (ISSUE 9): the
            # process-global totals delta overlaps with every other
            # in-flight job — attribute only the per-shuffle deltas of
            # THIS job's own lineage, so records never cross-contaminate
            totals = {k: 0 for k in snap["totals"]}
            for sid in sids:
                for k, v in per_deltas.get(sid, {}).items():
                    totals[k] = totals.get(k, 0) + v
        else:
            base_totals = base.get("totals", {})
            totals = {k: v - base_totals.get(k, 0)
                      for k, v in snap["totals"].items()}
        if any(totals.values()) or coding.active():
            record["decodes"] = dict(totals, mode=coding.describe())
        for sid, delta in per_deltas.items():
            if sids is not None and sid not in sids:
                continue
            parent = self.shuffle_to_stage.get(sid)
            if parent is not None:
                info = self._stage_info(record, parent.id)
                d = info.setdefault("decodes", {})
                for k, v in delta.items():
                    d[k] = d.get(k, 0) + v
        self._merge_worker_decodes(record, sids)

    def _merge_worker_decodes(self, record, sids=None):
        """Fold WORKER-PROCESS decode deltas (spooled counter events,
        ISSUE 8 satellite) into this job's record: the multiprocess
        master's workers decode in their own processes, and before the
        trace spool their counters never reached the driver (the
        documented per-process caveat of PRs 6-7).  `sids` (service
        mode) restricts attribution to this job's own shuffles."""
        from dpark_tpu import coding
        wbase = record.pop("_trace_decode_base", None)
        if wbase is None:
            return
        try:
            snap = trace.merged_worker_counters()
        except Exception:
            return
        base_per = wbase.get("decodes_per_shuffle", {})
        per_deltas = {}
        for sid, counts in snap.get("decodes_per_shuffle",
                                    {}).items():
            prev = base_per.get(sid, {})
            delta = {k: v - prev.get(k, 0) for k, v in counts.items()}
            if any(delta.values()):
                per_deltas[sid] = delta
        if sids is not None:
            totals = {}
            for sid in sids:
                for k, v in per_deltas.get(sid, {}).items():
                    totals[k] = totals.get(k, 0) + v
        else:
            base_tot = wbase.get("decodes", {})
            totals = {k: v - base_tot.get(k, 0)
                      for k, v in snap.get("decodes", {}).items()}
        if any(totals.values()):
            d = record.setdefault("decodes",
                                  {"mode": coding.describe()})
            for k, v in totals.items():
                d[k] = d.get(k, 0) + v
            d["worker_processes"] = snap.get("processes", 0)
        for sid, delta in per_deltas.items():
            if sids is not None and sid not in sids:
                continue
            parent = self.shuffle_to_stage.get(sid)
            if parent is not None:
                info = self._stage_info(record, parent.id)
                d = info.setdefault("decodes", {})
                for k, v in delta.items():
                    d[k] = d.get(k, 0) + v

    def _finalize_adapt(self, record):
        """Attribute adaptive-execution decisions taken during this job
        to its record (ISSUE 7): ``record["adapt"]`` carries the mode
        plus the decision-log delta — steered choices (applied: true)
        and observe-mode would-be choices (applied: false), each with
        predicted (and, once measured, observed) ms.  Absent entirely
        with DPARK_ADAPT=off, so off-mode records stay bit-identical
        to the pre-PR shape."""
        base = record.pop("_adapt_base", None)
        if base is None:
            return
        try:
            from dpark_tpu import adapt
            if not adapt.enabled():
                return
            # concurrent jobs (ISSUE 9): the log interleaves decisions
            # from every in-flight job — restrict to the ones tagged
            # with THIS job's id (the service's slot threads tag them)
            job = record["id"] if record.get("service") else None
            decisions = adapt.decisions_since(base, job=job)
            record["adapt"] = {"mode": adapt.mode(),
                               "decisions": decisions}
        except Exception:
            pass

    # -- straggler-adaptive coded shuffle (ISSUE 19, decision pt 6) ------
    def _maybe_choose_code(self, dep):
        """Price a per-exchange shuffle code from the adapt store's
        per-peer fetch-tail sketches before the map stage writes its
        first bucket: an exchange whose peers historically straggle
        gets parity even with the global code off, a tight-tailed one
        drops to uncoded under a global rs(k,m).  The choice rides
        ``dep.code_spec`` to every task (writer AND reader register it
        process-locally), so mixed per-shuffle codes stay wire-safe
        through the self-describing container framing.  One flag check
        when DPARK_CODE_ADAPT is off."""
        if not conf.CODE_ADAPT:
            return
        if getattr(dep, "code_spec", None) is not None:
            return                      # resubmit: keep the first choice
        site = getattr(dep, "adapt_site", None)
        if not site:
            return
        from dpark_tpu import adapt, coding
        try:
            spec = adapt.choose_shuffle_code(site)
        except Exception:
            logger.exception("code choice failed for %s", site)
            return
        if spec is None:
            return                      # observe mode / no usable tails
        dep.code_spec = spec
        coding.set_shuffle_code(dep.shuffle_id, spec)

    def _finalize_exchanges(self, record):
        """Drain the per-exchange peer observations this process
        accumulated while the job fetched (ISSUE 19) into persistent
        adapt "xch" records keyed by the exchange's call site — the
        input the NEXT run's code policy prices from — and close the
        loop on any pending code decision (predicted vs observed fetch
        wall).  Worker processes of the multiprocess master accumulate
        in their own processes (the documented per-process caveat)."""
        from dpark_tpu import adapt
        if not adapt.enabled():
            return
        from dpark_tpu import shuffle as _shuffle
        try:
            obs = _shuffle.drain_exchange_observations()
        except Exception:
            return
        for sid, ent in obs.items():
            stage = self.shuffle_to_stage.get(sid)
            dep = stage.shuffle_dep if stage is not None else None
            site = getattr(dep, "adapt_site", None) if dep else None
            if not site:
                continue
            try:
                adapt.observe_exchange(site, ent.get("peers") or {},
                                       fetch_ms=ent.get("ms"))
            except Exception:
                pass

    # -- mid-job re-planning (ISSUE 19, decision pt 7) -------------------
    def _bucket_sizes(self, dep, stage):
        """Per-reduce-bucket byte sizes of a finished map stage,
        stat'd by the driver from the bucket files themselves — the
        skew probe's histogram.  None when any output is not a local
        file:// loc (bucket-server/tcp and hbm exchanges are excluded
        from re-planning: no cheap driver-side size probe)."""
        import os as _os
        n = dep.partitioner.num_partitions
        sizes = [0] * n
        for m, uri in enumerate(stage.output_locs):
            if not isinstance(uri, str) \
                    or not uri.startswith("file://"):
                return None
            d = _os.path.join(uri[len("file://"):], "shuffle",
                              str(dep.shuffle_id), str(m))
            for r in range(n):
                p = _os.path.join(d, str(r))
                try:
                    sizes[r] += _os.path.getsize(p)
                except OSError:
                    try:
                        sizes[r] += _os.path.getsize(p + ".shards")
                    except OSError:
                        return None
        return sizes

    def _replan_consumer(self, stage, dep, waiting):
        """The unique (waiting child stage, ShuffledRDD) pair that
        consumes `dep`, or (None, None) when the shape is not safely
        re-plannable: multiple children, multiple consumers, a
        consumer that is not a plain ShuffledRDD, or a CoGroupedRDD
        anywhere on the narrow walk (its narrow-vs-shuffle dep choice
        was fixed at graph build from partitioner EQUALITY — swapping
        the partitioner underneath it could desynchronize
        copartitioning)."""
        children = [c for c in waiting if stage in c.parents]
        if len(children) != 1:
            return None, None
        child = children[0]
        from dpark_tpu.rdd import CoGroupedRDD
        consumers = []
        hazard = [False]
        seen = set()

        def visit(r):
            if r.id in seen or hazard[0]:
                return
            seen.add(r.id)
            if isinstance(r, CoGroupedRDD):
                hazard[0] = True
                return
            for d in r.dependencies:
                if d is dep:
                    consumers.append(r)
                elif not isinstance(d, ShuffleDependency):
                    visit(d.rdd)
        visit(child.rdd)
        if hazard[0] or len(consumers) != 1:
            return None, None
        consumer = consumers[0]
        if getattr(consumer, "dep", None) is not dep:
            return None, None
        return child, consumer

    def _maybe_replan(self, stage, waiting, submit_stage, record):
        """Mid-job re-plan at the stage boundary (ISSUE 19 decision
        point 7): the map side just finished, its bucket sizes are
        REAL, and the reduce side has not launched — if one reduce
        bucket dominates the exchange (hash-collision skew the
        map-side combine could not dissolve), re-key the reduce side
        through a salted re-split of the already-written buckets.  No
        map task is recomputed: a ResplitReaderRDD stage re-buckets
        (map, reduce) pairs under SaltedHashPartitioner at the SAME
        width, and the waiting consumer is rewired onto it before it
        ever runs.  Observe mode logs the would-be decision and
        changes nothing.  One flag check when DPARK_REPLAN is off."""
        if not conf.REPLAN:
            return
        from dpark_tpu import adapt
        if not adapt.enabled():
            return
        dep = stage.shuffle_dep
        site = getattr(dep, "adapt_site", None)
        if not site:
            return
        from dpark_tpu.dependency import (
            Aggregator, HashPartitioner, SaltedHashPartitioner)
        if type(dep.partitioner) is not HashPartitioner:
            return                # already salted / range: leave alone
        n = dep.partitioner.num_partitions
        if n <= 1:
            return
        try:
            sizes = self._bucket_sizes(dep, stage)
        except Exception:
            return
        if not sizes:
            return
        total = sum(sizes)
        if total < conf.REPLAN_MIN_BYTES:
            return
        frac = max(sizes) / float(total)
        if frac < conf.REPLAN_SKEW_FRAC:
            return
        child, consumer = self._replan_consumer(stage, dep, waiting)
        if child is None:
            return
        salt = 1
        steering = adapt.steering()
        try:
            reason = adapt.note_replan(site, n, salt, frac,
                                       applied=steering)
        except Exception:
            return
        if not steering:
            return                     # observe: decision logged only
        from dpark_tpu.rdd import ResplitReaderRDD, _identity
        mc = dep.aggregator.merge_combiners
        with self._graph_lock:
            reader = ResplitReaderRDD(dep)
            # readers yield (key, combiner) with each key at most once
            # per split (map-side dicts dedupe), so identity-create +
            # merge_combiners reproduces the original combine exactly;
            # map-id-major reader splits keep the merge order
            # bit-identical to the un-replanned fetch
            new_dep = ShuffleDependency(
                reader, Aggregator(_identity, mc, mc),
                SaltedHashPartitioner(n, salt))
            resplit_stage = self.get_shuffle_map_stage(new_dep)
            consumer.dep = new_dep
            consumer.dependencies = [new_dep]
            consumer.partitioner = new_dep.partitioner
            child.parents = self._get_parent_stages_locked(child.rdd)
        submit_stage(resplit_stage)
        record["replans"] = record.get("replans", 0) + 1
        record["stages"] = record.get("stages", 0) + 1
        info = self._stage_info(record, child.id)
        info["replan_reason"] = reason
        logger.info("re-planned shuffle %d -> %d (stage %d): %s",
                    dep.shuffle_id, new_dep.shuffle_id,
                    resplit_stage.id, reason)

    def _stage_info(self, record, stage_id):
        """The per-stage observability dict inside a job record
        (SURVEY.md 5.1: per-stage timings/path for the web UI)."""
        for info in record.get("stage_info", ()):
            if info["id"] == stage_id:
                return info
        info = {"id": stage_id, "kind": "object", "seconds": None}
        record.setdefault("stage_info", []).append(info)
        return info

    def note_stage(self, stage_id, **kw):
        """Executor/backends annotate the CURRENT job's stage record
        (e.g. kind=array, shuffle bytes) — best-effort, never raises."""
        record = getattr(self, "_current_record", None)
        if record is not None:
            self._stage_info(record, stage_id).update(kw)
        if "degrade_reason" in kw:
            # flight recorder (ISSUE 14): a runtime degrade is a
            # warning-and-above event — it lands in the always-armed
            # ring regardless of trace mode, and dumps a snapshot
            # when DPARK_FLIGHT_DIR is set.  Degrades are rare by
            # definition (each one already cost a retry or fallback).
            from dpark_tpu import health
            trace.flight("stage.degrade", "exec", stage=stage_id,
                         reason=str(kw["degrade_reason"])[:200])
            health.flight_dump("stage-degrade", scheduler=self)

    def _note_remote_fetch(self, stage_id, rx0):
        """Attribute bulk-channel bytes received while this stage's
        tasks ran (cross-controller shuffle fetches, ISSUE 12) to its
        stage record — the web UI's "remote fetch B" column.  Inline
        masters only: multiprocess workers fetch in their own
        processes (same per-process contract as the fault/decode
        counters).  The delta is over a PROCESS-WIDE counter, so with
        concurrent jobs on a resident service the stages that overlap
        in time each see the combined bytes — same documented contract
        as the per-job program_cache delta (ISSUE 9); fetches run on
        fetcher worker threads, so thread-local attribution cannot
        narrow it."""
        try:
            from dpark_tpu import bulkplane
            rx = bulkplane.total_received_bytes() - rx0
        except Exception:
            return
        if rx > 0:
            record = getattr(self, "_current_record", None)
            if record is not None:
                info = self._stage_info(record, stage_id)
                info["remote_fetch_bytes"] = \
                    info.get("remote_fetch_bytes", 0) + rx

    def fallback_reasons(self):
        """Every recorded WHY-the-array-path-was-left reason across the
        job history (the tpu master notes one per declined stage; other
        masters record none).  Bench artifacts ship this next to the
        per-phase table so a silent object-path regression is visible
        in CI."""
        out = []
        for rec in self.history:
            for st in rec.get("stage_info", ()):
                reason = st.get("fallback_reason")
                if reason and reason not in out:
                    out.append(reason)
        return out

    def degrade_reasons(self):
        """Every recorded runtime DEGRADATION reason across the job
        history (the tpu master notes one per stage that hit a device
        error / spill failure and recovered — halved wave budget,
        object-path fallback).  The runtime twin of
        fallback_reasons(); bench artifacts ship both."""
        out = []
        for rec in self.history:
            for st in rec.get("stage_info", ()):
                reason = st.get("degrade_reason")
                if reason and reason not in out:
                    out.append(reason)
        return out

    def _journal_stage(self, record, stage):
        """Write-ahead one COMPLETED shuffle-map stage (journal plane,
        ISSUE 20): fingerprint + writer shuffle id + output locations,
        so a restarted controller resumes past this stage instead of
        recomputing it."""
        jfp = record.get("_jfp")
        if jfp is not None:
            from dpark_tpu import journal
            journal.append_stage(jfp, stage)

    def recovery_summary(self):
        """Aggregate recovery accounting across the job history plus
        the chaos plane's per-site injection counters — the bench
        JSON's `faults`/`degrades` sections (ISSUE 5 satellite):
        proves in CI that injected faults actually fired and recovery
        actually ran."""
        from dpark_tpu import coding, faults
        out = {"resubmits": 0, "recomputes": 0, "retries": 0,
               "fetch_failed": 0, "speculated": 0, "replans": 0,
               "resumed_stages": 0}
        for rec in self.history:
            for k in list(out):
                out[k] += rec.get(k, 0)
        out["reasons"] = self.degrade_reasons()
        out["faults"] = faults.stats()
        # coded-shuffle view (ISSUE 6): repair / straggler_win /
        # decode_failures + the active mode.  decode_failures stays
        # DISTINCT from fetch_failed above — a failed decode names how
        # close parity came (shards_found/shards_needed ride the
        # FetchFailed), a plain fetch failure never had parity at all.
        out["decodes"] = coding.stats()
        # worker-counter merge (ISSUE 8 satellite): with spool tracing
        # on, worker processes append cumulative fault/decode counters
        # to the trace spool; fold them in so the multiprocess master's
        # summary finally covers what its workers observed
        if trace.mode() == "spool":
            try:
                workers = trace.merged_worker_counters()
            except Exception:
                workers = None
            if workers and workers.get("processes"):
                for site, st in workers["faults"].items():
                    ent = out["faults"].setdefault(
                        site, {"hits": 0, "fired": 0, "kind": "?"})
                    ent["hits"] = ent.get("hits", 0) + st["hits"]
                    ent["fired"] = ent.get("fired", 0) + st["fired"]
                for kind, v in workers["decodes"].items():
                    out["decodes"][kind] = \
                        out["decodes"].get(kind, 0) + v
                out["worker_processes"] = workers["processes"]
        # crash-consistency view (ISSUE 20): journal replay counters
        # and the peer-liveness lease registry, when armed
        from dpark_tpu import dcn, journal
        js = journal.stats()
        if js is not None:
            out["journal"] = js
        lv = dcn.liveness_stats()
        if lv is not None:
            out["liveness"] = lv
        return out

    @staticmethod
    def _new_metrics():
        return {"jobs": {}, "stages": {},
                "tasks": {"ok": 0, "fail": 0},
                "counters": {"retries": 0, "resubmits": 0,
                             "recomputes": 0, "fetch_failed": 0,
                             "speculated": 0, "replans": 0,
                             "resumed_stages": 0},
                "adapt_decisions": {"applied": 0, "logged": 0},
                "phases": {}}

    @staticmethod
    def _observe_phase(hists, phase, seconds):
        h = hists.get(phase)
        if h is None:
            h = hists[phase] = {
                "buckets": [0] * (len(PHASE_BUCKETS) + 1),
                "sum": 0.0, "count": 0}
        for i, le in enumerate(PHASE_BUCKETS):
            if seconds <= le:
                h["buckets"][i] += 1
                break
        else:
            h["buckets"][-1] += 1
        h["sum"] += seconds
        h["count"] += 1

    @classmethod
    def _fold_metrics_record(cls, out, rec):
        """Fold one job record into a metrics aggregate — defensively:
        a record mid-mutation contributes what it can, never throws.
        Records still RUNNING contribute nothing: their state flips
        and their counters/phase totals grow between scrapes, which
        would make counter-typed /metrics series decrease (Prometheus
        reads any decrease as a counter reset) — in-flight jobs are
        exposed separately as the dpark_jobs_running gauge."""
        try:
            state = str(rec.get("state", "unknown"))
            if state == "running":
                return
            out["jobs"][state] = out["jobs"].get(state, 0) + 1
            for k in out["counters"]:
                out["counters"][k] += int(rec.get(k, 0) or 0)
            ad = rec.get("adapt") or {}
            for d in list(ad.get("decisions") or ()):
                out["adapt_decisions"]["logged"] += 1
                if d.get("applied"):
                    out["adapt_decisions"]["applied"] += 1
            for st in list(rec.get("stage_info") or ()):
                kind = str(st.get("kind", "object"))
                out["stages"][kind] = out["stages"].get(kind, 0) + 1
                for t in list(st.get("tasks") or ()):
                    out["tasks"]["ok" if t.get("ok")
                                 else "fail"] += 1
                pipe = st.get("pipeline")
                if isinstance(pipe, dict):
                    for phase, key in (
                            ("ingest_tokenize", "ingest_ms"),
                            ("narrow", "compute_ms"),
                            ("exchange", "exchange_ms"),
                            ("spill", "spill_ms")):
                        ms = pipe.get(key)
                        if ms:
                            cls._observe_phase(out["phases"], phase,
                                               float(ms) / 1e3)
        except Exception:
            pass                    # record mid-mutation: best effort

    def _archive_metrics(self, records):
        """Fold records about to fall out of the 100-job history
        window into the persistent archive, so /metrics counters stay
        MONOTONIC (Prometheus counters must never decrease — a drop
        reads as a counter reset and rate() reports a huge spurious
        increase).  Called under the metrics lock; records this old
        are finalized."""
        for rec in records:
            self._fold_metrics_record(self._metrics_archive, rec)

    def metrics_snapshot(self):
        """Aggregate counters for the /metrics endpoint (ISSUE 8):
        the archived aggregate of trimmed history plus a defensive
        fold of the live window, copied under the scheduler lock — a
        scrape racing a mutating job record must return valid,
        monotonic numbers, never throw."""
        import copy
        with self._metrics_lock:
            records = list(self.history)
            out = copy.deepcopy(self._metrics_archive)
        for rec in records:
            self._fold_metrics_record(out, rec)
        try:
            out["jobs_running"] = sum(
                1 for rec in records
                if str(rec.get("state")) == "running")
        except Exception:
            out["jobs_running"] = 0
        ex = getattr(self, "executor", None)
        try:
            out["export_seconds"] = float(
                getattr(ex, "export_seconds", 0.0)) if ex else 0.0
        except Exception:
            out["export_seconds"] = 0.0
        # resident-service observability (ISSUE 9): compiled-program
        # cache counters and the admission-queue gauge ride /metrics
        try:
            out["program_cache"] = ex.program_cache_stats() \
                if ex is not None else None
        except Exception:
            out["program_cache"] = None
        svc = getattr(self, "_service", None)
        if svc is not None:
            try:
                out["service"] = svc.service_stats()
            except Exception:
                pass
        return out

    def phase_table(self):
        """Per-phase wall-time table of the DEEPEST streamed stage
        (ingest/tokenize, narrow compute, exchange, spill) plus the
        executor's host-bridge export total — the bench JSON's
        `phases` field.  None when no stage streamed."""
        pipe = self.pipeline_summary()
        if pipe is None:
            return None
        table = {
            "ingest_tokenize_ms": pipe.get("ingest_ms", 0.0),
            "narrow_ms": pipe.get("compute_ms", 0.0),
            "exchange_ms": pipe.get("exchange_ms", 0.0),
            "spill_ms": pipe.get("spill_ms", 0.0),
            "export_ms": 0.0,
        }
        ex = getattr(self, "executor", None)
        if ex is not None:
            table["export_ms"] = round(
                getattr(ex, "export_seconds", 0.0) * 1e3, 1)
        return table

    def pipeline_summary(self):
        """The overlapped-wave-pipeline snapshot of the DEEPEST streamed
        stage across the job history (most waves), per-wave detail
        dropped — the aggregate consumers (bench.py, benchmarks/) report:
        ingest/compute/exchange/spill ms + device-idle fraction.
        None when no stage streamed."""
        best = None
        for rec in self.history:
            for st in rec.get("stage_info", ()):
                p = st.get("pipeline")
                if p and (best is None
                          or p.get("waves", 0) > best.get("waves", 0)):
                    best = p
        if best is None:
            return None
        return {k: v for k, v in best.items()
                if not k.startswith("per_wave")}

    def _finish_stage_info(self, record, stage_id):
        import time as _time
        info = self._stage_info(record, stage_id)
        if info.get("started") and info.get("seconds") is None:
            info["seconds"] = round(_time.time() - info["started"], 3)
            if trace._PLANE is not None:
                trace.emit("stage", "sched", info["started"],
                           info["seconds"], job=record["id"],
                           stage=stage_id, rdd=info.get("rdd"),
                           kind=info.get("kind"),
                           parents=list(info.get("parents") or ()))
        # streamed stages report per-wave pipeline timings live; once
        # the stage is done, keep only the tail so a thousand-wave run
        # doesn't bloat the job history (/api/jobs ships it as JSON)
        pipe = info.get("pipeline")
        if isinstance(pipe, dict):
            per_wave = pipe.get("per_wave")
            if per_wave and len(per_wave) > 16:
                pipe["per_wave"] = per_wave[-16:]
                pipe["per_wave_truncated"] = True

    def max_concurrency(self):
        """How many tasks can execute at once (None = unbounded/inline).
        Speculation only considers tasks that are actually RUNNING, which
        on a saturated pool means at most this many."""
        return None

    def _check_speculation(self, running, pending_tasks, durations,
                           submitted_at, speculated, spawn_duplicate):
        """Straggler re-launch (reference: dpark/job.py speculation)."""
        import time as _time
        now = _time.time()
        cap = self.max_concurrency()
        for stage in list(running):
            pend = pending_tasks.get(stage)
            done = durations.get(stage.id, [])
            if not pend or not done:
                continue
            if cap is not None and len(pend) > cap:
                # some pending tasks are still queue-waiting, not slow —
                # their submit-time age would trigger mass duplicates
                continue
            total = len(pend) + len(done)
            if len(done) / total < conf.SPECULATION_QUANTILE:
                continue
            med = sorted(done)[len(done) // 2]
            threshold = max(conf.SPECULATION_MULTIPLIER * med, 0.5)
            for p in list(pend):
                key = (stage.id, p)
                started = submitted_at.get(key)
                if (started is not None and key not in speculated
                        and now - started > threshold):
                    speculated.add(key)
                    spawn_duplicate(stage, p)

    def _event_loop(self, output_parts, finished, results, events,
                    in_flight, waiting, running, pending_tasks, failures,
                    progress, stage_of, submit_stage,
                    submit_missing_tasks, record, report, submitted_at,
                    spawn_duplicate, stage_failures=None):
        if stage_failures is None:
            stage_failures = {}
        import time as _time
        num_finished = 0
        next_to_yield = 0
        durations = {}          # stage_id -> completed task durations
        speculated = set()
        poll = 1.0 if conf.SPECULATION else conf.SCHEDULER_STALL_TIMEOUT
        while num_finished < len(output_parts):
            try:
                task, status, payload = events.get(timeout=poll)
            except queue.Empty:
                if in_flight[0] <= 0:
                    raise RuntimeError(
                        "scheduler deadlock: no tasks in flight and no "
                        "events (waiting=%r running=%r finished=%d/%d)"
                        % (waiting, running, num_finished,
                           len(output_parts)))
                if conf.SPECULATION:
                    self._check_speculation(
                        running, pending_tasks, durations, submitted_at,
                        speculated, spawn_duplicate)
                continue        # a long task is legitimately running
            in_flight[0] -= 1
            if "_t_submit" in record and "first_wave_ms" not in record:
                # resident-service latency metric (ISSUE 9): submit ->
                # first completed wave of work (includes queue wait and
                # any trace+compile the first stage paid — the number
                # the warm-submit A/B drives down)
                record["first_wave_ms"] = round(
                    (_time.time() - record["_t_submit"]) * 1e3, 1)
            stage = stage_of.get(task.stage_id)
            tkey = (task.stage_id, task.partition)
            started = submitted_at.pop(tkey, None)
            if started is not None and status == "success":
                durations.setdefault(task.stage_id, []).append(
                    _time.time() - started)
            if started is not None:
                # per-task drill-down for the web UI (SURVEY.md 5.1);
                # bounded so huge jobs don't bloat the history record
                tl = self._stage_info(record, task.stage_id) \
                    .setdefault("tasks", [])
                if len(tl) < 512:
                    # the host/executor that RAN the task when the
                    # master records one (locality-aware placement),
                    # else this process's host
                    tl.append({"p": task.partition,
                               "s": round(_time.time() - started, 3),
                               "host": getattr(task, "_ran_on",
                                               env.host),
                               "ok": status == "success"})
                if trace._PLANE is not None:
                    # driver-side task span (submit -> completion
                    # event), retroactive from the recorded times
                    trace.emit("task", "sched", started,
                               _time.time() - started,
                               job=record["id"], stage=task.stage_id,
                               task=task.partition, status=status,
                               host=getattr(task, "_ran_on",
                                            env.host))
            if status == "success":
                result, acc_updates, md_updates = payload
                self.host_manager.task_succeed_on(
                    getattr(task, "_ran_on", env.host))
                stats = (acc_updates or {}).pop(PROFILE_KEY, None)
                if stats is not None:
                    if self.profile is None:
                        from dpark_tpu.utils.profile import MergedProfile
                        self.profile = MergedProfile()
                    self.profile.add(stats)
                accumulator.merge_on_driver(acc_updates)
                if md_updates:
                    from dpark_tpu import mutable_dict
                    mutable_dict.merge_on_driver(md_updates)
                if isinstance(task, ResultTask):
                    pend = pending_tasks.get(stage)
                    if pend is not None:
                        pend.discard(task.partition)
                    idx = task.output_id
                    if not finished[idx]:
                        finished[idx] = True
                        results[idx] = result
                        num_finished += 1
                        record["finished"] = num_finished
                        if num_finished == len(output_parts):
                            self._finish_stage_info(record,
                                                    task.stage_id)
                        progress.tick()
                    while (next_to_yield < len(output_parts)
                           and finished[next_to_yield]):
                        yield results[next_to_yield]
                        results[next_to_yield] = None
                        next_to_yield += 1
                else:
                    stage.add_output_loc(task.partition, result)
                    pend = pending_tasks.get(stage)
                    if pend is not None:
                        pend.discard(task.partition)
                    if not stage.is_available and pend is not None \
                            and not pend:
                        # outputs were invalidated (FetchFailed on another
                        # map) while this stage was running: resubmit the
                        # holes, else the job deadlocks with no events left
                        submit_missing_tasks(stage)
                    if stage.is_available:
                        env.map_output_tracker.register_outputs(
                            stage.shuffle_dep.shuffle_id, stage.output_locs)
                        self._finish_stage_info(record, stage.id)
                        self._journal_stage(record, stage)
                        running.discard(stage)
                        # mid-job re-plan probe (ISSUE 19): if this
                        # map stage's bucket histogram shows one
                        # dominant reduce bucket, re-key the waiting
                        # reduce side through a salted re-split of the
                        # JUST-WRITTEN buckets before it launches
                        self._maybe_replan(stage, waiting,
                                           submit_stage, record)
                        # wake children whose parents are now all ready
                        for child in list(waiting):
                            if not self.get_missing_parent_stages(child):
                                waiting.discard(child)
                                submit_missing_tasks(child)
                                running.add(child)
            elif status == "fetch_failed":
                e = payload
                parent = self.shuffle_to_stage.get(e.shuffle_id)
                record["fetch_failed"] = record.get("fetch_failed",
                                                    0) + 1
                if parent is not None:
                    if e.map_id >= 0:
                        parent.output_locs[e.map_id] = None
                    if e.uri and (e.map_id < 0
                                  or str(e.uri).startswith("hbm://")):
                        # device-resident shuffles compute EVERY
                        # partition in one stage program and export
                        # through one uri: losing any hbm bucket means
                        # the whole store recomputes (a lone-map
                        # object-path recompute would silently cover
                        # only that map's rows)
                        parent.remove_outputs_by_uri(e.uri)
                    # publish the surviving outputs (only the lost maps
                    # are None) so in-flight reduces don't treat every
                    # healthy map as missing and trigger a full parent
                    # recompute (round-1 advisor fix)
                    env.map_output_tracker.register_outputs(
                        e.shuffle_id, list(parent.output_locs))
                if parent is not None and not parent.is_available:
                    logger.warning(
                        "fetch failed on %s; resubmitting parent %s",
                        stage, parent)
                    running.discard(stage)
                    waiting.add(stage)
                    # cap lineage-recovery ROUNDS per parent stage: a
                    # shuffle source that keeps failing must abort the
                    # job with the real error chained, not loop the
                    # DAG forever (ISSUE 5 satellite).  A burst of
                    # sibling FetchFaileds from one lost map counts as
                    # ONE round — only the event that initiates the
                    # resubmission increments (later siblings find the
                    # parent already re-running)
                    if parent not in running and parent not in waiting:
                        rounds = stage_failures.get(parent.id, 0) + 1
                        stage_failures[parent.id] = rounds
                        if rounds > conf.MAX_STAGE_FAILURES:
                            err = RuntimeError(
                                "stage %d failed %d lineage-recovery "
                                "rounds (conf.MAX_STAGE_FAILURES=%d); "
                                "aborting job — last fetch failure "
                                "chained below"
                                % (parent.id, rounds,
                                   conf.MAX_STAGE_FAILURES))
                            err.__cause__ = e
                            raise err
                        record["resubmits"] = record.get(
                            "resubmits", 0) + 1
                    submit_stage(parent)
                else:
                    # parent intact (task-local loss — e.g. a spill
                    # chunk failed its crc) or unknown shuffle: there
                    # is nothing for the parent to redo, so retry just
                    # THIS task under the ordinary per-task failure
                    # cap.  A stage resubmit here would enqueue zero
                    # parent tasks (deadlock) or duplicate every
                    # still-pending sibling per event.
                    logger.warning(
                        "fetch failed on %s (parent %s intact); "
                        "retrying the task", stage, parent)
                    if parent is not None:
                        record["recomputes"] = record.get(
                            "recomputes", 0) + 1
                    key = (task.stage_id, task.partition)
                    failures[key] = failures.get(key, 0) + 1
                    if failures[key] >= conf.MAX_TASK_FAILURES:
                        err = RuntimeError(
                            "task for partition %d of stage %d hit "
                            "FetchFailed %d times on shuffle %s with "
                            "intact parent outputs"
                            % (task.partition, task.stage_id,
                               failures[key], e.shuffle_id))
                        err.__cause__ = e
                        raise err
                    record["retries"] = record.get("retries", 0) + 1
                    retry = task.retry_copy()
                    in_flight[0] += 1
                    submitted_at[tkey] = _time.time()
                    if trace._PLANE is not None:
                        retry._trace_job = record["id"]
                    with trace.ctx(job=record["id"],
                                   stage=task.stage_id):
                        self._dispatch(stage, [retry], report, record)
            else:       # failure
                # credit the EXECUTOR that ran the task (fleet
                # placement): blacklist ranking must see failures
                # against 'exec-N', not this process's hostname
                self.host_manager.task_failed_on(
                    getattr(task, "_ran_on", env.host))
                # losing duplicate of a partition another attempt already
                # completed: ignore (speculation/retry race), don't count
                if isinstance(task, ResultTask):
                    if finished[task.output_id]:
                        continue
                elif stage is not None \
                        and stage.output_locs[task.partition] is not None:
                    continue
                key = (task.stage_id, task.partition)
                failures[key] = failures.get(key, 0) + 1
                if failures[key] >= conf.MAX_TASK_FAILURES:
                    raise RuntimeError(
                        "task for partition %d of stage %d failed %d times; "
                        "last error:\n%s" % (task.partition, task.stage_id,
                                             failures[key], payload))
                logger.warning("task %r failed (try %d): %s",
                               task, failures[key], str(payload)[:200])
                # a retry is a FRESH attempt with its own task id — no
                # shared-object mutation between attempts, so completion
                # attribution stays unambiguous when dispatch crosses
                # process/host boundaries
                record["retries"] = record.get("retries", 0) + 1
                retry = task.retry_copy()
                in_flight[0] += 1
                submitted_at[tkey] = _time.time()
                if trace._PLANE is not None:
                    retry._trace_job = record["id"]
                with trace.ctx(job=record["id"],
                               stage=task.stage_id):
                    self._dispatch(stage, [retry], report, record)

    # -- master-specific -------------------------------------------------
    def _dispatch(self, stage, tasks, report, record):
        """Run tasks now — or, with a resident job server attached
        (ISSUE 9), enqueue them into its fair dispatcher so stages
        from concurrent jobs interleave on the shared mesh.  One
        `is None` check when no service is attached."""
        svc = self._service
        if svc is None:
            self.submit_tasks(stage, tasks, report)
        else:
            svc.enqueue(self, record, stage, tasks, report)

    def submit_tasks(self, stage, tasks, report):
        """Run tasks and call report(task, status, payload) for each."""
        raise NotImplementedError

    def default_parallelism(self):
        return 2


PROFILE_KEY = "__profile__"


def _run_task_inline(task):
    if trace._PLANE is None:
        return _run_task_body(task)
    # the task.run span is the WORKER-side timeline unit: in a
    # multiprocess run it lands in that process's spool (its pid
    # distinguishes it in the merged Chrome trace); nested fetch/spill
    # spans inherit the job/stage/task fields from this context
    with trace.ctx(job=getattr(task, "_trace_job", None),
                   stage=task.stage_id, task=task.partition), \
            trace.span("task.run", "worker",
                       kind=type(task).__name__, tried=task.tried):
        return _run_task_body(task)


def _run_task_body(task):
    from dpark_tpu import mutable_dict
    accumulator.start_task()
    mutable_dict.clear_task_updates()
    try:
        if getattr(env, "profile", False):
            from dpark_tpu.utils.profile import profile_call
            result, stats = profile_call(task.run, task.tried)
        else:
            result, stats = task.run(task.tried), None
        updates = accumulator.finish_task()
        if stats is not None:
            updates[PROFILE_KEY] = stats
        md_updates = mutable_dict.collect_task_updates()
        return "success", (result, updates, md_updates)
    except FetchFailed as e:
        accumulator.finish_task()
        mutable_dict.clear_task_updates()
        return "fetch_failed", e
    except Exception:
        accumulator.finish_task()
        mutable_dict.clear_task_updates()
        return "failed", traceback.format_exc()


class LocalScheduler(DAGScheduler):
    """Single-threaded in-process master — the golden model every other
    backend is tested against (SURVEY.md section 4)."""

    def __init__(self, threads=1):
        super().__init__()

    def submit_tasks(self, stage, tasks, report):
        from dpark_tpu import bulkplane
        rx0 = bulkplane.total_received_bytes()
        for task in tasks:
            status, payload = _run_task_inline(task)
            report(task, status, payload)
        self._note_remote_fetch(stage.id, rx0)

    def default_parallelism(self):
        return 2


class InlineExecutor:
    """One named executor identity on this host, with its own workdir
    (the unit the locality scheduler places tasks on).  Tasks still run
    inline in-process — placement, not isolation, is what this models:
    the executor that ran a task is stamped on it (``task._ran_on``)
    and lands in the scheduler's per-task host records."""

    def __init__(self, host, workdir):
        import os as _os
        self.host = host
        self.workdir = workdir
        _os.makedirs(workdir, exist_ok=True)
        self.tasks_run = 0

    def run(self, task):
        task._ran_on = self.host
        self.tasks_run += 1
        return _run_task_inline(task)


class LocalFleetScheduler(DAGScheduler):
    """Several workdir-distinct InlineExecutors on one host with
    LOCALITY-AWARE placement (reference: dpark's Mesos offers honoring
    task.preferredLocations — SURVEY.md 2.1): a task whose
    preferred_locations() (chunkserver per-chunk hosts, cached-partition
    holders) name a fleet executor runs THERE; candidates rank through
    the shared TaskHostManager (blacklisted holders lose the
    preference); unhinted tasks round-robin.  A successful task on a
    should_cache RDD records its executor as the partition's holder, so
    later jobs over the cached RDD chase the data."""

    def __init__(self, executors=2, names=None):
        super().__init__()
        names = list(names) if names else [
            "exec-%d" % i for i in range(int(executors))]
        if not names:
            raise ValueError("fleet needs at least one executor")
        env.start()
        import os as _os
        self.executors = [
            InlineExecutor(n, _os.path.join(env.workdir, "fleet", n))
            for n in names]
        self._by_host = {e.host: e for e in self.executors}
        self._rr = 0
        self.cache_locs = {}     # (rdd_id, partition) -> executor host

    def _pick_executor(self, task):
        hints = []
        key = (task.rdd.id, task.partition)
        holder = self.cache_locs.get(key)
        if holder is not None:
            hints.append(holder)
        try:
            hints.extend(task.preferred_locations() or [])
        except Exception:
            pass
        local = [h for h in hints if h in self._by_host]
        if local:
            best = self.host_manager.offer_choice(local)
            if best is not None:
                return self._by_host[best]
        ex = self.executors[self._rr % len(self.executors)]
        self._rr += 1
        return ex

    def submit_tasks(self, stage, tasks, report):
        from dpark_tpu import bulkplane
        rx0 = bulkplane.total_received_bytes()
        for task in tasks:
            ex = self._pick_executor(task)
            status, payload = ex.run(task)
            if status == "success" \
                    and getattr(task.rdd, "should_cache", False):
                self.cache_locs[(task.rdd.id, task.partition)] = ex.host
            report(task, status, payload)
        self._note_remote_fetch(stage.id, rx0)

    def default_parallelism(self):
        return len(self.executors)


def _process_worker(task_bytes, snapshot, environ):
    """Runs in a forked pool worker; returns result bytes (our serializer,
    so arbitrary user values survive the trip back)."""
    from dpark_tpu.utils import memory as memutil
    env.start(is_master=False, environ=environ)
    env.is_master = False      # fork inherits the driver's started env
    env.profile = environ.get("DPARK_PROFILE") == "1"
    env.map_output_tracker.update(snapshot)
    try:
        task = serialize.loads(task_bytes)
    except Exception:
        return pickle.dumps(("failed", traceback.format_exc()), -1)
    limit = float(environ.get("DPARK_MEM_LIMIT") or 0)
    checker = None
    if limit and task.tried >= conf.MAX_TASK_FAILURES - 1:
        limit = 0.0        # final attempt runs unrestricted
    if limit:
        # escalate the budget on retries (reference: memory-kill + retry
        # with more memory, SURVEY.md 5.3), capped by MAX_TASK_MEMORY
        limit = min(limit * (1 << task.tried), conf.MAX_TASK_MEMORY)
        checker = memutil.MemoryChecker(limit).start()
        memutil.current_checker = checker
    try:
        status, payload = _run_task_inline(task)
    finally:
        if checker is not None:
            checker.stop()
            memutil.current_checker = None
        # cumulative fault/decode counters -> the trace spool (spool
        # mode only): the driver merges the latest event per process,
        # closing the per-process counter blindspot (ISSUE 8)
        trace.emit_process_counters()
    try:
        return serialize.dumps((status, payload))
    except Exception:
        if status == "success":
            return pickle.dumps(
                ("failed", "unserializable task result:\n" +
                 traceback.format_exc()), -1)
        return pickle.dumps(("failed", repr(payload)), -1)


class MultiProcessScheduler(DAGScheduler):
    """Process-pool master (reference: -m process).  Exercises the full
    serialize/ship/track path and is the CPU baseline for benchmarks.

    Workers fork from a FORKSERVER, not from the driver: the driver has
    usually initialized jax (multithreaded — forking it is the classic
    latent deadlock), while the forkserver process only ever imports
    modules and starts no backend threads, so forking it is safe and
    keeps per-task worker startup cheap.  Worker state therefore does
    NOT inherit driver memory: everything a task needs travels in
    task_bytes + the map-output snapshot + environ (broadcast derefs go
    through workdir files / TCP, same as a real remote worker)."""

    def __init__(self, threads=None):
        super().__init__()
        self.num_workers = threads or multiprocessing.cpu_count()
        self.pool = None

    def start(self):
        super().start()
        if self.pool is None:
            ctx = multiprocessing.get_context("forkserver")
            ctx.set_forkserver_preload(["dpark_tpu.schedule"])
            # suppress the worker bootstrap's __main__ re-import: our
            # serializer ships __main__-defined closures BY VALUE, so
            # workers never need the user's script — and re-importing
            # it breaks outright for <stdin>/-c programs and re-runs
            # script module bodies otherwise
            import sys
            main_mod = sys.modules.get("__main__")
            had_file = main_mod is not None \
                and hasattr(main_mod, "__file__")
            saved_file = getattr(main_mod, "__file__", None)
            # __spec__ must EXIST for the spawn prep (it reads the
            # attribute unconditionally) but None makes it skip the
            # module-name path; no __file__ skips the path path
            had_spec = main_mod is not None \
                and hasattr(main_mod, "__spec__")
            saved_spec = getattr(main_mod, "__spec__", None)
            if main_mod is not None:
                if had_file:
                    del main_mod.__file__
                main_mod.__spec__ = None
            try:
                self.pool = ctx.Pool(self.num_workers)
            finally:
                if main_mod is not None:
                    if had_file:
                        main_mod.__file__ = saved_file
                    if had_spec:
                        main_mod.__spec__ = saved_spec
                    else:
                        del main_mod.__spec__

    def stop(self):
        super().stop()
        if self.pool is not None:
            self.pool.terminate()
            self.pool.join()
            self.pool = None

    def submit_tasks(self, stage, tasks, report):
        if self.pool is None:
            self.start()
        environ = env.environ_for_worker()
        for task in tasks:
            # exact snapshot: parent stages are complete before this point
            snapshot = env.map_output_tracker.snapshot(
                self._needed_shuffles(task.rdd))
            task_bytes = serialize.dumps(task)

            def on_done(result_bytes, task=task):
                status, payload = serialize.loads(result_bytes)
                report(task, status, payload)

            def on_error(exc, task=task):
                report(task, "failed", repr(exc))

            self.pool.apply_async(
                _process_worker, (task_bytes, snapshot, environ),
                callback=on_done, error_callback=on_error)

    def default_parallelism(self):
        return self.num_workers

    def max_concurrency(self):
        return self.num_workers
