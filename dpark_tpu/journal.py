"""Crash-consistent job journal (ISSUE 20 tentpole): a crc-framed
write-ahead log of job submission, stage completion, and the
shuffle-output registry, so a controller killed mid-job (kill -9, OOM,
power) can be restarted and RESUME accepted jobs from the last
completed stage instead of recomputing the whole DAG.

Design:

- One append-only journal file per process under DPARK_JOURNAL_DIR
  (``j-<nonce>.jnl``), each line a crc-framed canonical-JSON record
  (utils.frame_jsonl — the adapt-store/trace-spool format): a single
  O_APPEND write per record, so a torn tail from a crash skips at load
  instead of poisoning it.  The first record is a ``meta`` frame
  carrying the schema version; a file written by a NEWER schema is
  refused whole (never half-interpreted).
- Stage identity across restarts is a content fingerprint — a sha1
  over a deterministic lineage walk (rdd types, split counts, call-site
  scope names, shuffle-boundary partitioner widths) — because
  process-local stage/shuffle ids restart from 1 in a fresh process.
- Bucket paths embed the WRITER's shuffle id
  (``<root>/shuffle/<sid>/<map>/<reduce>``), and a restarted process
  mints new ids; replay records the writer's old sid and aliases
  ``<root>/shuffle/<new_sid>`` to the old directory with a relative
  symlink, so the unchanged fetch path resolves surviving buckets.
- Replay seeds ``stage.output_locs`` for maps whose outputs still
  exist (file:// roots verified on disk; hbm:// and tcp:// outputs are
  unverifiable after a crash and treated as gone) — the scheduler's
  existing submit_missing_tasks then re-runs ONLY the holes,
  recomputing lost partitions by lineage exactly as dpark does.

Replay assumes the resubmitted job is the same computation over the
same inputs — the contract lineage recompute itself already assumes
(sources must be deterministic).  The plane is off by default
(``DPARK_JOURNAL=on`` to arm) and follows the plane contract: one
``is None`` check per seam when off, bit-identical results either way.
"""

import hashlib
import json
import os
import threading
import uuid

from dpark_tpu import conf, locks, trace
from dpark_tpu.utils.log import get_logger

logger = get_logger("journal")

# bump when the record layout changes incompatibly; a journal file
# whose meta frame carries a LARGER schema is refused at load (ISSUE 20
# satellite: never resurrect a record this code can't interpret)
SCHEMA = 1

_COUNTER_KEYS = ("records", "journal_replays", "recovered_stages",
                 "seeded_partitions", "skipped_frames", "refused_files",
                 "flushes")


def _frame(rec):
    from dpark_tpu.utils import frame_jsonl
    return frame_jsonl(rec)


class _Plane:
    """One process's view of the journal directory: its own append-only
    file plus the loaded index of every file already there."""

    def __init__(self, journal_dir):
        self.dir = journal_dir
        self.lock = locks.named_lock("journal.plane")
        self.counters = {k: 0 for k in _COUNTER_KEYS}
        self._fd = None
        self._path = os.path.join(
            journal_dir, "j-%s.jnl" % uuid.uuid4().hex[:12])
        self._loaded = False
        self._stages = {}        # stage_fp -> last stage record
        self._jobs_done = set()  # job fingerprints with a job_done

    # -- load (replay side) ---------------------------------------------
    def _ensure_loaded(self):
        with self.lock:
            if self._loaded:
                return
            self._loaded = True
            try:
                names = sorted(
                    n for n in os.listdir(self.dir)
                    if n.endswith(".jnl"))
            except OSError:
                return
            from dpark_tpu.utils import unframe_jsonl
            for name in names:
                path = os.path.join(self.dir, name)
                try:
                    with open(path, "rb") as f:
                        raw = f.read()
                except OSError:
                    continue
                recs, skipped = unframe_jsonl(raw)
                self.counters["skipped_frames"] += skipped
                if recs and recs[0].get("kind") == "meta" \
                        and int(recs[0].get("schema", 0)) > SCHEMA:
                    # a newer process wrote this file: refuse it whole
                    # rather than guess at records this schema can't
                    # interpret
                    self.counters["refused_files"] += 1
                    logger.warning(
                        "refusing journal %s (schema %s > supported "
                        "%d)", name, recs[0].get("schema"), SCHEMA)
                    continue
                for rec in recs:
                    kind = rec.get("kind")
                    if kind == "stage" and rec.get("stage"):
                        # duplicates are idempotent: last record wins
                        # (a stage resubmitted after a fetch failure
                        # re-journals with its fresh locations)
                        self._stages[rec["stage"]] = rec
                    elif kind == "job_done" and rec.get("job"):
                        self._jobs_done.add(rec["job"])
                    # meta/job/unknown kinds: index-free (forward
                    # compatible within one schema)

    def lookup_stage(self, stage_fp):
        self._ensure_loaded()
        with self.lock:
            return self._stages.get(stage_fp)

    # -- append (write-ahead side) --------------------------------------
    def append(self, rec):
        line = _frame(rec)
        with self.lock:
            if self._fd is None:
                os.makedirs(self.dir, exist_ok=True)
                self._fd = os.open(
                    self._path,
                    os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
                os.write(self._fd, _frame(
                    {"kind": "meta", "schema": SCHEMA,
                     "pid": os.getpid()}))
                self.counters["records"] += 1
            os.write(self._fd, line)
            self.counters["records"] += 1
            # keep this process's own index current so a SECOND job in
            # the same process (or the same job resubmitted) replays
            # without re-reading the directory
            if rec.get("kind") == "stage" and rec.get("stage"):
                if self._loaded:
                    self._stages[rec["stage"]] = rec
            elif rec.get("kind") == "job_done" and rec.get("job"):
                if self._loaded:
                    self._jobs_done.add(rec["job"])

    def flush(self):
        """Durability barrier (the drain endpoint calls this before
        exit): fsync the append fd.  Individual appends rely on the
        page cache — sufficient for process death (kill -9), which is
        the failure this plane certifies against."""
        with self.lock:
            self.counters["flushes"] += 1
            if self._fd is not None:
                try:
                    os.fsync(self._fd)
                except OSError:
                    pass

    def stats(self):
        with self.lock:
            out = dict(self.counters)
            out["loaded_stages"] = len(self._stages)
        out["mode"] = "on"
        out["dir"] = self.dir
        return out


_PLANE = None


def configure(mode=None, journal_dir=None):
    """(Re)arm the plane from conf or explicit args; "off" disarms."""
    global _PLANE
    mode = (mode if mode is not None
            else getattr(conf, "DPARK_JOURNAL", "off") or "off")
    mode = str(mode).strip().lower()
    if mode in ("", "off", "0", "none", "false"):
        _PLANE = None
        return None
    d = journal_dir or getattr(conf, "DPARK_JOURNAL_DIR", "") \
        or os.path.join(conf.DPARK_WORK_DIR.split(",")[0].strip(),
                        "journal")
    _PLANE = _Plane(d)
    return _PLANE


def active():
    return _PLANE is not None


def stats():
    """Counters for /metrics, /api/health, and recovery_summary();
    None when the plane is off."""
    p = _PLANE
    return p.stats() if p is not None else None


def flush():
    p = _PLANE
    if p is not None:
        p.flush()


# ---------------------------------------------------------------------------
# content fingerprints: stage identity that survives a restart
# ---------------------------------------------------------------------------

def _walk(rdd, out, visited):
    rid = getattr(rdd, "id", None)
    if rid in visited:
        out.append("cycle:%s" % rid)
        return
    visited.add(rid)
    out.append("%s|%d|%s" % (type(rdd).__name__, len(rdd.splits),
                             getattr(rdd, "scope_name", "") or ""))
    path = getattr(rdd, "path", None)
    if isinstance(path, str):
        out.append("path=" + path)
    from dpark_tpu.dependency import ShuffleDependency
    for dep in rdd.dependencies:
        if isinstance(dep, ShuffleDependency):
            out.append("xch|%s|%d"
                       % (type(dep.partitioner).__name__,
                          dep.partitioner.num_partitions))
        else:
            out.append("dep|%s" % type(dep).__name__)
        _walk(dep.rdd, out, visited)
    out.append("end")


def _digest(parts):
    return hashlib.sha1(
        "\n".join(parts).encode("utf-8")).hexdigest()[:20]


def stage_fingerprint(stage):
    """Deterministic identity of a shuffle-map stage: the full lineage
    walk of its rdd plus its own write-side partitioner width.  Two
    processes building the same DAG from the same script compute the
    same fingerprint; process-local stage/shuffle ids never appear."""
    out = []
    _walk(stage.rdd, out, set())
    if stage.shuffle_dep is not None:
        out.append("write|%s|%d"
                   % (type(stage.shuffle_dep.partitioner).__name__,
                      stage.shuffle_dep.partitioner.num_partitions))
    return _digest(out)


def job_fingerprint(final_rdd, partitions):
    out = ["job", ",".join(str(p) for p in partitions)]
    _walk(final_rdd, out, set())
    return _digest(out)


# ---------------------------------------------------------------------------
# write-ahead records (called from the scheduler's job loop)
# ---------------------------------------------------------------------------

def append_job(jfp, scope):
    p = _PLANE
    if p is None:
        return
    try:
        p.append({"kind": "job", "job": jfp, "scope": scope})
    except Exception:
        logger.warning("journal job append failed", exc_info=True)


def append_stage(jfp, stage):
    """Record one COMPLETED shuffle-map stage: fingerprint, the
    writer's shuffle id (replay aliases it), the effective shuffle
    code, and every map output uri."""
    p = _PLANE
    if p is None or stage.shuffle_dep is None:
        return
    from dpark_tpu import coding
    sid = stage.shuffle_dep.shuffle_id
    code = coding.shuffle_code(sid)
    try:
        p.append({"kind": "stage", "job": jfp,
                  "stage": stage_fingerprint(stage), "sid": sid,
                  "nparts": stage.num_partitions,
                  "nreduce":
                      stage.shuffle_dep.partitioner.num_partitions,
                  "code": code.describe() if code else "off",
                  "locs": list(stage.output_locs)})
    except Exception:
        logger.warning("journal stage append failed", exc_info=True)


def append_job_done(jfp):
    p = _PLANE
    if p is None:
        return
    try:
        p.append({"kind": "job_done", "job": jfp})
    except Exception:
        logger.warning("journal job_done append failed", exc_info=True)


# ---------------------------------------------------------------------------
# replay seeding (called once per job, before the first stage submits)
# ---------------------------------------------------------------------------

def _surviving_locs(rec):
    """Validate a stage record's locations against the filesystem:
    file:// roots must still hold the old-sid bucket dir with a full
    complement of reduce files; hbm:// (device memory) and tcp:// (a
    peer that may have died with us) cannot be verified after a crash
    and are treated as gone — lineage recomputes them."""
    old_sid = int(rec["sid"])
    nreduce = int(rec.get("nreduce", 1))
    out = []
    for m, uri in enumerate(rec["locs"]):
        ok = False
        if isinstance(uri, str) and uri.startswith("file://"):
            d = os.path.join(uri[len("file://"):], "shuffle",
                             str(old_sid), str(m))
            try:
                ok = len(os.listdir(d)) >= nreduce
            except OSError:
                ok = False
        out.append(uri if ok else None)
    return out


def _alias_sid(root, old_sid, new_sid):
    """Point ``<root>/shuffle/<new_sid>`` at the surviving old-sid
    bucket tree (relative symlink, same parent dir).  Returns False
    when the alias can't be made — the caller treats those outputs as
    gone and lineage recomputes."""
    if old_sid == new_sid:
        return True
    base = os.path.join(root, "shuffle")
    link = os.path.join(base, str(new_sid))
    try:
        if os.path.lexists(link):
            return os.path.realpath(link) == os.path.realpath(
                os.path.join(base, str(old_sid)))
        os.makedirs(base, exist_ok=True)
        os.symlink(str(old_sid), link)
        return True
    except OSError:
        return False


def seed_stages(scheduler, final_stage, record, jfp):
    """Walk the job's stage graph; for every unavailable shuffle-map
    stage with a journaled completion, seed the output locations that
    survived on disk.  Fully-seeded stages register their map outputs
    and never resubmit (0 recomputes); partially-surviving stages
    resubmit only the holes.  Returns the number of fully resumed
    stages (also stamped on the record and traced)."""
    p = _PLANE
    if p is None:
        return 0
    from dpark_tpu import coding
    from dpark_tpu.env import env
    stages, seen = [], set()

    def collect(st):
        if st.id in seen:
            return
        seen.add(st.id)
        for parent in st.parents:
            collect(parent)
        if st.is_shuffle_map and not st.is_available:
            stages.append(st)

    collect(final_stage)
    resumed, seeded_parts = 0, 0
    for st in stages:
        rec = p.lookup_stage(stage_fingerprint(st))
        if rec is None:
            continue
        try:
            if int(rec.get("nparts", -1)) != st.num_partitions \
                    or int(rec.get("nreduce", -1)) != \
                    st.shuffle_dep.partitioner.num_partitions \
                    or len(rec.get("locs") or ()) != st.num_partitions:
                continue
            locs = _surviving_locs(rec)
        except Exception:
            continue
        new_sid = st.shuffle_dep.shuffle_id
        old_sid = int(rec["sid"])
        roots = {uri[len("file://"):] for uri in locs if uri}
        bad_roots = {r for r in roots
                     if not _alias_sid(r, old_sid, new_sid)}
        locs = [None if (uri and uri[len("file://"):] in bad_roots)
                else uri for uri in locs]
        if not any(uri for uri in locs):
            continue
        # the on-disk containers were written under the OLD run's code
        # choice; pin the new sid to the same spec so the fetch path
        # reads what is actually there (self-describing frames make a
        # mismatch safe but slow — this makes it exact)
        spec = rec.get("code")
        if spec is not None:
            try:
                coding.set_shuffle_code(new_sid, spec)
            except Exception:
                pass
        for m, uri in enumerate(locs):
            if uri is not None:
                st.add_output_loc(m, uri)
                seeded_parts += 1
        if st.is_available:
            env.map_output_tracker.register_outputs(
                new_sid, list(st.output_locs))
            resumed += 1
            logger.info(
                "journal replay: stage %s resumed from sid %d "
                "(%d maps, 0 recomputes)", st, old_sid,
                st.num_partitions)
        else:
            holes = sum(1 for u in st.output_locs if u is None)
            logger.info(
                "journal replay: stage %s partially resumed from sid "
                "%d (%d of %d maps recompute by lineage)", st,
                old_sid, holes, st.num_partitions)
    if seeded_parts:
        with p.lock:
            p.counters["journal_replays"] += 1
            p.counters["recovered_stages"] += resumed
            p.counters["seeded_partitions"] += seeded_parts
        record["resumed_stages"] = resumed
        record["seeded_partitions"] = seeded_parts
        trace.event("journal.replay", "sched", job=record.get("id"),
                    resumed_stages=resumed,
                    seeded_partitions=seeded_parts)
    return resumed


def _init_from_conf():
    try:
        if getattr(conf, "DPARK_JOURNAL", "off") not in (
                "", "off", "0", "none", "false"):
            configure()
    except Exception:
        logger.warning("journal init failed", exc_info=True)


_init_from_conf()
