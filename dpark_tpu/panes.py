"""Pane-tree windowing (ISSUE 10 tentpole): shared machinery behind
the sliding-window DStreams in dstream.py.

The decomposition is "Partial Partial Aggregates" (PAPERS.md): a
window of w = window/slide panes shares slide-sized PARTIAL
aggregates across consecutive window instances instead of re-reducing
the whole window every slide.  Each pane is one cached reduced RDD —
on the tpu master its shuffle output stays HBM-resident between ticks
(the SegMapOp-era device shuffle store), so the per-tick cost is the
merge work, not recompute:

  invertible ops      window' = prev + new pane - expired pane: O(1)
                      panes per slide (ReducedWindowedDStream)
  non-invertible ops  the window's pane range decomposes into at most
                      ~2*log2(w) ALIGNED dyadic blocks; each block's
                      merge is built once, cached, and reused while
                      any later window covers it (MergeTree below) —
                      O(log w) merged branches per tick, amortized
                      O(1) node builds per pane

Fault story: a pane is an ordinary cached reduced RDD, so a lost
shuffle bucket under `DPARK_FAULTS` recovers through the standard
planes — coded-shuffle decode (DPARK_SHUFFLE_CODE) or lineage — and
NEVER forces a whole-window recompute: only the lost pane's stage is
touched (chaos cell in tests/test_dstream.py).

Event time: `Watermark` tracks the max observed event timestamp; the
watermark trails it by the allowed lateness.  Late records inside the
bound patch ONLY their pane (the window update folds the patch delta
in; the merge tree invalidates just the O(log w) nodes covering that
pane); older records drop, counted per stream.  The admission buffer
is bounded (conf.STREAM_LATE_BUFFER_ROWS).

Every pane stream registers a live stats dict here; the web UI's
/api/streams and the /metrics stream gauges read `stream_stats()`.
"""

import itertools
import math
import threading

from dpark_tpu.utils.log import get_logger

logger = get_logger("panes")

_INF = float("inf")


# ---------------------------------------------------------------------------
# dyadic pane-range decomposition
# ---------------------------------------------------------------------------

def dyadic_blocks(lo, hi, max_size=None):
    """Aligned power-of-two blocks covering the inclusive pane-index
    range [lo, hi]: each block (start, size) has size a power of two,
    start % size == 0, and consecutive windows share most blocks — a
    block is built once ever and reused while any window covers it.
    At most ~2*log2(hi-lo+1) blocks.  `max_size` caps the block size
    (a node bigger than the window itself can never be reused)."""
    assert lo >= 0 and hi >= lo, (lo, hi)
    out = []
    i = lo
    while i <= hi:
        size = (i & -i) if i else 1 << 60
        if max_size:
            size = min(size, max_size)
        while i + size - 1 > hi:
            size >>= 1
        out.append((i, size))
        i += size
    return out


class MergeTree:
    """Cache of dyadic pane-merge nodes for a non-invertible window.

    `get_pane(idx)` returns the pane partial (an RDD) or None;
    `merge(rdds, level, start)` combines children into one node RDD
    (the caller supplies the union+reduce and does its own caching
    side effects).  `cover(lo, hi)` returns the O(log w) node RDDs for
    a window's pane range, building missing nodes bottom-up (each
    build merges exactly its two half-size children, so a pane
    participates in at most log2(w) builds over its lifetime).

    Late-data patches call `invalidate(idx)`: only the nodes covering
    that pane (one per level) drop; the next cover rebuilds them."""

    def __init__(self, get_pane, merge):
        self.get_pane = get_pane
        self.merge = merge
        self.nodes = {}                # (start, size) -> rdd or None
        self._owned = set()            # keys whose rdd THIS tree built
        self.builds = 0                # merge nodes built (stats)

    def _node(self, start, size):
        if size == 1:
            return self.get_pane(start)
        key = (start, size)
        if key in self.nodes:
            return self.nodes[key]
        half = size // 2
        kids = [self._node(start, half), self._node(start + half, half)]
        kids = [k for k in kids if k is not None]
        if not kids:
            rdd = None
        elif len(kids) == 1:
            rdd = kids[0]              # empty half: the node IS its child
        else:
            rdd = self.merge(kids, size, start)
            self._owned.add(key)       # dropping may unpersist this one
            self.builds += 1
        self.nodes[key] = rdd
        return rdd

    def cover(self, lo, hi, max_size=None):
        """Node RDDs covering panes [lo, hi] (Nones filtered)."""
        out = []
        for start, size in dyadic_blocks(lo, hi, max_size):
            rdd = self._node(start, size)
            if rdd is not None:
                out.append(rdd)
        return out

    def invalidate(self, idx):
        """Drop every cached node covering pane `idx` (<= 1 per level,
        so a late patch costs O(log w) rebuilds, not a tree rebuild)."""
        for start, size in list(self.nodes):
            if start <= idx < start + size:
                self._drop((start, size))

    def forget(self, before_idx):
        """Drop nodes that end before `before_idx` (window + lateness
        horizon): their panes can never be covered again."""
        for start, size in list(self.nodes):
            if start + size - 1 < before_idx:
                self._drop((start, size))

    def _drop(self, key):
        rdd = self.nodes.pop(key)
        # only unpersist rdds this tree BUILT: a single-child node
        # shares identity with a pane (or a lower node) that may still
        # be live in the window
        if key in self._owned:
            self._owned.discard(key)
            if rdd is not None and getattr(rdd, "should_cache", False):
                rdd.unpersist()


# ---------------------------------------------------------------------------
# event-time watermarks
# ---------------------------------------------------------------------------

class Watermark:
    """Bounded-delay event-time watermark: trails the max OBSERVED
    event timestamp by `lateness` seconds.  Admission is gated on the
    watermark as of the PREVIOUS tick (update() runs after the tick's
    records were classified), the standard micro-batch contract — a
    batch can never retro-tighten the bound on its own records."""

    def __init__(self, lateness):
        self.lateness = float(lateness)
        self.max_event_ts = None

    def floor(self):
        """Records with event ts below this drop."""
        if self.max_event_ts is None:
            return -_INF
        return self.max_event_ts - self.lateness

    def value(self):
        return None if self.max_event_ts is None else self.floor()

    def update(self, mx):
        if mx is not None and (self.max_event_ts is None
                               or mx > self.max_event_ts):
            self.max_event_ts = mx

    def lag(self, t):
        """Processing-time distance from tick `t` back to the
        watermark (how far completed event time trails the clock)."""
        if self.max_event_ts is None:
            return None
        return max(0.0, t - self.floor())


def pane_back_index(ts, t, slide):
    """How many panes BEFORE the pane ending at `t` the event
    timestamp `ts` belongs to: 0 = the current pane (ts in (t-slide,
    t], and future timestamps clamp to 0), k >= 1 = the pane ending at
    t - k*slide.  The single shared assignment rule — the scan job and
    the pane filters both use it, so counts and contents cannot
    drift."""
    if ts > t:
        return 0                      # ahead of the clock: current pane
    # pane b covers (t-(b+1)*slide, t-b*slide]: b = floor((t-ts)/slide),
    # nudged UP so an exact pane-boundary timestamp (ts == t-b*slide,
    # which belongs to pane b) survives float error in either direction
    return int(math.floor((t - ts) / slide + 1e-9))


class _EventScan:
    """Per-partition classifier for the tick's new records (picklable
    task function): returns (max_ts, on_time_rows, {back: late_rows},
    dropped_rows) under the PREVIOUS watermark floor."""

    def __init__(self, ts_fn, t, slide, max_back, floor):
        self.ts_fn = ts_fn
        self.t = t
        self.slide = slide
        self.max_back = max_back
        self.floor = floor

    def __call__(self, it):
        mx = None
        on_time = dropped = 0
        late = {}
        for rec in it:
            ts = self.ts_fn(rec)
            if mx is None or ts > mx:
                mx = ts
            back = pane_back_index(ts, self.t, self.slide)
            if back <= 0:
                on_time += 1
            elif back <= self.max_back and ts >= self.floor:
                late[back] = late.get(back, 0) + 1
            else:
                dropped += 1
        return [(mx, on_time, late, dropped)]


def event_scan(rdd, ts_fn, t, slide, max_back, floor):
    """One small driver job over the tick's new data: fold the
    per-partition classifications into (max_ts, on_time, {back:
    rows}, dropped)."""
    parts = rdd.ctx.runJob(rdd, _EventScan(ts_fn, t, slide, max_back,
                                           floor))
    mx, on_time, dropped = None, 0, 0
    late = {}
    for rows in parts:
        for pmx, pon, plate, pdrop in rows:
            if pmx is not None and (mx is None or pmx > mx):
                mx = pmx
            on_time += pon
            dropped += pdrop
            for back, n in plate.items():
                late[back] = late.get(back, 0) + n
    return mx, on_time, late, dropped


class _PaneFilter:
    """Predicate selecting the records of ONE pane (picklable): back
    index equality under the shared assignment rule, plus the
    watermark floor for late panes."""

    def __init__(self, ts_fn, t, slide, back, floor):
        self.ts_fn = ts_fn
        self.t = t
        self.slide = slide
        self.back = back
        self.floor = floor

    def __call__(self, rec):
        ts = self.ts_fn(rec)
        if pane_back_index(ts, self.t, self.slide) != self.back:
            return False
        return self.back == 0 or ts >= self.floor


# ---------------------------------------------------------------------------
# live per-stream stats registry (web UI /api/streams, /metrics gauges)
# ---------------------------------------------------------------------------

_REG_LOCK = threading.Lock()
_REGISTRY = {}
_ids = itertools.count(1)


def new_stream_id(kind):
    return "%s-%d" % (kind, next(_ids))


def register_stream(sid, stats):
    """Expose a stream's live stats dict (the stream mutates it in
    place per tick; readers snapshot under the lock)."""
    with _REG_LOCK:
        _REGISTRY[sid] = stats


def unregister_stream(sid):
    with _REG_LOCK:
        _REGISTRY.pop(sid, None)


def stream_stats():
    """Snapshot of every registered pane stream's stats."""
    with _REG_LOCK:
        return {sid: dict(st) for sid, st in _REGISTRY.items()}
