"""Write-only-from-workers accumulators.

Reference parity: dpark/accumulator.py — Accumulator + AccumulatorParam
(zero/addInPlace), per-task update registry shipped back with task results
and merged on the driver (SURVEY.md section 2.1).
"""

import threading


class AccumulatorParam:
    def __init__(self, zero, add_in_place):
        self.zero = zero
        self.add_in_place = add_in_place


numAcc = AccumulatorParam(0, lambda x, y: x + y)
listAcc = AccumulatorParam([], lambda l, v: (l.append(v) or l)
                           if not isinstance(v, list) else (l.extend(v) or l))
setAcc = AccumulatorParam(set(), lambda s, v: (s.update(v) or s)
                          if isinstance(v, (set, list)) else (s.add(v) or s))

_registry = {}            # id -> driver-side Accumulator
_local = threading.local()


class Accumulator:
    _next_id = [0]

    def __init__(self, initial_value=0, param=numAcc):
        Accumulator._next_id[0] += 1
        self.id = Accumulator._next_id[0]
        self.param = param
        self.value = initial_value
        _registry[self.id] = self

    def add(self, v):
        updates = getattr(_local, "updates", None)
        if updates is not None:
            # inside a task: record locally, merged on the driver later
            if self.id in updates:
                updates[self.id] = self.param.add_in_place(updates[self.id], v)
            else:
                zero = self.param.zero
                zero = zero.copy() if hasattr(zero, "copy") else zero
                updates[self.id] = self.param.add_in_place(zero, v)
        else:
            self.value = self.param.add_in_place(self.value, v)

    def __iadd__(self, v):
        self.add(v)
        return self

    def reset(self):
        zero = self.param.zero
        self.value = zero.copy() if hasattr(zero, "copy") else zero

    def __getstate__(self):
        # ships id + param only; worker-side adds go to the task registry
        return (self.id, self.param)

    def __setstate__(self, state):
        self.id, self.param = state
        self.value = None


def start_task():
    _local.updates = {}


def finish_task():
    updates = getattr(_local, "updates", {})
    _local.updates = None
    return updates


def merge_on_driver(updates):
    for acc_id, v in (updates or {}).items():
        acc = _registry.get(acc_id)
        if acc is not None:
            acc.value = acc.param.add_in_place(acc.value, v)
