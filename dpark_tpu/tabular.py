"""Tabular: columnar on-disk format with per-column compression and
column-pruned scans.

Reference parity: dpark/tabular.py + dpark/bitindex.py (SURVEY.md section
2.3) — column chunks with per-column compression and an optional index
enabling predicate-pruned scans.  Format here is an original design with
the same capabilities, numpy-friendly so ingestion to device columns is a
memcpy:

  file := header_json_len(4) header_json chunk*
  chunk: per-column compressed numpy buffers (or pickled object columns),
         with min/max statistics per numeric column in the header for
         chunk pruning (the bitmap-index analog).
"""

import json
import os
import pickle
import struct
import zlib

import numpy as np

from dpark_tpu.rdd import RDD, Split, DerivedRDD
from dpark_tpu.utils import atomic_file

MAGIC = b"DTB1"


def _pack_column(arr):
    arr = np.asarray(arr)
    if arr.dtype == object or arr.dtype.kind in "US":
        payload = zlib.compress(pickle.dumps(list(arr), -1))
        return {"kind": "object"}, payload
    payload = zlib.compress(np.ascontiguousarray(arr).tobytes())
    meta = {"kind": "numpy", "dtype": str(arr.dtype),
            "shape": list(arr.shape)}
    if arr.size and arr.dtype.kind in "if":
        # .item() keeps integers exact (floats above 2**53 would make
        # chunk pruning skip matching data)
        meta["min"] = arr.min().item()
        meta["max"] = arr.max().item()
    return meta, payload


def _unpack_column(meta, payload):
    if meta["kind"] == "object":
        return pickle.loads(zlib.decompress(payload))
    buf = zlib.decompress(payload)
    arr = np.frombuffer(buf, dtype=np.dtype(meta["dtype"]))
    return arr.reshape(meta["shape"])


def write_tabular(path, fields, rows, chunk_rows=65536):
    """rows: iterable of tuples matching `fields`."""
    chunks = []
    payloads = []
    buf = []

    def flush():
        if not buf:
            return
        cols = list(zip(*buf))
        metas = []
        offs = []
        for col in cols:
            meta, payload = _pack_column(np.asarray(col))
            offs.append(len(payload))
            metas.append(meta)
            payloads.append(payload)
        chunks.append({"rows": len(buf), "columns": metas, "sizes": offs})
        buf.clear()

    for row in rows:
        buf.append(tuple(row))
        if len(buf) >= chunk_rows:
            flush()
    flush()
    header = json.dumps({"fields": list(fields),
                         "chunks": chunks}).encode("utf-8")
    with atomic_file(path) as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(header)))
        f.write(header)
        for p in payloads:
            f.write(p)
    return path


def read_header(path):
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise IOError("not a tabular file: %s" % path)
        (n,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(n).decode("utf-8"))
        header["data_offset"] = f.tell()
    return header


def read_chunks(path, wanted_fields=None, predicate_ranges=None):
    """Yield dicts of column-name -> array per chunk.

    wanted_fields: subset of columns to materialize (column pruning).
    predicate_ranges: {field: (lo, hi)} — chunks whose min/max statistics
    cannot intersect are skipped without reading their bytes.
    """
    header = read_header(path)
    fields = header["fields"]
    want = wanted_fields or fields
    with open(path, "rb") as f:
        off = header["data_offset"]
        for chunk in header["chunks"]:
            sizes = chunk["sizes"]
            metas = chunk["columns"]
            # chunk pruning via column stats
            skip = False
            if predicate_ranges:
                for fi, name in enumerate(fields):
                    rng = predicate_ranges.get(name)
                    meta = metas[fi]
                    if rng and "min" in meta:
                        lo, hi = rng
                        if (hi is not None and meta["min"] > hi) or \
                           (lo is not None and meta["max"] < lo):
                            skip = True
                            break
            if skip:
                off += sum(sizes)
                continue
            out = {}
            coff = off
            for fi, name in enumerate(fields):
                if name in want:
                    f.seek(coff)
                    payload = f.read(sizes[fi])
                    out[name] = _unpack_column(metas[fi], payload)
                coff += sizes[fi]
            off += sum(sizes)
            yield chunk["rows"], out


class TabularSplit(Split):
    def __init__(self, index, path):
        super().__init__(index)
        self.path = path


class TabularRDD(RDD):
    """RDD of namedtuple-compatible row tuples from tabular part files,
    with column pruning + chunk-stat predicate pushdown."""

    def __init__(self, ctx, path, fields=None, wanted=None,
                 predicate_ranges=None):
        super().__init__(ctx)
        self.path = path
        if os.path.isdir(path):
            self.files = sorted(
                os.path.join(path, n) for n in os.listdir(path)
                if n.endswith(".tab"))
        else:
            self.files = [path]
        header = read_header(self.files[0]) if self.files else {"fields": []}
        self.fields = fields or header["fields"]
        self.wanted = wanted or self.fields
        self.predicate_ranges = predicate_ranges

    def _make_splits(self):
        return [TabularSplit(i, p) for i, p in enumerate(self.files)]

    def compute(self, split):
        for nrows, cols in read_chunks(split.path, self.wanted,
                                       self.predicate_ranges):
            mats = [cols[name] for name in self.wanted]
            pys = [m.tolist() if isinstance(m, np.ndarray) else m
                   for m in mats]
            for i in range(nrows):
                yield tuple(p[i] for p in pys)

    def asTable(self, name="tabular"):
        from dpark_tpu.table import TableRDD
        return TableRDD(self, self.wanted, name)


class OutputTabularRDD(DerivedRDD):
    def __init__(self, prev, path, fields, overwrite=True,
                 chunk_rows=65536):
        super().__init__(prev)
        path = os.path.abspath(path)
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.fields = list(fields)
        self.overwrite = overwrite
        self.chunk_rows = chunk_rows

    def compute(self, split):
        target = os.path.join(self.path, "part-%05d.tab" % split.index)
        if os.path.exists(target) and not self.overwrite:
            yield target
            return
        write_tabular(target, self.fields, self.prev.iterator(split),
                      self.chunk_rows)
        yield target
