"""Tabular: columnar on-disk format with per-column compression and
column-pruned scans.

Reference parity: dpark/tabular.py + dpark/bitindex.py (SURVEY.md section
2.3) — column chunks with per-column compression and an optional index
enabling predicate-pruned scans.  Format here is an original design with
the same capabilities, numpy-friendly so ingestion to device columns is a
memcpy.

Two on-disk versions, one reader:

  v1 (magic ``DTB1``, read-only compat):
      magic(4) header_json_len(4) header_json chunk-payload*
  v2 (magic ``DTB2``, what write_tabular emits):
      magic(4) chunk-payload* footer_json footer_len(4) magic(4)

v2 moved the metadata to a FOOTER so the writer streams chunks to disk
as they fill instead of buffering every compressed payload in memory,
and extended the per-chunk per-column statistics: min/max for every
numeric column (exact ints via .item()) plus a null count (``None``
entries of object columns, NaNs of float columns).  The query planner's
chunk-skip pushdown (dpark_tpu/query/) reads these stats; old v1 files
still read (their headers carry min/max but no null counts).
"""

import json
import os
import pickle
import struct
import zlib

import numpy as np

from dpark_tpu.rdd import RDD, Split, DerivedRDD
from dpark_tpu.utils import atomic_file

MAGIC = b"DTB1"            # v1: header at the front (read-only compat)
MAGIC2 = b"DTB2"           # v2: streamed chunks + stats footer
FOOTER_VERSION = 2


def _pack_column(arr):
    arr = np.asarray(arr)
    if arr.dtype == object or arr.dtype.kind in "US":
        # tolist() (not list()) so '<U' string arrays pickle PYTHON
        # strs, not np.str_ scalars — readers feed these to
        # partitioners/joins, where a np.str_ twin of an equal str
        # must not exist on disk at all
        vals = arr.tolist()
        payload = zlib.compress(pickle.dumps(vals, -1))
        meta = {"kind": "object",
                "nulls": sum(1 for v in vals if v is None)}
        return meta, payload
    payload = zlib.compress(np.ascontiguousarray(arr).tobytes())
    meta = {"kind": "numpy", "dtype": str(arr.dtype),
            "shape": list(arr.shape)}
    if arr.size and arr.dtype.kind in "if":
        if arr.dtype.kind == "f":
            nulls = int(np.count_nonzero(np.isnan(arr)))
            meta["nulls"] = nulls
            finite = arr[~np.isnan(arr)] if nulls else arr
        else:
            meta["nulls"] = 0
            finite = arr
        if finite.size:
            # .item() keeps integers exact (floats above 2**53 would
            # make chunk pruning skip matching data)
            meta["min"] = finite.min().item()
            meta["max"] = finite.max().item()
    return meta, payload


def _unpack_column(meta, payload):
    if meta["kind"] == "object":
        vals = pickle.loads(zlib.decompress(payload))
        # files written before the tolist() fix carry np.str_ scalars;
        # normalize on read so equal keys hash/compare as one type
        if vals and isinstance(vals[0], np.generic):
            vals = [v.item() if isinstance(v, np.generic) else v
                    for v in vals]
        return vals
    buf = zlib.decompress(payload)
    arr = np.frombuffer(buf, dtype=np.dtype(meta["dtype"]))
    return arr.reshape(meta["shape"])


def write_tabular(path, fields, rows, chunk_rows=65536,
                  version=FOOTER_VERSION):
    """rows: iterable of tuples matching `fields`.  Writes the v2
    footer format: chunk payloads stream to disk as they fill, the
    stats footer (per-chunk per-column min/max + null counts, version
    byte) lands at the end.  version=1 emits the legacy front-header
    layout (compat regression tests; real writers keep the default)."""
    if version == 1:
        return _write_tabular_v1(path, fields, rows, chunk_rows)
    chunks = []
    buf = []

    with atomic_file(path) as f:
        f.write(MAGIC2)

        def flush():
            if not buf:
                return
            cols = list(zip(*buf))
            metas = []
            offs = []
            for col in cols:
                meta, payload = _pack_column(np.asarray(col))
                offs.append(len(payload))
                metas.append(meta)
                f.write(payload)
            chunks.append({"rows": len(buf), "columns": metas,
                           "sizes": offs})
            buf.clear()

        for row in rows:
            buf.append(tuple(row))
            if len(buf) >= chunk_rows:
                flush()
        flush()
        footer = json.dumps({"version": FOOTER_VERSION,
                             "fields": list(fields),
                             "chunks": chunks}).encode("utf-8")
        f.write(footer)
        f.write(struct.pack("<I", len(footer)))
        f.write(MAGIC2)
    return path


def _write_tabular_v1(path, fields, rows, chunk_rows):
    """Legacy layout (front header, payloads buffered): exists so the
    old-files-still-read contract stays pinned by a real v1 writer."""
    chunks = []
    payloads = []
    buf = []

    def flush():
        if not buf:
            return
        cols = list(zip(*buf))
        metas = []
        offs = []
        for col in cols:
            meta, payload = _pack_column(np.asarray(col))
            # v1 headers never carried null counts
            meta.pop("nulls", None)
            offs.append(len(payload))
            metas.append(meta)
            payloads.append(payload)
        chunks.append({"rows": len(buf), "columns": metas,
                       "sizes": offs})
        buf.clear()

    for row in rows:
        buf.append(tuple(row))
        if len(buf) >= chunk_rows:
            flush()
    flush()
    header = json.dumps({"fields": list(fields),
                         "chunks": chunks}).encode("utf-8")
    with atomic_file(path) as f:
        f.write(MAGIC)
        f.write(struct.pack("<I", len(header)))
        f.write(header)
        for p in payloads:
            f.write(p)
    return path


def read_header(path):
    """Format-version-dispatching metadata read: v2 footers and v1
    front headers both come back as the same dict shape ({"version",
    "fields", "chunks", "data_offset"})."""
    with open(path, "rb") as f:
        magic = f.read(4)
        if magic == MAGIC2:
            f.seek(-8, os.SEEK_END)
            tail = f.read(8)
            (n,) = struct.unpack("<I", tail[:4])
            if tail[4:] != MAGIC2:
                raise IOError("truncated tabular v2 file: %s" % path)
            f.seek(-(8 + n), os.SEEK_END)
            header = json.loads(f.read(n).decode("utf-8"))
            header["data_offset"] = 4
            header.setdefault("version", FOOTER_VERSION)
            return header
        if magic != MAGIC:
            raise IOError("not a tabular file: %s" % path)
        (n,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(n).decode("utf-8"))
        header["data_offset"] = f.tell()
        header["version"] = 1
    return header


def chunk_stats(path):
    """Per-chunk, per-column statistics: a list (one entry per chunk)
    of {"rows": n, "columns": {field: {"min", "max", "nulls"}}} — the
    chunk-skip substrate the query planner's pushdown rule reads.
    Fields whose column kind carries no stats map to {} (v1 object
    columns); v1 numeric columns have min/max but no null counts."""
    header = read_header(path)
    out = []
    for chunk in header["chunks"]:
        cols = {}
        for name, meta in zip(header["fields"], chunk["columns"]):
            st = {}
            for k in ("min", "max", "nulls"):
                if k in meta:
                    st[k] = meta[k]
            cols[name] = st
        out.append({"rows": chunk["rows"], "columns": cols})
    return out


def source_fingerprint(path):
    """Cheap content fingerprint of one tabular file, for the result
    cache's invalidation key (ISSUE 18).

    v2 files digest the footer statistics (fields + per-chunk rows /
    sizes / min / max / nulls): rewriting any chunk's data rewrites its
    stats and sizes, so the digest drifts without reading a single data
    byte.  v1 files (and stat-less columns) have nothing content-like
    in the header — `chunk_stats` legitimately returns {} there — so
    they fall back to (path, mtime_ns, size), which must NOT error
    (satellite: mixed v1/v2 tables fingerprint fine, v1 just
    invalidates on any rewrite-in-place that touches mtime)."""
    import hashlib
    try:
        st = os.stat(path)
    except OSError:
        # a vanished part still fingerprints (to a sentinel no real
        # file can produce): the key just never matches again
        return ("v?", path, 0, -1)
    try:
        header = read_header(path)
    except (IOError, OSError, ValueError):
        return ("v?", path, st.st_mtime_ns, st.st_size)
    if header.get("version", 1) < FOOTER_VERSION:
        return ("v1", path, st.st_mtime_ns, st.st_size)
    h = hashlib.sha1()
    h.update(repr(header.get("fields")).encode("utf-8"))
    for chunk in header.get("chunks", []):
        h.update(repr((chunk.get("rows"), chunk.get("sizes"),
                       [sorted(m.items()) for m in
                        chunk.get("columns", [])])).encode("utf-8"))
    return ("v2", h.hexdigest())


def read_chunks(path, wanted_fields=None, predicate_ranges=None,
                stats=None):
    """Yield dicts of column-name -> array per chunk.

    wanted_fields: subset of columns to materialize (column pruning).
    predicate_ranges: {field: (lo, hi)} — chunks whose min/max statistics
    cannot intersect are skipped without reading their bytes.
    stats: optional dict the reader fills with scan accounting
    (chunks_total / chunks_skipped / columns_read / bytes_read) — the
    observability the query plane's "reads only referenced columns"
    acceptance asserts against.
    """
    header = read_header(path)
    fields = header["fields"]
    want = wanted_fields or fields
    if stats is not None:
        stats.setdefault("chunks_total", 0)
        stats.setdefault("chunks_skipped", 0)
        stats.setdefault("bytes_read", 0)
        cols_read = stats.setdefault("columns_read", set())
    with open(path, "rb") as f:
        off = header["data_offset"]
        for chunk in header["chunks"]:
            sizes = chunk["sizes"]
            metas = chunk["columns"]
            if stats is not None:
                stats["chunks_total"] += 1
            # chunk pruning via column stats
            skip = False
            if predicate_ranges:
                for fi, name in enumerate(fields):
                    rng = predicate_ranges.get(name)
                    meta = metas[fi]
                    if rng and "min" in meta:
                        lo, hi = rng
                        if (hi is not None and meta["min"] > hi) or \
                           (lo is not None and meta["max"] < lo):
                            skip = True
                            break
            if skip:
                off += sum(sizes)
                if stats is not None:
                    stats["chunks_skipped"] += 1
                continue
            out = {}
            coff = off
            for fi, name in enumerate(fields):
                if name in want:
                    f.seek(coff)
                    payload = f.read(sizes[fi])
                    out[name] = _unpack_column(metas[fi], payload)
                    if stats is not None:
                        stats["bytes_read"] += sizes[fi]
                        cols_read.add(name)
                coff += sizes[fi]
            off += sum(sizes)
            yield chunk["rows"], out


class TabularSplit(Split):
    def __init__(self, index, path):
        super().__init__(index)
        self.path = path


class TabularRDD(RDD):
    """RDD of namedtuple-compatible row tuples from tabular part files,
    with column pruning + chunk-stat predicate pushdown."""

    def __init__(self, ctx, path, fields=None, wanted=None,
                 predicate_ranges=None):
        super().__init__(ctx)
        self.path = path
        if os.path.isdir(path):
            self.files = sorted(
                os.path.join(path, n) for n in os.listdir(path)
                if n.endswith(".tab"))
        else:
            self.files = [path]
        header = read_header(self.files[0]) if self.files else {"fields": []}
        self.fields = fields or header["fields"]
        self.wanted = wanted or self.fields
        self.predicate_ranges = predicate_ranges

    def _make_splits(self):
        return [TabularSplit(i, p) for i, p in enumerate(self.files)]

    def compute(self, split):
        for nrows, cols in read_chunks(split.path, self.wanted,
                                       self.predicate_ranges):
            mats = [cols[name] for name in self.wanted]
            pys = [m.tolist() if isinstance(m, np.ndarray) else m
                   for m in mats]
            for i in range(nrows):
                yield tuple(p[i] for p in pys)

    def asTable(self, name="tabular"):
        from dpark_tpu.table import TableRDD
        return TableRDD(self, self.wanted, name)


class OutputTabularRDD(DerivedRDD):
    def __init__(self, prev, path, fields, overwrite=True,
                 chunk_rows=65536):
        super().__init__(prev)
        path = os.path.abspath(path)
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.fields = list(fields)
        self.overwrite = overwrite
        self.chunk_rows = chunk_rows

    def compute(self, split):
        target = os.path.join(self.path, "part-%05d.tab" % split.index)
        if os.path.exists(target) and not self.overwrite:
            yield target
            return
        write_tabular(target, self.fields, self.prev.iterator(split),
                      self.chunk_rows)
        yield target
