"""Process-level runtime wiring for driver and workers.

Reference parity: dpark/env.py (DparkEnv singleton + global `env`) — picks a
writable workdir from DPARK_WORK_DIR candidates and wires the map-output
tracker, cache tracker and shuffle fetcher into every process (SURVEY.md
sections 1 and 2.1).

Single-host simplification vs the reference: the zmq TrackerServer becomes an
in-process dict on the driver; workers receive map-output *snapshots*
embedded in task payloads (parent stages are always complete before a reduce
task is serialized, so a snapshot is exact — see schedule.py).  A TCP tracker
for multi-host DCN deployments lives in tracker.py.
"""

import os
import socket
import tempfile
import uuid


class MapOutputTracker:
    """shuffle_id -> list of per-map-task output URIs (None = missing)."""

    def __init__(self):
        self.locs = {}

    def register_outputs(self, shuffle_id, locs):
        self.locs[shuffle_id] = list(locs)

    def get_outputs(self, shuffle_id):
        return self.locs.get(shuffle_id)

    def invalidate_host(self, shuffle_id, host):
        locs = self.locs.get(shuffle_id, [])
        for i, uri in enumerate(locs):
            if uri and host in uri:
                locs[i] = None

    def snapshot(self, shuffle_ids):
        return {sid: self.locs[sid] for sid in shuffle_ids
                if sid in self.locs}

    def update(self, snap):
        self.locs.update(snap)


class DparkEnv:
    def __init__(self):
        from dpark_tpu.hostatus import TaskHostManager
        self.started = False
        self.is_master = False
        self.workdir = None
        self.map_output_tracker = MapOutputTracker()
        self.cache = None                 # set by cache.py on start
        self.shuffle_fetcher = None       # set by shuffle.py on start
        self.session_id = None
        self.bucket_server = None         # DCN data plane, opt-in
        self.tracker_client = None        # DCN metadata plane, opt-in
        self.tracker_addr = None
        # ONE host-health view per process, shared by the scheduler's
        # task placement AND the shuffle fetcher's replica choice —
        # fetch failures inform placement and vice versa (SURVEY.md
        # section 5.3; hostatus.py)
        self.host_manager = TaskHostManager()

    def start(self, is_master=True, environ=None):
        if self.started:
            return
        self.started = True
        self.is_master = is_master
        environ = environ or {}
        self.session_id = environ.get(
            "DPARK_SESSION", uuid.uuid4().hex[:12])
        self.workdir = environ.get("DPARK_WORKDIR") or self._pick_workdir()
        os.makedirs(self.workdir, exist_ok=True)

        # trace plane (ISSUE 8): a worker process inherits the
        # driver's mode/dir through the shipped environ (covers
        # programmatic trace.configure() on the driver, which env vars
        # alone would miss).  Re-configuring also re-stamps the plane
        # with THIS process's pid — a plane inherited by fork (the
        # forkserver imported trace with DPARK_TRACE set) carries the
        # parent's pid, which would corrupt the latest-counter-per-pid
        # merge.  Spool files are per-pid, so workers never contend.
        if not is_master:
            from dpark_tpu import trace
            tmode = environ.get("DPARK_TRACE")
            try:
                if tmode:
                    trace.configure(tmode,
                                    environ.get("DPARK_TRACE_DIR"),
                                    run=environ.get("DPARK_TRACE_RUN"))
                elif trace.active():
                    trace.configure(trace.mode(), trace.trace_dir(),
                                    run=trace.run_id())
            except Exception:
                pass

        from dpark_tpu.shuffle import ParallelShuffleFetcher
        from dpark_tpu.cache import Cache
        self.shuffle_fetcher = ParallelShuffleFetcher()
        self.cache = Cache(self.workdir)
        if environ.get("DPARK_BUCKET_SERVER") \
                or os.environ.get("DPARK_BUCKET_SERVER"):
            self.start_bucket_server()
        addr = environ.get("DPARK_TRACKER") \
            or os.environ.get("DPARK_TRACKER")
        if addr:
            from dpark_tpu.tracker import TrackerClient
            self.tracker_client = TrackerClient(addr)
            self.tracker_addr = addr

    def start_bucket_server(self, port=0):
        """Serve this process's shuffle buckets + broadcast chunks over
        TCP (the DCN data plane); shuffle URIs switch to tcp://."""
        if self.bucket_server is None:
            from dpark_tpu.dcn import BucketServer
            self.bucket_server = BucketServer(
                self.workdir, port=port).start()
        return self.bucket_server

    def _pick_workdir(self):
        from dpark_tpu import conf
        for cand in conf.DPARK_WORK_DIR.split(","):
            cand = cand.strip()
            if not cand:
                continue
            try:
                path = os.path.join(cand, "dpark-%s" % self.session_id)
                os.makedirs(path, exist_ok=True)
                return path
            except OSError:
                continue
        return tempfile.mkdtemp(prefix="dpark-")

    def environ_for_worker(self):
        out = {"DPARK_SESSION": self.session_id,
               "DPARK_WORKDIR": self.workdir}
        if getattr(self, "mem_limit", None):
            out["DPARK_MEM_LIMIT"] = str(self.mem_limit)
        if getattr(self, "profile", False):
            out["DPARK_PROFILE"] = "1"
        from dpark_tpu import trace
        if trace.active():
            out["DPARK_TRACE"] = trace.mode()
            out["DPARK_TRACE_DIR"] = trace.trace_dir()
            out["DPARK_TRACE_RUN"] = trace.run_id()
        return out

    def stop(self):
        if not self.started:
            return
        self.started = False
        if self.shuffle_fetcher:
            self.shuffle_fetcher.stop()
        if self.bucket_server is not None:
            self.bucket_server.stop()
            self.bucket_server = None
        if self.tracker_client is not None:
            self.tracker_client.close()
            self.tracker_client = None
            self.tracker_addr = None

    @property
    def host(self):
        return socket.gethostname()


env = DparkEnv()
