"""Multi-host bootstrap: DCN coordination for the tpu master.

Reference parity: the reference's Mesos control plane + zmq tracker
(SURVEY.md section 2.8) — its TPU-era equivalent is jax.distributed (one
jax process per host, devices glued into one global mesh over ICI/DCN)
plus the TCP tracker (dpark_tpu/tracker.py) as the metadata plane.

Topology:
  host 0: driver — DparkContext('tpu'), TrackerServer, jax coordinator;
  host k: `mrun -n N python -m dpark_tpu.distributed` (or any program
          calling init()) joins the mesh; the TPUScheduler then sees
          jax.devices() spanning all hosts and shard_map collectives ride
          ICI within a host and DCN across hosts.

Single-host processes may call init() with num_processes=1 (no-op
coordinator) so the same program runs unchanged everywhere.
"""

import os

from dpark_tpu.utils.log import get_logger

logger = get_logger("distributed")


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Join (or create) the multi-host jax world.

    Defaults come from the mrun/SLURM-style env vars:
      MRUN_RANK/RANK, MRUN_SIZE/WORLD_SIZE, DPARK_COORDINATOR.
    Returns (process_id, num_processes).
    """
    import jax

    if num_processes is None:
        num_processes = int(os.environ.get("MRUN_SIZE")
                            or os.environ.get("WORLD_SIZE") or 1)
    if process_id is None:
        process_id = int(os.environ.get("MRUN_RANK")
                         or os.environ.get("RANK") or 0)
    if coordinator_address is None:
        coordinator_address = os.environ.get(
            "DPARK_COORDINATOR", "127.0.0.1:8476")

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        logger.info("joined jax world %d/%d via %s",
                    process_id, num_processes, coordinator_address)
    return process_id, num_processes


def start_tracker_if_driver(process_id=0, port=0):
    """On the driver host, start the TCP tracker (metadata plane) and
    return its address; workers connect with TrackerClient."""
    from dpark_tpu.tracker import TrackerServer
    if process_id != 0:
        return None
    srv = TrackerServer(port=port)
    srv.start()
    return srv
