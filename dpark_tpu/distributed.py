"""Multi-host bootstrap: DCN coordination for the tpu master.

Reference parity: the reference's Mesos control plane + zmq tracker
(SURVEY.md section 2.8) — its TPU-era equivalent is jax.distributed (one
jax process per host, devices glued into one global mesh over ICI/DCN)
plus the TCP tracker (dpark_tpu/tracker.py) as the metadata plane.

Topology:
  host 0: driver — DparkContext('tpu'), TrackerServer, jax coordinator;
  host k: `mrun -n N python -m dpark_tpu.distributed` (or any program
          calling init()) joins the mesh; the TPUScheduler then sees
          jax.devices() spanning all hosts and shard_map collectives ride
          ICI within a host and DCN across hosts.

Single-host processes may call init() with num_processes=1 (no-op
coordinator) so the same program runs unchanged everywhere.
"""

import os
import time

from dpark_tpu.utils.log import get_logger

logger = get_logger("distributed")


def _file_rendezvous(path, process_id, timeout=120):
    """file:// coordinator rendezvous: rank 0 picks a free port ITSELF
    (no launcher-side bind/close/reuse race — the window between
    choosing and jax binding is microseconds inside one process, and a
    stolen port fails the bind loudly instead of connecting ranks to a
    stranger) and publishes host:port by atomic rename; other ranks
    poll the path.  Multi-host deployments put the path on the shared
    FS (the reference's workdir-on-MooseFS pattern)."""
    nonce = os.environ.get("DPARK_RUN_NONCE", "")
    if process_id == 0:
        import socket
        from dpark_tpu.dcn import _routable_host
        try:
            os.unlink(path)       # a LEFTOVER address from a previous
        except OSError:           # run must never be joinable; use a
            pass                  # fresh path per run where possible
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        addr = "%s:%d" % (_routable_host(), port)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            # second line: per-run nonce (when the launcher provides
            # one) — joiners require an exact match, so freshness never
            # depends on a TCP probe that an unrelated service re-bound
            # to the recorded port could also pass
            f.write(addr + ("\n" + nonce if nonce else ""))
        os.replace(tmp, path)
        return addr
    # leftover guard, clock-free: a rank can start before rank 0 has
    # replaced a LEFTOVER file from a previous run, and joining a dead
    # old coordinator hangs until jax's timeout.  A file that already
    # existed when this rank began polling is suspect; accept it once
    # (a) its identity (inode/mtime/size) changes — rank 0 of THIS run
    # re-published — or (b) the address accepts a TCP connection (the
    # coordinator is alive; rank 0 publishes before jax binds, so (b)
    # turns true once initialize() listens).  No wall-clock window: a
    # rank that starts minutes late (image pull, scheduler delay) or a
    # shared FS with a skewed clock must still join.  A still-running
    # coordinator from an OLD run passes (b) — use a fresh path per
    # run to exclude that, as mrun does.
    def _ident():
        st = os.stat(path)
        return (st.st_ino, st.st_mtime_ns, st.st_size)

    def _alive(addr):
        import socket
        host, _, port = addr.rpartition(":")
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=1.0):
                return True
        except (OSError, ValueError):
            return False

    try:
        suspect = _ident()
    except OSError:
        suspect = None
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
            addr = lines[0].strip() if lines else ""
            file_nonce = lines[1].strip() if len(lines) > 1 else ""
        except OSError:
            addr, file_nonce = "", ""
        if nonce:
            # launcher gave every rank the run's nonce: accept only a
            # file carrying it (an unrelated listener at a recycled
            # port can pass _alive(); it cannot forge the nonce), then
            # gate on liveness alone
            if addr and file_nonce == nonce and _alive(addr):
                return addr
        elif addr:
            try:
                fresh = suspect is None or _ident() != suspect
            except OSError:
                fresh = False
            if fresh or _alive(addr):
                return addr
        time.sleep(0.05)
    raise TimeoutError("no coordinator address at %s after %ds"
                       % (path, timeout))


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Join (or create) the multi-host jax world.

    Defaults come from the mrun/SLURM-style env vars:
      MRUN_RANK/RANK, MRUN_SIZE/WORLD_SIZE, DPARK_COORDINATOR.
    DPARK_COORDINATOR may be host:port or file:///path — the latter
    rendezvouses through the filesystem with rank 0 choosing the port.
    Returns (process_id, num_processes).
    """
    import jax

    if num_processes is None:
        num_processes = int(os.environ.get("MRUN_SIZE")
                            or os.environ.get("WORLD_SIZE") or 1)
    if process_id is None:
        process_id = int(os.environ.get("MRUN_RANK")
                         or os.environ.get("RANK") or 0)
    if coordinator_address is None:
        coordinator_address = os.environ.get(
            "DPARK_COORDINATOR", "127.0.0.1:8476")
    if coordinator_address.startswith("file://"):
        coordinator_address = _file_rendezvous(
            coordinator_address[len("file://"):], process_id)

    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        logger.info("joined jax world %d/%d via %s",
                    process_id, num_processes, coordinator_address)
    return process_id, num_processes


def start_tracker_if_driver(process_id=0, port=0):
    """On the driver host, start the TCP tracker (metadata plane) and
    return its address; workers connect with TrackerClient."""
    from dpark_tpu.tracker import TrackerServer
    if process_id != 0:
        return None
    srv = TrackerServer(port=port)
    srv.start()
    return srv
