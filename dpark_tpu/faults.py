"""Chaos plane: deterministic, conf-driven fault injection at named
sites (ISSUE 5 tentpole).

dpark's promise is lineage-based recovery — FetchFailed resubmits the
parent stage, failed tasks retry with escalation, stragglers speculate
— but recovery code that is never exercised is recovery code that is
assumed, not proven.  This module provides NAMED INJECTION SITES wired
through the shuffle, scheduler, executor, dcn, and checkpoint layers;
a seeded spec activates them deterministically so the same chaos run
replays bit-identically, and the parity suite (tests/test_faults.py)
asserts that jobs complete with results identical to their clean runs.

Spec grammar (the ``DPARK_FAULTS`` env var / ``conf.DPARK_FAULTS``)::

    site:param=value,param=value;site2:...

    DPARK_FAULTS="shuffle.fetch:p=0.2,seed=7;executor.dispatch:nth=3,kind=oom"

Sites (each a choke point the runtime already flows through):

    shuffle.fetch        reduce-side bucket fetch (per replica attempt)
    shuffle.spill_write  spill-run / spill-chunk write (host + device paths)
    shuffle.spill_read   spill-run / spill-chunk read-back
    executor.dispatch    device program dispatch (per program / per wave)
    executor.compile     device program compile (per cache miss)
    dcn.connect          TCP connect to a peer bucket server
    dcn.transfer         bulk-channel chunk transfer (per frame, BOTH
                         sides: a server-side `raise` kills the stream
                         mid-transfer — deterministic peer-death — and
                         a client-side `corrupt` flips payload bytes
                         the frame crc must catch)
    checkpoint.write     checkpoint / snapshot part-file write

Per-site parameters:

    nth=N     fire on exactly the Nth hit of the site (1-based)
    p=X       fire per hit with probability X from a seeded RNG
    seed=S    RNG seed for p= draws (default 0; the draw SEQUENCE is
              deterministic, so a chaos run replays exactly)
    times=T   cap total firings (default: 1 for nth/bare specs,
              unlimited for p=)
    kind=K    what a firing does:
                raise    raise FaultInjected (an OSError) [default]
                enospc   raise OSError(ENOSPC) — disk full
                oom      raise XlaRuntimeError("RESOURCE_EXHAUSTED...")
                corrupt  flip a byte of the site's payload bytes
                         (crc framing downstream must catch it)
                delay    sleep ms= milliseconds, then proceed
                kill     os._exit(137) — the process dies as if
                         `kill -9`-ed at the site: no atexit, no
                         finally blocks, no journal flush.  The crash
                         leg of the chaos certification (ISSUE 20).
    ms=M      delay duration for kind=delay (default 50)

A bare ``site`` (no params) fires once, on the first hit.

Hot-path cost when no plane is configured: one global ``is None``
check per hit.  Thread-safe: sites are hit from fetcher/spill-writer
threads concurrently.
"""

import errno
import os
import threading
import time

__all__ = ["SITES", "FaultInjected", "configure", "active", "hit",
           "stats"]

SITES = ("shuffle.fetch", "shuffle.spill_write", "shuffle.spill_read",
         "executor.dispatch", "executor.compile", "dcn.connect",
         "dcn.transfer", "checkpoint.write")

KINDS = ("raise", "enospc", "oom", "corrupt", "delay", "kill")


class FaultInjected(OSError):
    """An injected fault.  Subclasses OSError so every site treats it
    as the I/O error it simulates: the shuffle fetch wraps it into
    FetchFailed, the dcn connect retry backs off on it, the spill
    writer surfaces it as a task failure."""

    def __init__(self, site, detail=""):
        msg = "injected fault at %s%s" % (site,
                                          " (%s)" % detail if detail
                                          else "")
        super().__init__(errno.EIO, msg)
        self.site = site


def _oom_error():
    """A device-OOM-shaped error: the REAL XlaRuntimeError type when
    jax is importable (so production except-clauses are exercised
    verbatim), else a name-matched stand-in — the degradation
    classifier matches type name and the RESOURCE_EXHAUSTED message,
    which both forms carry."""
    msg = ("RESOURCE_EXHAUSTED: injected device OOM (chaos plane); "
           "allocating 0B exceeds 0B HBM")
    try:
        import jaxlib.xla_extension as _xe
        return _xe.XlaRuntimeError(msg)
    except Exception:
        pass

    class XlaRuntimeError(RuntimeError):
        pass

    return XlaRuntimeError(msg)


def corrupt_bytes(data):
    """Deterministically flip one byte in the middle of `data`
    (length-preserving — simulates on-disk/in-flight corruption that
    only an integrity check can catch)."""
    buf = bytearray(data)
    if not buf:
        return bytes(buf)
    buf[len(buf) // 2] ^= 0xFF
    return bytes(buf)


class _SiteSpec:
    def __init__(self, site, params):
        import random
        self.site = site
        self.kind = params.get("kind", "raise")
        if self.kind not in KINDS:
            raise ValueError("unknown fault kind %r (one of %s)"
                             % (self.kind, ", ".join(KINDS)))
        self.p = float(params["p"]) if "p" in params else None
        self.nth = int(params["nth"]) if "nth" in params else None
        self.seed = int(params.get("seed", 0))
        self.ms = float(params.get("ms", 50.0))
        if "times" in params:
            self.times = int(params["times"])
        else:
            # nth naturally fires once; a bare spec fires once too so a
            # recovery test terminates; p= runs until told otherwise
            self.times = None if self.p is not None else 1
        self.rng = random.Random(self.seed)
        self.hits = 0
        self.fired = 0

    def should_fire(self):
        """Count a hit; decide (deterministically) whether to fire."""
        self.hits += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None:
            fire = self.hits == self.nth
        elif self.p is not None:
            # the draw happens on EVERY hit so the firing pattern is a
            # pure function of (seed, hit index), independent of caps
            fire = self.rng.random() < self.p
        else:
            fire = True
        if fire:
            self.fired += 1
        return fire

    def describe(self):
        out = {"kind": self.kind, "hits": self.hits,
               "fired": self.fired}
        if self.p is not None:
            out["p"] = self.p
            out["seed"] = self.seed
        if self.nth is not None:
            out["nth"] = self.nth
        return out


class FaultPlane:
    def __init__(self, specs):
        self.specs = specs              # site -> _SiteSpec
        self._lock = threading.Lock()

    def hit(self, site, payload=None):
        spec = self.specs.get(site)
        if spec is None:
            return payload
        with self._lock:
            fire = spec.should_fire()
        if not fire:
            return payload
        if spec.kind == "delay":
            time.sleep(spec.ms / 1000.0)
            return payload
        if spec.kind == "kill":
            # hard process death, bypassing atexit/finally — the only
            # honest way to certify crash recovery is to never give the
            # dying process a chance to tidy up
            os._exit(137)
        if spec.kind == "corrupt":
            if payload is None:
                # the site carries no byte payload: corruption
                # degenerates to a failure, not a silent no-op
                raise FaultInjected(site, "corrupt at a payload-less "
                                          "site")
            return corrupt_bytes(payload)
        if spec.kind == "oom":
            raise _oom_error()
        if spec.kind == "enospc":
            raise OSError(errno.ENOSPC,
                          "injected fault at %s (disk full)" % site)
        raise FaultInjected(site, "kind=raise")

    def stats(self):
        with self._lock:
            return {site: spec.describe()
                    for site, spec in self.specs.items()}


def parse_spec(text):
    """``site:k=v,k=v;site2:...`` -> {site: _SiteSpec}.  Unknown sites
    and malformed params raise ValueError — a chaos run with a typo'd
    site silently injecting nothing would "prove" recovery it never
    exercised."""
    specs = {}
    for part in (text or "").split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, rest = part.partition(":")
        site = site.strip()
        if site not in SITES:
            raise ValueError("unknown fault site %r (one of %s)"
                             % (site, ", ".join(SITES)))
        params = {}
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError("malformed fault param %r in %r"
                                 % (kv, part))
            params[k.strip()] = v.strip()
        specs[site] = _SiteSpec(site, params)
    return specs


_PLANE = None


def configure(spec=None):
    """Install the chaos plane from a spec string (None/"" clears it).
    Counters start fresh — configuring the same spec twice replays the
    same firing sequence.  Returns the installed FaultPlane or None."""
    global _PLANE
    if not spec:
        _PLANE = None
        return None
    _PLANE = FaultPlane(parse_spec(spec))
    return _PLANE


def active():
    """True when a chaos plane with at least one site is installed."""
    return _PLANE is not None and bool(_PLANE.specs)


def site_active(site):
    """True when the installed plane names `site`.  Fetch paths use
    this to decide whether per-shard chaos routing is worth racing
    through a thread pool (an injected delay must be able to LOSE the
    fastest-k race) or can run inline on the hot path."""
    plane = _PLANE
    return plane is not None and site in plane.specs


def hit(site, payload=None):
    """Record a hit at `site`.  May raise (raise/enospc/oom kinds),
    sleep (delay), or return a corrupted copy of `payload` (corrupt);
    otherwise returns `payload` unchanged.  No-op without a plane."""
    plane = _PLANE
    if plane is None:
        return payload
    return plane.hit(site, payload)


def stats():
    """{site: {hits, fired, kind, ...}} for the installed plane (empty
    when inactive) — the bench JSON's `faults` section."""
    plane = _PLANE
    if plane is None:
        return {}
    return plane.stats()


def _init_from_conf():
    from dpark_tpu import conf
    spec = getattr(conf, "DPARK_FAULTS", "")
    if spec:
        configure(spec)


_init_from_conf()
