"""dpark_tpu.analysis — pre-flight plan linter + AST closure analyzer.

Catches silent-wrong-answer shapes and shuffle anti-patterns BEFORE a
job runs: DparkContext.runJob calls preflight() on every submitted
lineage, and the dlint CLI (tools/dlint / python -m dpark_tpu.analysis)
runs the closure rules over source trees for CI.

Severity policy (conf.DPARK_LINT / the DPARK_LINT env var):
  off    no checks at all
  warn   findings log once per (rule, site) per process  [default]
  error  error-severity findings refuse the plan (PlanLintError)
         before any task launches

This package is also the lineage-introspection substrate for future
communication-structure work (coded shuffles know the comms pattern of
a plan up front — the same artifact these rules walk).
"""

from dpark_tpu.analysis.report import (Finding, PlanLintError, Report,
                                       lint_mode)
from dpark_tpu.analysis.plan_rules import iter_lineage, lint_plan
from dpark_tpu.analysis.closure_rules import (iter_plan_functions,
                                              lint_function, lint_source)
from dpark_tpu.analysis.concurrency import (ConcurrencyPass,
                                            lint_concurrency)
from dpark_tpu.utils.log import get_logger

logger = get_logger("analysis")

__all__ = ["ConcurrencyPass", "Finding", "PlanLintError", "Report",
           "lint_concurrency", "lint_mode",
           "lint_plan", "lint_source", "lint_function", "iter_lineage",
           "iter_plan_functions", "preflight"]

# (rule, site) pairs already logged this process — pre-flight runs on
# EVERY job (including tiny internal probe jobs), so each finding logs
# exactly once; error-severity refusal still triggers every submit
_reported = set()


def preflight(rdd, master="local", func=None):
    """Lint the lineage of `rdd` (plan rules + closure rules over every
    user function it carries) before the scheduler sees it.

    Returns the Report (possibly empty).  Under DPARK_LINT=error any
    error-severity finding raises PlanLintError — the plan is refused
    before a single task launches.  Under the default "warn" each
    finding logs once per process.  "off" skips all work."""
    mode = lint_mode()
    if mode == "off":
        return None
    tpu = str(master).partition(":")[0] == "tpu"
    report = Report()
    try:
        import itertools
        from dpark_tpu import conf
        from dpark_tpu.analysis.plan_rules import iter_lineage as _il
        cap = int(getattr(conf, "LINT_MAX_NODES", 500)) or 500
        lineage = list(itertools.islice(_il(rdd), cap + 1))
        if len(lineage) > cap:
            lineage = lineage[:cap]
            logger.debug("preflight walk capped at %d lineage nodes "
                         "(LINT_MAX_NODES)", cap)
        fcode = getattr(func, "__code__", None)
        cache_key = (len(lineage), mode,
                     (fcode.co_filename, fcode.co_firstlineno)
                     if fcode is not None else type(func).__name__)
        cached = getattr(rdd, "_preflight_cache", None)
        if cached is not None and cached[0] == cache_key:
            # same final rdd object, same-shaped lineage, same mode and
            # action function: repeated actions on one RDD (collect
            # then count, sort's sampling passes) skip the rule walk —
            # findings were already reported once, and the error-mode
            # verdict is replayed so a refused plan stays refused on
            # re-submission.  (Streaming ticks build a FRESH final rdd
            # per batch and miss this cache; their per-tick cost is
            # bounded by the LINT_MAX_NODES walk cap instead.)
            report = cached[1]
            if mode == "error" and report.errors():
                raise PlanLintError(report)
            return report
        lint_plan(rdd, master=master, report=report, lineage=lineage)
        for fn, site in iter_plan_functions(rdd, lineage=lineage):
            lint_function(fn, site=site, report=report, tpu=tpu)
        if func is not None:
            lint_function(func, report=report, tpu=tpu)
        rdd._preflight_cache = (cache_key, report)
    except PlanLintError:
        raise
    except Exception as e:          # the linter must never kill a good job
        logger.debug("preflight lint pass failed: %s", e)
        return report
    for f in report:
        if f.key not in _reported:
            _reported.add(f.key)
            log = logger.error if f.severity == "error" else (
                logger.warning if f.severity == "warn" else logger.info)
            log("%s", f.render())
    # stash on the final rdd so the scheduler's job record (web UI)
    # carries the findings alongside stage info
    if report:
        rdd._lint_findings = report.as_dicts()
    if mode == "error" and report.errors():
        raise PlanLintError(report)
    return report
