"""AST closure analyzer: inspects user functions passed to RDD
transforms WITHOUT executing them.

Two entry layers share the same rules:

* lint_function(fn, ...)  — a live callable collected from an RDD
  lineage at pre-flight time.  Closure cells and referenced globals are
  introspected directly (precise: an actual RDD/DparkContext instance
  in a cell IS a capture); the function's source, when available, also
  runs through the AST checks.
* lint_source(path, ...)  — a whole source file (the dlint CLI / CI
  self-lint).  Module-scope assignment tracking identifies names bound
  to contexts and RDD chains; closures passed to transform calls are
  analyzed against that scope.

Rules:

  closure-rdd-capture     a function shipped to workers references an
                          RDD or DparkContext — pickling either drags
                          the whole driver graph into every task (or
                          fails outright on the process/tpu masters).
  closure-unseeded-random unseeded random.*/np.random.*/time.time()
                          inside a deterministic transform: retries and
                          speculative duplicates see different data
                          (silent wrong answers under speculation).
  closure-tracer-branch   Python control flow on runtime values
                          (`if x > 0:`), `.item()`, or float()/int()
                          coercion of arguments — unsafe under the jax
                          tracer when the stage is routed to the tpu
                          master (forces host fallback at best, tracer
                          errors at worst).
"""

import ast
import inspect
import textwrap

from dpark_tpu.analysis.report import Report

# transform methods whose function argument ships to workers and must be
# deterministic; foreach/mapPartitions ride along for the capture rule
TRANSFORM_METHODS = {
    "map", "flatMap", "filter", "mapValue", "mapValues", "flatMapValue",
    "flatMapValues", "keyBy", "groupBy", "reduce", "fold", "aggregate",
    "reduceByKey", "combineByKey", "foldByKey", "mapPartitions",
    "mapPartition", "mapPartitionsWithIndex", "mapPartitionWithIndex",
    "foreach", "foreachPartition", "top", "sort",
    "updateStateByKey", "reduceByKeyAndWindow", "transform",
}

# DparkContext factories producing RDDs (file-mode scope tracking)
CONTEXT_FACTORIES = {
    "parallelize", "makeRDD", "textFile", "partialTextFile", "csvFile",
    "binaryFile", "tableFile", "table", "beansdb", "tabular", "union",
    "zip",
}

_RANDOM_FNS = {"random", "randint", "randrange", "uniform", "choice",
               "choices", "shuffle", "sample", "gauss", "normalvariate",
               "betavariate", "expovariate", "vonmisesvariate",
               "paretovariate", "weibullvariate", "triangular",
               "lognormvariate", "getrandbits", "randbytes", "rand",
               "randn", "standard_normal", "permutation"}
_TIME_FNS = {"time", "time_ns", "monotonic", "perf_counter"}


# ---------------------------------------------------------------------------
# shared AST checks over one function body
# ---------------------------------------------------------------------------

class _ClosureVisitor(ast.NodeVisitor):
    """Walk ONE function's body collecting rule hits; nested lambdas
    and defs are part of the closure and walked too."""

    def __init__(self, params, known_rdd_names=(), known_ctx_names=()):
        self.params = set(params)
        self.rdd_names = set(known_rdd_names)
        self.ctx_names = set(known_ctx_names)
        self.random_calls = []      # (lineno, "random.random")
        self.time_calls = []
        self.tracer_branches = []   # (lineno, kind)
        self.captured = []          # (lineno, name)

    # -- captures --------------------------------------------------------
    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load) \
                and node.id not in self.params \
                and (node.id in self.rdd_names
                     or node.id in self.ctx_names):
            self.captured.append((node.lineno, node.id))
        self.generic_visit(node)

    # -- nondeterminism --------------------------------------------------
    def visit_Call(self, node):
        fn = node.func
        dotted = _dotted(fn)
        if dotted:
            parts = dotted.split(".")
            head, tail = parts[0], parts[-1]
            if tail in _RANDOM_FNS and head in ("random", "np", "numpy",
                                                "jax"):
                self.random_calls.append((node.lineno, dotted))
            elif tail in _TIME_FNS and head == "time":
                self.time_calls.append((node.lineno, dotted))
            elif tail == "item":
                # x.item() forces a concrete value out of a traced array
                self.tracer_branches.append((node.lineno, dotted + "()"))
        elif isinstance(fn, ast.Name) and fn.id in ("float", "int",
                                                    "bool"):
            if any(self._derives_from_param(a) for a in node.args):
                self.tracer_branches.append(
                    (node.lineno, "%s() on an argument" % fn.id))
        self.generic_visit(node)

    # -- tracer-unsafe branching ----------------------------------------
    def visit_If(self, node):
        if self._derives_from_param(node.test):
            self.tracer_branches.append(
                (node.lineno, "if on a runtime value"))
        self.generic_visit(node)

    def visit_While(self, node):
        if self._derives_from_param(node.test):
            self.tracer_branches.append(
                (node.lineno, "while on a runtime value"))
        self.generic_visit(node)

    def visit_IfExp(self, node):
        if self._derives_from_param(node.test):
            self.tracer_branches.append(
                (node.lineno, "conditional expression on a runtime "
                              "value"))
        self.generic_visit(node)

    def _derives_from_param(self, expr):
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in self.params:
                return True
        return False

    # nested functions inherit the parameter set (their own params are
    # also runtime values when called with closure data)
    def visit_Lambda(self, node):
        inner = set(self.params)
        inner.update(a.arg for a in node.args.args)
        saved, self.params = self.params, inner
        self.generic_visit(node)
        self.params = saved

    def visit_FunctionDef(self, node):
        inner = set(self.params)
        inner.update(a.arg for a in node.args.args)
        saved, self.params = self.params, inner
        self.generic_visit(node)
        self.params = saved


def _dotted(node):
    """'a.b.c' for an Attribute chain rooted at a Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _fn_params(node):
    args = node.args
    names = [a.arg for a in args.posonlyargs + args.args
             + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    # defaults rebind a captured name to a parameter-local copy — the
    # classic `lambda x, rdd=rdd:` idiom is SAFE only for plain values,
    # but for the capture rule the name is a param, not a free load
    return names


def _emit(report, visitor, site, tpu=False, deterministic=True):
    for lineno, name in visitor.captured:
        report.add(
            "closure-rdd-capture", "error", "%s:%d" % (site, lineno),
            "worker function captures %r (an RDD/DparkContext): tasks "
            "would serialize the whole driver-side graph" % name,
            "collect()/broadcast() the data it needs instead, or join "
            "the two datasets")
    if deterministic:
        for lineno, name in visitor.random_calls:
            report.add(
                "closure-unseeded-random", "warn",
                "%s:%d" % (site, lineno),
                "unseeded %s() in a deterministic stage: task retries "
                "and speculative duplicates see different data" % name,
                "seed per partition (mapPartitionsWithIndex + "
                "random.Random(seed + index)) or precompute the draw")
        for lineno, name in visitor.time_calls:
            report.add(
                "closure-unseeded-random", "warn",
                "%s:%d" % (site, lineno),
                "%s() in a deterministic stage: recomputation and "
                "retries observe different clocks" % name,
                "stamp times on the driver and broadcast the value")
    sev = "warn" if tpu else "info"
    for lineno, kind in visitor.tracer_branches:
        report.add(
            "closure-tracer-branch", sev, "%s:%d" % (site, lineno),
            "%s: tracer-unsafe under the tpu master's jitted array "
            "path (concretization error or silent host fallback)"
            % kind,
            "use jnp.where/lax.cond-style data-parallel forms, or "
            "keep this stage on the host path")


# ---------------------------------------------------------------------------
# live-callable mode (pre-flight)
# ---------------------------------------------------------------------------

def _capture_values(fn):
    """(name, value) pairs a callable would drag along when pickled:
    closure cells plus the globals its code references."""
    out = []
    code = getattr(fn, "__code__", None)
    if code is None:
        call = getattr(type(fn), "__call__", None)
        # callable object (the _PartReduce idiom): its attributes ship
        for k, v in list(getattr(fn, "__dict__", {}).items())[:32]:
            out.append((k, v))
        code = getattr(call, "__code__", None)
        if code is None:
            return out
        fn = call
    closure = getattr(fn, "__closure__", None) or ()
    for name, cell in zip(code.co_freevars, closure):
        try:
            out.append((name, cell.cell_contents))
        except ValueError:
            pass                    # empty cell
    fglobals = getattr(fn, "__globals__", {})
    for name in code.co_names:
        if name in fglobals:
            out.append((name, fglobals[name]))
    return out


def lint_function(fn, site=None, report=None, tpu=False,
                  deterministic=True, _ast_cache={}):
    """Lint one live callable.  Closure/global capture inspection never
    needs source; the AST rules run when inspect.getsource works."""
    from dpark_tpu.context import DparkContext
    from dpark_tpu.rdd import RDD
    report = report if report is not None else Report()
    site = site or _describe(fn)
    for name, value in _capture_values(fn):
        if isinstance(value, (RDD, DparkContext)):
            report.add(
                "closure-rdd-capture", "error", site,
                "worker function captures %r = %r: tasks would "
                "serialize the whole driver-side graph" % (name, value),
                "collect()/broadcast() the data it needs instead, or "
                "join the two datasets")
    code = getattr(fn, "__code__", None)
    if code is None:
        return report
    # stable identity — id(code) can be reused after GC, serving a
    # stale AST for a different function; co_code disambiguates
    # several lambdas sharing one source line
    key = (code.co_filename, code.co_firstlineno, code.co_name,
           code.co_code)
    tree = _ast_cache.get(key)
    if tree is None:
        try:
            src = textwrap.dedent(inspect.getsource(fn))
            tree = ast.parse(src)
        except (OSError, TypeError, SyntaxError, IndentationError,
                ValueError):
            tree = False
        _ast_cache[key] = tree
        if len(_ast_cache) > 4096:
            _ast_cache.clear()
    if tree is False:
        return report
    node = _match_fn_node(tree, code)
    if node is not None:
        v = _ClosureVisitor(_fn_params(node))
        for stmt in (node.body if isinstance(node.body, list)
                     else [node.body]):
            v.visit(stmt)
        _emit(report, v, site, tpu=tpu, deterministic=deterministic)
    return report


def _match_fn_node(tree, code):
    """The FunctionDef/Lambda in `tree` that corresponds to `code`:
    when several lambdas share one source line (so getsource returned
    them all), prefer the one whose parameter names match the code
    object — best-effort, first candidate otherwise."""
    argnames = list(code.co_varnames[:code.co_argcount])
    first = None
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.Lambda)):
            continue
        if first is None:
            first = node
        if [a.arg for a in node.args.args] == argnames:
            return node
    return first


def _describe(fn):
    code = getattr(fn, "__code__", None)
    if code is None:
        return repr(fn)
    import os
    return "%s:%d %s" % (os.path.basename(code.co_filename),
                         code.co_firstlineno,
                         getattr(fn, "__qualname__", code.co_name))


def iter_plan_functions(rdd, lineage=None):
    """(fn, site) for every user callable reachable from the lineage of
    `rdd` — narrow transform functions and aggregator triples.
    `lineage` lets the pre-flight gate pass its (possibly capped) walk
    instead of re-walking."""
    from dpark_tpu.analysis.plan_rules import iter_lineage
    from dpark_tpu import rdd as _rdd
    skip = {_rdd._identity, _rdd._mk_list, _rdd._append, _rdd._extend,
            _rdd._fst, _rdd._snd, _rdd._add, _rdd._keep_first,
            _rdd._radd_zero, _rdd._one, _rdd._count_merge,
            _rdd._mean_create, _rdd._mean_merge_value, _rdd._mean_merge,
            _rdd._mean_final, _rdd._pair_none, _rdd._pair_one,
            _rdd._pair_self}
    for r in (lineage if lineage is not None else iter_lineage(rdd)):
        fn = getattr(r, "f", None)
        if callable(fn) and fn not in skip:
            yield fn, r.scope_name
        agg = getattr(r, "aggregator", None)
        if agg is not None:
            for part in (agg.create_combiner, agg.merge_value,
                         agg.merge_combiners):
                if callable(part) and part not in skip \
                        and getattr(part, "__module__", "").split(".")[0] \
                        not in ("operator", "builtins", "_operator"):
                    yield part, r.scope_name


# ---------------------------------------------------------------------------
# source-file mode (dlint CLI / CI self-lint)
# ---------------------------------------------------------------------------

class _ModuleScope(ast.NodeVisitor):
    """Track module/function-scope names bound to DparkContexts and to
    RDD chains, then lint every closure passed to a transform call."""

    def __init__(self, path, report, tpu=False):
        self.path = path
        self.report = report
        self.tpu = tpu
        self.ctx_names = set()
        self.rdd_names = set()
        self.defs = {}              # name -> FunctionDef (module level)
        self.collect_only = True    # pass 1 gathers names, pass 2 lints

    # -- assignment tracking --------------------------------------------
    def visit_Assign(self, node):
        value = node.value
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if targets:
            if self._is_ctx_expr(value):
                self.ctx_names.update(targets)
            elif self._is_rdd_expr(value):
                self.rdd_names.update(targets)
        self.generic_visit(node)

    def _is_ctx_expr(self, expr):
        if isinstance(expr, ast.Call):
            dotted = _dotted(expr.func) or ""
            if dotted.split(".")[-1] in ("DparkContext",):
                return True
        return False

    def _is_rdd_expr(self, expr):
        """ctx.<factory>(...) or <rdd>.<transform>(...) chains."""
        while isinstance(expr, ast.Call):
            fn = expr.func
            if not isinstance(fn, ast.Attribute):
                return False
            if isinstance(fn.value, ast.Name):
                base = fn.value.id
                if base in self.ctx_names \
                        and fn.attr in CONTEXT_FACTORIES:
                    return True
                if base in self.rdd_names:
                    return True
                return False
            expr = fn.value         # deeper chain: a.b(...).c(...)
        return False

    def visit_FunctionDef(self, node):
        self.defs[node.name] = node
        self.generic_visit(node)

    # -- transform calls -------------------------------------------------
    def visit_Call(self, node):
        fn = node.func
        if not self.collect_only and isinstance(fn, ast.Attribute) \
                and fn.attr in TRANSFORM_METHODS:
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                self._lint_closure_arg(arg, fn.attr)
        self.generic_visit(node)

    def _lint_closure_arg(self, arg, method):
        node = None
        name = None
        if isinstance(arg, ast.Lambda):
            node = arg
            name = "lambda"
        elif isinstance(arg, ast.Name) and arg.id in self.defs:
            node = self.defs[arg.id]
            name = arg.id
        if node is None:
            return
        params = set(_fn_params(node))
        # default-arg rebinding (lambda x, r=rdd: ...) still captures:
        # the default VALUE is the rdd — flag those too
        default_rdds = []
        for d, a in zip(reversed(node.args.defaults),
                        reversed(node.args.args)):
            if isinstance(d, ast.Name) and (d.id in self.rdd_names
                                            or d.id in self.ctx_names):
                default_rdds.append((node.lineno, d.id))
        v = _ClosureVisitor(params, self.rdd_names, self.ctx_names)
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            v.visit(stmt)
        v.captured.extend(default_rdds)
        site = "%s %s" % (self.path, name)      # _emit appends :lineno
        _emit(self.report, v, site, tpu=self.tpu)


def lint_source(path, report=None, text=None, tpu=False):
    """Lint one Python source file; returns the Report."""
    report = report if report is not None else Report()
    if text is None:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        report.add("syntax-error", "error", "%s:%s" % (path, e.lineno),
                   "file does not parse: %s" % e.msg)
        return report
    scope = _ModuleScope(path, report, tpu=tpu)
    # two passes: assignments/defs first so forward uses of an rdd name
    # inside main() still resolve, then the transform-call lint
    scope.visit(tree)
    scope.collect_only = False
    scope.visit(tree)
    return report
