"""Plan linter: a static pass over the RDD lineage DAG, run pre-flight
by DparkContext.runJob (and importable standalone via lint_plan).

Rules catch the two failure families the round-5 audit surfaced —
silent-wrong-answer shapes decided at plan-construction time, and
shuffle anti-patterns that dominate cost at production scale:

  plan-group-agg         groupByKey().mapValue(provable aggregate) that
                         the graph-build rewrite did NOT absorb: every
                         row ships to its group instead of a map-side
                         combine.
  plan-uncached-reshuffle one lineage shuffled 2+ times without
                         cache()/checkpoint(): the parent recomputes
                         once per shuffle.
  plan-wide-depth        more than conf.LINT_WIDE_DEPTH shuffle edges on
                         one lineage path with no checkpoint: a lost
                         partition replays the whole chain.
  unbounded-recovery     the same uncheckpointed depth while fault
                         injection (DPARK_FAULTS) is active: every
                         injected failure replays the whole chain —
                         chaos runs need a recovery pin.  Quiet when
                         an erasure code with parity is active
                         (DPARK_SHUFFLE_CODE, m >= 1): coded fetches
                         decode instead of replaying lineage.
  plan-join-repartition  a cogroup/join whose inputs already share a
                         partitioner, re-exchanged because the join was
                         given a different partition count.
  host-fallback-group    a groupByKey().mapValues(f) consumer that will
                         leave the array path, and why (SEG_MAP off,
                         unsupported value pytree, untraceable or
                         padding-sensitive per-group function) — the
                         pre-flight twin of the runtime fallback_reason
                         the tpu scheduler records per stage.
  monoid-multileaf       reduceByKey/combineByKey with a classified
                         min/max merge over values whose pytree has >1
                         leaf or a non-scalar leaf — the exact round-5
                         silent-wrong-answer shape on the device monoid
                         path (the host compares whole records
                         lexicographically, a per-leaf device reduction
                         mixes leaves from different records; add/mul
                         over sequences are legitimate concat/repeat
                         and stay unflagged).
  adapt-stale-hint       the adaptive store's learned wave budgets
                         (dpark_tpu/adapt.py) are keyed by row-width
                         class and NONE matches this plan's columnar
                         source: schema drift left the store's hints
                         stale, so the first run re-walks the OOM
                         ladder instead of seeding.
  static-code-hint       the pinned DPARK_SHUFFLE_CODE contradicts
                         the adapt store's recorded per-peer fetch
                         tails: parity everywhere while every peer is
                         tight (wasted tax), or no parity while a
                         recorded peer straggles (lineage replay on
                         every slow fetch).  Quiet when
                         DPARK_CODE_ADAPT re-prices per exchange.
  trace-overhead-hint    DPARK_TRACE=spool with a reduce side whose
                         estimated spool writes per task (one fetch
                         span per parent map bucket) exceed
                         conf.TRACE_SPAN_WRITES_PER_TASK — on
                         tiny-task jobs the span spooling can rival
                         the work being traced; coalesce, raise the
                         threshold, or trace with ring mode.

The walk reads graph structure only (dependencies / partitioner /
cache flags) — it never touches RDD.splits (which can promote lazy
checkpoints) and never runs jobs.  Record probing for monoid-multileaf
reads only data already resident on the driver (parallelize slices);
user functions are never executed unless conf.LINT_PROBE == "deep".
"""

from dpark_tpu.analysis.report import Report


# ---------------------------------------------------------------------------
# lineage traversal
# ---------------------------------------------------------------------------

def iter_lineage(rdd):
    """Yield every RDD reachable from `rdd` (itself included) exactly
    once, parents after children discovery order — purely structural,
    no splits access."""
    seen = set()
    frontier = [rdd]
    while frontier:
        r = frontier.pop()
        if id(r) in seen:
            continue
        seen.add(id(r))
        yield r
        for dep in getattr(r, "dependencies", ()):
            parent = getattr(dep, "rdd", None)
            if parent is not None:
                frontier.append(parent)


def _is_pinned(r):
    """cache/checkpoint/snapshot pins: this RDD's lineage does not
    recompute on re-use (for lint purposes)."""
    return (getattr(r, "should_cache", False)
            or getattr(r, "_checkpoint_path", None) is not None
            or getattr(r, "_checkpoint_rdd", None) is not None
            or getattr(r, "_snapshot_path", None) is not None)


# ---------------------------------------------------------------------------
# merge classification (jax-free fallback)
# ---------------------------------------------------------------------------

def _ensure_backend_identities():
    """Register the tpu backend's jnp by-identity callables in the
    shared classifier — but ONLY when jax is already loaded: a
    pure-local job must not pay a jax import (review finding; the
    registrations only matter if the user passed a jnp callable, which
    implies jax is in sys.modules already)."""
    import sys
    if "jax" in sys.modules:
        try:
            import dpark_tpu.backend.tpu.fuse      # noqa: F401
        except ImportError:
            pass


def _classify_merge(fn):
    """The SHARED exact classifier (utils/monoid.py — the same core
    fuse.classify_merge delegates to, so linter and executor can never
    drift)."""
    _ensure_backend_identities()
    from dpark_tpu.utils.monoid import classify_merge
    return classify_merge(fn)


def _classify_segagg(fn):
    _ensure_backend_identities()
    from dpark_tpu.utils.monoid import classify_segagg
    return classify_segagg(fn)


# ---------------------------------------------------------------------------
# value-shape probing (monoid-multileaf)
# ---------------------------------------------------------------------------

def _value_leaves(v):
    """Flatten a record value the way the device path would: tuples,
    lists, and dict values are structure; everything else is one leaf."""
    if isinstance(v, (tuple, list)):
        out = []
        for item in v:
            out.extend(_value_leaves(item))
        return out
    if isinstance(v, dict):
        out = []
        for k in sorted(v, key=repr):
            out.extend(_value_leaves(v[k]))
        return out
    return [v]


def _leaf_is_scalar(leaf):
    shape = getattr(leaf, "shape", None)
    if shape:                       # ndarray with ndim > 0
        return False
    return True


def _peek_source_records(rdd, k=4, _depth=0):
    """Up to k records WITHOUT running a job: reads data already
    resident on the driver (parallelize slices), looks through unions,
    and — only under conf.LINT_PROBE == "deep" — replays narrow
    per-record functions over the probe rows (user functions may have
    side effects, e.g. accumulators, so execution is opt-in).  Returns
    a list of records, possibly empty, or None when the source is not
    cheaply probeable."""
    from dpark_tpu import conf, rdd as _rdd
    if _depth > 16:
        return None
    if isinstance(rdd, _rdd.ParallelCollection):
        slices = getattr(rdd, "_slices", None)
        if slices is None:          # worker-side copy: data stripped
            return None
        out = []
        for s in slices:
            try:
                for i in range(min(k - len(out), len(s))):
                    out.append(s[i])
            except Exception:
                return None
            if len(out) >= k:
                break
        return out
    if isinstance(rdd, _rdd.UnionRDD):
        for parent in getattr(rdd, "rdds", ()):
            rows = _peek_source_records(parent, k, _depth + 1)
            if rows:
                return rows
        return None
    if getattr(conf, "LINT_PROBE", "shallow") != "deep":
        return None
    per_record = {
        _rdd.MappedRDD: lambda f, rows: [f(r) for r in rows],
        _rdd.FilteredRDD: lambda f, rows: [r for r in rows if f(r)],
        _rdd.FlatMappedRDD: lambda f, rows: [o for r in rows
                                             for o in f(r)],
        _rdd.MappedValuesRDD: lambda f, rows: [(r[0], f(r[1]))
                                               for r in rows],
        _rdd.KeyedRDD: lambda f, rows: [(f(r), r) for r in rows],
    }
    fn = per_record.get(type(rdd))
    if fn is None:
        return None
    parent_rows = _peek_source_records(rdd.prev, k, _depth + 1)
    if not parent_rows:
        return parent_rows
    try:
        return fn(rdd.f, parent_rows)[:k]
    except Exception:
        return None


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def _rule_group_agg(r, report):
    """MappedValuesRDD over a bare groupByKey whose mapValue function is
    a PROVABLE aggregate: the graph rewrite did not absorb it (cache
    pin, np twins, reused outputs, or conf off), so every row rides the
    exchange."""
    from dpark_tpu import conf, rdd as _rdd
    if not getattr(conf, "GROUP_AGG_REWRITE", True):
        return                      # user opted out deliberately
    if not isinstance(r, _rdd.MappedValuesRDD):
        return
    prev = r.prev
    if not isinstance(prev, _rdd.ShuffledRDD):
        return
    agg = prev.aggregator
    if not (agg.create_combiner is _rdd._mk_list
            and agg.merge_value is _rdd._append
            and agg.merge_combiners is _rdd._extend):
        return
    try:
        from dpark_tpu.env import env
        if env.map_output_tracker.get_outputs(
                prev.dep.shuffle_id) is not None:
            return          # rewrite declined to REUSE existing outputs
    except Exception:
        pass
    f = getattr(r, "f", None)
    provable = f is not None and _classify_segagg(f) is not None
    np_twin = False
    if not provable:
        try:
            import numpy as np
            np_twin = f in (np.sum, np.mean, np.min, np.max)
        except Exception:
            np_twin = False
    if not provable and not np_twin:
        return                      # f may be a real list transform
    report.add(
        "plan-group-agg", "warn", r.scope_name,
        "groupByKey().mapValue(<aggregate>) ships every row to its "
        "group; the combiner rewrite did not absorb this chain",
        "use reduceByKey/combineByKey (or drop the cache pin on the "
        "grouped RDD); np.sum/np.mean twins need the builtin forms")


def _rule_uncached_reshuffle(lineage, report):
    """The same parent RDD feeding 2+ distinct shuffles without a
    cache/checkpoint pin: its lineage recomputes once per shuffle."""
    shuffled_by = {}                # id(parent) -> (parent, {shuffle_id})
    for r in lineage:
        for dep in getattr(r, "dependencies", ()):
            if getattr(dep, "is_shuffle", False):
                parent = dep.rdd
                ent = shuffled_by.setdefault(id(parent), (parent, set()))
                ent[1].add(dep.shuffle_id)
    for parent, sids in shuffled_by.values():
        if len(sids) < 2 or _is_pinned(parent):
            continue
        report.add(
            "plan-uncached-reshuffle", "warn", parent.scope_name,
            "this lineage feeds %d separate shuffles and is not "
            "cached: it recomputes for each one" % len(sids),
            "cache() (or checkpoint()) the RDD before fanning out")


def _shuffle_depth(r, memo):
    """Max number of shuffle edges on any path below `r`; a pinned RDD
    resets the count (its lineage won't replay)."""
    key = id(r)
    if key in memo:
        return memo[key]
    memo[key] = 0                   # cycle guard (graphs are acyclic)
    if _is_pinned(r):
        return 0
    best = 0
    for dep in getattr(r, "dependencies", ()):
        d = _shuffle_depth(dep.rdd, memo) \
            + (1 if getattr(dep, "is_shuffle", False) else 0)
        best = max(best, d)
    memo[key] = best
    return best


def _excess_wide_depth(rdd):
    """(depth, limit) when the plan chains more shuffles than
    conf.LINT_WIDE_DEPTH with no checkpoint pin, else None — shared by
    plan-wide-depth and its chaos twin unbounded-recovery."""
    from dpark_tpu import conf
    limit = int(getattr(conf, "LINT_WIDE_DEPTH", 4))
    if limit <= 0:
        return None
    depth = _shuffle_depth(rdd, {})
    return (depth, limit) if depth > limit else None


def _rule_wide_depth(rdd, report, excess):
    if excess is None:
        return
    depth, limit = excess
    report.add(
        "plan-wide-depth", "warn", rdd.scope_name,
        "%d chained shuffles with no checkpoint on the path "
        "(limit %d): a lost partition replays the whole chain"
        % (depth, limit),
        "checkpoint() (or cache()) an intermediate RDD; raise "
        "conf.LINT_WIDE_DEPTH if the depth is intentional")


def _rule_unbounded_recovery(rdd, report, excess):
    """Fault injection is ACTIVE (DPARK_FAULTS) and this plan chains
    more shuffles than conf.LINT_WIDE_DEPTH with no checkpoint pin:
    every injected failure past the last pin replays the whole chain —
    a chaos run against such a plan measures recompute amplification,
    not recovery (ISSUE 5 satellite; the chaos twin of
    plan-wide-depth)."""
    from dpark_tpu import coding, faults
    if excess is None or not faults.active():
        return
    # coded shuffle quiets the rule (ISSUE 6 satellite): with m >= 1
    # parity shards on every bucket/spill payload, a failed or
    # straggling fetch is DECODED from survivors instead of replayed
    # through lineage — the chain no longer needs a checkpoint pin to
    # bound recovery under injection
    code = coding.active_code()
    if code is not None and code.m >= 1:
        return
    # adaptive per-exchange codes quiet it too (ISSUE 19): the policy
    # can escalate any exchange whose peers demonstrably straggle to
    # m >= 1 parity mid-fleet, so recovery under injection is bounded
    # by decode even with the static code off
    if coding.adaptive_enabled():
        return
    depth, limit = excess
    report.add(
        "unbounded-recovery", "warn", rdd.scope_name,
        "fault injection is active (DPARK_FAULTS) and this plan "
        "chains %d shuffles with no checkpoint (limit %d): each "
        "injected failure replays the whole uncheckpointed chain"
        % (depth, limit),
        "checkpoint() an intermediate RDD before running under "
        "chaos, or raise conf.LINT_WIDE_DEPTH deliberately")


def _rule_join_repartition(r, report):
    """A cogroup whose inputs ALL share one partitioner, forced through
    a full re-exchange because the cogroup was created with a different
    partitioner (usually an implicit numSplits default)."""
    from dpark_tpu import rdd as _rdd
    if not isinstance(r, _rdd.CoGroupedRDD):
        return
    inputs = getattr(r, "rdds", ())
    if len(inputs) < 2:
        return
    parts = [p.partitioner for p in inputs]
    if any(p is None for p in parts):
        return
    first = parts[0]
    if not all(p == first for p in parts[1:]):
        return
    if first == r.partitioner:
        return                      # narrow already — nothing to flag
    report.add(
        "plan-join-repartition", "warn", r.scope_name,
        "join/cogroup inputs already agree on a partitioner "
        "(%d parts) but the join repartitions to %d: both sides "
        "re-exchange for nothing"
        % (first.num_partitions, r.partitioner.num_partitions),
        "pass numSplits=%d (or the shared partitioner) to the join"
        % first.num_partitions)


def _rule_monoid_multileaf(r, report):
    """Combining shuffle with a classified monoid merge over multi-leaf
    or non-scalar values: the round-5 wrong-answer shape.  The host
    merges whole records (tuples compare lexicographically) while the
    device monoid path reduces each leaf independently — results mix
    leaves from different records.  The executor now refuses the
    device monoid for this shape (falling back to the raw-combiner
    exchange), so severity=error here is the pre-flight twin that
    refuses the plan outright under DPARK_LINT=error."""
    from dpark_tpu import rdd as _rdd
    if not isinstance(r, _rdd.ShuffledRDD):
        return
    agg = r.aggregator
    if (agg.create_combiner is _rdd._mk_list
            and agg.merge_value is _rdd._append
            and agg.merge_combiners is _rdd._extend):
        return                      # no-combine shuffle: no monoid path
    kind = _classify_merge(agg.merge_combiners)
    if kind not in ("min", "max"):
        # add/mul over sequences are legitimate HOST semantics (tuple
        # concat/repeat) that every master now agrees on; only ordered
        # comparisons have the lexicographic-vs-per-leaf ambiguity
        return
    rows = _peek_source_records(r.parent)
    if not rows:
        return                      # not cheaply probeable: stay quiet
    bad = None
    for row in rows:
        if not (isinstance(row, tuple) and len(row) == 2):
            continue
        leaves = _value_leaves(row[1])
        if len(leaves) > 1:
            bad = "%d value leaves" % len(leaves)
            break
        if leaves and not _leaf_is_scalar(leaves[0]):
            bad = "a non-scalar value leaf (shape %s)" \
                % (getattr(leaves[0], "shape", None),)
            break
    if bad is None:
        return
    report.add(
        "monoid-multileaf", "error", r.scope_name,
        "reduceByKey/combineByKey merge classifies as monoid %r but "
        "records carry %s: per-leaf device reduction would mix leaves "
        "from different records (host %s merges whole records)"
        % (kind, bad, kind),
        "merge per-field explicitly (e.g. lambda a, b: (min(a[0], "
        "b[0]), ...) is NOT the same as %s(a, b)) or keep a single "
        "scalar value per record" % kind)


def _key_fallback_reason(key, hash_keys=True):
    """Why this record KEY keeps a shuffle off the array path, or None
    when the key shape classifies (scalar numeric, or a flat numeric
    tuple of 2..conf.MAX_KEY_LEAVES leaves — the composite keys the
    device path now carries end to end).  Mirrors layout.key_width /
    fuse's epilogue checks without importing jax: `hash_keys` is True
    for hash-partitioned shuffles, whose device routing additionally
    needs INT leaves (portable_hash has no device twin for floats);
    range repartitioning (sortByKey) accepts floats."""
    from dpark_tpu import conf
    ints = (int,)
    floats = (float,)
    try:
        import numpy as _np
        ints = (int, _np.integer)
        floats = (float, _np.floating)
    except ImportError:
        pass

    def leaf_reason(item):
        if isinstance(item, bool):
            return "bool key (no device hash semantics)"
        if isinstance(item, ints):
            return None
        if isinstance(item, floats):
            return ("float key on a hash shuffle (device routing "
                    "needs int keys; floats ride range/sortByKey)"
                    if hash_keys else None)
        return "non-numeric"

    if isinstance(key, (str, bytes)):
        return ("string key: only text-source chains ride the device "
                "(dictionary-encoded); everything else takes the "
                "object path")
    if isinstance(key, tuple):
        if not getattr(conf, "TUPLE_KEYS", True):
            return "tuple key with conf.TUPLE_KEYS disabled"
        if len(key) < 2 or len(key) > conf.MAX_KEY_LEAVES:
            return ("tuple key with %d leaves (device path carries "
                    "flat tuples of 2..conf.MAX_KEY_LEAVES=%d)"
                    % (len(key), conf.MAX_KEY_LEAVES))
        for i, item in enumerate(key):
            r = leaf_reason(item)
            if r == "non-numeric":
                if isinstance(item, tuple):
                    return ("nested tuple key (only FLAT numeric "
                            "tuples ride the device)")
                return ("non-numeric key leaf %d (%s) in a tuple key"
                        % (i, type(item).__name__))
            if r is not None:
                return r
        return None
    r = leaf_reason(key)
    if r == "non-numeric":
        return ("unsupported key type %s (object path)"
                % type(key).__name__)
    return r


def _rule_host_fallback_key(r, report):
    """Shuffles whose KEY SHAPE evicts the plan from the array path:
    the pre-flight twin of fuse.analyze_stage's key checks, reporting
    WHY (unsupported key shape, non-numeric leaf) instead of silently
    running orders of magnitude slower on the object path.  Flat
    numeric tuple keys now ride the device and stay unflagged."""
    from dpark_tpu import rdd as _rdd
    from dpark_tpu.dependency import HashPartitioner
    if not isinstance(r, _rdd.ShuffledRDD):
        return
    rows = _peek_source_records(r.parent)
    if not rows:
        return                      # not cheaply probeable: stay quiet
    hash_keys = isinstance(r.partitioner, HashPartitioner)
    for row in rows:
        if not (isinstance(row, tuple) and len(row) == 2):
            continue
        reason = _key_fallback_reason(row[0], hash_keys=hash_keys)
        if reason is None:
            continue
        severity = "info" if isinstance(row[0], (str, bytes)) \
            else "warn"
        report.add(
            "host-fallback-key", severity, r.scope_name,
            "this shuffle leaves the array path: %s" % reason,
            "key by ints/floats or a flat numeric tuple ((k1, k2), v) "
            "to stay on the device; see the README device-path "
            "support matrix")
        return


def _rule_host_fallback_group(r, report):
    """Grouped-value consumers — ``groupByKey().mapValues(f)`` — that
    will leave the array path, and WHY: the pre-flight twin of
    fuse._try_seg_map's admission pipeline (the device segmented
    apply).  Quiet when the chain rides: provable aggregates go
    through SegAggOp/the combiner rewrite, traceable padding-invariant
    functions through SegMapOp.  Reported reasons mirror the runtime
    ``fallback_reason`` exactly: SEG_MAP disabled, unsupported value
    pytree, data-dependent control flow (AST, no execution), and —
    only under conf.LINT_PROBE == "deep", because the check EXECUTES
    the user function on synthetic samples — the exact runtime
    classifier's non-traceable / not-padding-invariant verdicts."""
    import numbers
    from dpark_tpu import conf, rdd as _rdd
    if not isinstance(r, _rdd.MappedValuesRDD):
        return
    prev = r.prev
    if not isinstance(prev, _rdd.ShuffledRDD):
        return
    agg = prev.aggregator
    if not (agg.create_combiner is _rdd._mk_list
            and agg.merge_value is _rdd._append
            and agg.merge_combiners is _rdd._extend):
        return
    f = getattr(r, "f", None)
    if f is None:
        return
    state_update = getattr(f, "__dpark_seg_state__", None)
    f_check = state_update if state_update is not None else f
    if state_update is None and _classify_segagg(f) is not None:
        return          # provable aggregate: rides (plan-group-agg
        #                 separately flags the missed rewrite)
    reason = None
    if not getattr(conf, "SEG_MAP", True):
        reason = ("grouped consumer stays on host: DPARK_SEG_MAP=0")
    rows = None
    if reason is None:
        rows = _peek_source_records(prev.parent)
        for row in rows or ():
            if not (isinstance(row, tuple) and len(row) == 2):
                continue
            leaves = _value_leaves(row[1])
            if len(leaves) != 1 or not _leaf_is_scalar(leaves[0]) \
                    or isinstance(leaves[0], bool) \
                    or not isinstance(leaves[0], numbers.Number):
                reason = ("unsupported value pytree for grouped "
                          "consumption (seg_map needs a single scalar "
                          "numeric value per record)")
                break
    if reason is None:
        # no-execution check: Python control flow on the group data
        # cannot trace — the same verdict the runtime's eval_shape
        # probe reaches, decided from the AST alone
        try:
            from dpark_tpu.analysis.closure_rules import lint_function
            sub = lint_function(f_check, tpu=True)
            if any(fd.rule == "closure-tracer-branch" for fd in sub):
                reason = ("per-group function is not traceable "
                          "(data-dependent Python control flow)")
        except Exception:
            pass
    if reason is None and rows \
            and getattr(conf, "LINT_PROBE", "shallow") == "deep":
        import sys
        if "jax" in sys.modules:
            try:
                import numpy as _np
                from dpark_tpu.backend.tpu import fuse as _fuse
                vdt = _np.asarray(rows[0][1]).dtype
                vdt = _np.dtype(_np.int64) if vdt.kind in "iu" \
                    else _np.dtype(_np.float32)
                pad, why, _ = _fuse.classify_seg_map(
                    f_check, vdt, state=state_update is not None)
                if pad is None:
                    reason = why
            except Exception:
                pass
    if reason is None:
        return
    report.add(
        "host-fallback-group", "warn", r.scope_name,
        "this grouped consumer leaves the array path: %s" % reason,
        "make the per-group function traceable and padding-invariant "
        "(jnp/arithmetic ops, no data-dependent Python branching; "
        "sums zero-pad, order statistics repeat-last-pad) or use a "
        "provable aggregate / reduceByKey — see the README "
        "device-path support matrix")


def _columnar_source_row_bytes(r):
    """Bytes per record of a columnar parallelize source, jax-free
    (the linter must not pay a jax import): same arithmetic as the tpu
    backend's fuse._columnar_row_bytes, over numpy columns only.
    None for non-columnar / empty sources."""
    from dpark_tpu import rdd as _rdd
    if not isinstance(r, _rdd.ParallelCollection):
        return None
    for s in r._slices or ():
        cols = getattr(s, "columns", None)
        if cols is not None and len(s):
            import numpy as np
            return sum(np.asarray(c).dtype.itemsize
                       * int(np.prod(np.asarray(c).shape[1:] or (1,)))
                       for c in cols)
    return None


def _rule_adapt_stale_hint(r, report):
    """The adaptive-execution store (dpark_tpu/adapt.py, ISSUE 7)
    keys its learned wave budgets by row-width class; when NONE of the
    stored classes matches this plan's columnar source, the learned
    budgets silently fail to apply — the store was warmed by a
    different data shape (schema drift), and the first run of this
    shape re-derives the memory bound and re-walks the OOM ladder.
    Quiet with DPARK_ADAPT=off, with an empty store, and whenever any
    stored class matches (mixed-width workloads are legitimate)."""
    try:
        from dpark_tpu import adapt
        if not adapt.enabled():
            return
        row_bytes = _columnar_source_row_bytes(r)
        if row_bytes is None:
            return
        widths = adapt.wave_budget_row_widths()
        if not widths or row_bytes in widths:
            return
    except Exception:
        return
    report.add(
        "adapt-stale-hint", "warn", r.scope_name,
        "the adaptive store's learned wave budgets cover row widths "
        "%s bytes, but this plan's columnar source is %d bytes/row — "
        "stored budgets will not apply (stale shape class)"
        % (sorted(widths), row_bytes),
        "expected after a schema change: the first run re-learns its "
        "budget; delete the DPARK_ADAPT_DIR store (or call "
        "adapt.reset_store()) to drop stale entries"
        + ("" if adapt.steering() else
           " (note: DPARK_ADAPT=%s only records — budgets would "
           "steer under DPARK_ADAPT=on)" % adapt.mode())
        + (" (per-exchange code choices are unaffected: they key by "
           "shuffle call site, not row width — DPARK_CODE_ADAPT "
           "keeps steering across a schema change)"
           if _coding_adaptive() else ""))


def _coding_adaptive():
    try:
        from dpark_tpu import coding
        return bool(coding.adaptive_enabled())
    except Exception:
        return False


def _rule_static_code_hint(rdd, report):
    """The pinned DPARK_SHUFFLE_CODE contradicts the adapt store's
    recorded per-peer fetch tails (ISSUE 19): parity on every bucket
    while every recorded peer is tight wastes encode CPU and shuffle
    bytes; no parity while a recorded peer demonstrably straggles
    leaves recovery to lineage replay.  Quiet when the adaptive
    per-exchange policy is on (DPARK_CODE_ADAPT re-prices each
    exchange, superseding the pin), with DPARK_ADAPT off, and with no
    recorded fetch tails."""
    try:
        from dpark_tpu import adapt, coding, conf
        from dpark_tpu.health import Sketch
        if not adapt.enabled() or coding.adaptive_enabled():
            return
        ratio_bar = float(getattr(conf, "CODE_ADAPT_TAIL_RATIO", 3.0))
        min_n = int(getattr(conf, "CODE_ADAPT_MIN_SAMPLES", 8) or 1)
        worst = None                          # (ratio, peer)
        for site, digest in adapt.site_tails().items():
            site = str(site)
            if not site.startswith("fetch.bucket:"):
                continue
            sk = Sketch.from_dict(digest)
            if sk.n < min_n or sk.sum <= 0:
                continue
            p50 = sk.quantile(0.50) or 0.0
            p99 = sk.quantile(0.99) or 0.0
            ratio = (p99 / p50) if p50 > 0 else 0.0
            if worst is None or ratio > worst[0]:
                worst = (ratio, site[len("fetch.bucket:"):])
        if worst is None:
            return
        ratio, peer = worst
        code = coding.active_code()
        protected = code is not None and code.m >= 1
    except Exception:
        return
    if protected and ratio < ratio_bar:
        report.add(
            "static-code-hint", "info", rdd.scope_name,
            "DPARK_SHUFFLE_CODE=%s pays parity on every bucket, but "
            "every recorded peer fetch tail is tight (worst p99/p50 "
            "%.1f < %.1f) — the parity tax buys nothing here"
            % (coding.describe(), ratio, ratio_bar),
            "drop the static code, or set DPARK_CODE_ADAPT=1 to "
            "price parity per exchange from the recorded tails")
    elif not protected and ratio >= ratio_bar:
        report.add(
            "static-code-hint", "warn", rdd.scope_name,
            "no parity is pinned (DPARK_SHUFFLE_CODE=%s) but recorded "
            "peer %s straggles (fetch tail p99/p50 %.1f >= %.1f) — "
            "every slow or lost fetch from it replays lineage"
            % (coding.describe(), peer, ratio, ratio_bar),
            "pin a code with m >= 1, or set DPARK_CODE_ADAPT=1 to "
            "escalate only the exchanges that peer serves")


def _width_hint(r, depth=0):
    """Best-effort partition count WITHOUT touching RDD.splits (the
    property can promote lazy checkpoints, see the module header):
    already-materialized splits, parallelize slices, a shuffle
    output's own partitioner width, or a single narrow parent's hint.
    None when the width isn't structurally knowable."""
    from dpark_tpu.dependency import OneToOneDependency, \
        ShuffleDependency
    while r is not None and depth < 64:
        depth += 1
        splits = getattr(r, "_splits", None)
        if splits is not None:
            return len(splits)
        slices = getattr(r, "_slices", None)     # ParallelCollection
        if slices is not None:
            return len(slices)
        deps = getattr(r, "dependencies", ())
        if len(deps) == 1 and isinstance(deps[0], ShuffleDependency):
            part = getattr(r, "partitioner", None)
            n = getattr(part, "num_partitions", None)
            if n:
                return int(n)
        if len(deps) == 1 and isinstance(deps[0],
                                         OneToOneDependency):
            r = getattr(deps[0], "rdd", None)    # width-preserving
            continue
        return None
    return None


def _rule_trace_overhead_hint(r, report):
    """With DPARK_TRACE=spool every reduce task appends roughly one
    fetch span PER PARENT MAP BUCKET plus its own task spans to the
    spool — an O_APPEND write each.  On a tiny-task job (many map
    partitions feeding many short reduce tasks) the spool traffic can
    rival the compute the trace is meant to explain.  Warn when the
    estimated spool writes per reduce task exceed
    conf.TRACE_SPAN_WRITES_PER_TASK.  Quiet in off/ring modes (no disk
    writes at all)."""
    try:
        from dpark_tpu import conf as _conf, trace
        if trace.mode() != "spool":
            return
        from dpark_tpu.dependency import ShuffleDependency
        widest = 0
        for dep in getattr(r, "dependencies", ()):
            if isinstance(dep, ShuffleDependency):
                widest = max(widest, _width_hint(dep.rdd) or 0)
        if not widest:
            return
        est = 2 + widest          # task span + task.run + fetch/bucket
        cap = int(getattr(_conf, "TRACE_SPAN_WRITES_PER_TASK", 64))
        if est <= cap:
            return
    except Exception:
        return
    report.add(
        "trace-overhead-hint", "warn", r.scope_name,
        "DPARK_TRACE=spool will append ~%d spans per reduce task here "
        "(%d parent map buckets each fetch-spanned) — above the "
        "TRACE_SPAN_WRITES_PER_TASK=%d hint threshold, spooling can "
        "dominate tiny tasks" % (est, widest, cap),
        "coalesce the map side (fewer, larger partitions), raise "
        "DPARK_TRACE_SPAN_WRITES_PER_TASK if the tasks are long "
        "enough to amortize it, or trace with DPARK_TRACE=ring "
        "(in-memory, no spool writes)")


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def _rule_window_noninv(r, report):
    """window-noninv-no-merge (ISSUE 10 satellite): a non-invertible
    windowed reduce whose op has NO registered partial-aggregate merge
    (and no invFunc) re-reduces the whole window every slide — O(w)
    per tick where the pane tree would pay O(log w).  dstream marks
    the emitted plan (`_window_noninv`) when it falls back; this rule
    surfaces the why."""
    info = getattr(r, "_window_noninv", None)
    if not info:
        return
    report.add(
        "window-noninv-no-merge", "warn", r.scope_name,
        "non-invertible windowed reduce over %r recomputes the whole "
        "window every slide (O(w) per tick): %s"
        % (info.get("op"), info.get("reason", "")),
        hint="register a partial-aggregate merge (a classified monoid "
             "op, or set func.__dpark_window_merge__ = True to assert "
             "associativity over partials) and keep window/slide/batch "
             "grid-aligned so the pane tree serves the window in "
             "O(log w); or supply invFunc for O(1) slides")


def _rule_table_host_fallback(r, report):
    """table-host-fallback (ISSUE 13 satellite): why a table/SQL query
    operator left the array path.  The query planner
    (dpark_tpu/query/planner.py) attaches its per-operator host
    decisions — non-traceable UDA, unsupported column dtype (float
    group key, string aggregate), int-overflow risk, priced object
    path — to the host-chain lineage it falls back to
    (`_query_fallbacks`); this rule surfaces them pre-flight, the
    exact mirror of the per-stage `fallback_reason` the scheduler
    records at run time."""
    fallbacks = getattr(r, "_query_fallbacks", None)
    if not fallbacks:
        return
    for fb in fallbacks:
        report.add(
            "table-host-fallback", "info", r.scope_name,
            "query operator %r left the array path: %s"
            % (fb.get("op"), fb.get("reason")),
            "see the README Table/SQL plane section for the device "
            "query support matrix (int/encoded-string keys, "
            "sum/count/min/max/avg + traceable UDAs, equi-joins); "
            "DPARK_QUERY=0 silences planning entirely")


def _rule_repeated_subplan(lineage, report):
    """repeated-subplan (ISSUE 18 satellite): the same canonical
    sub-plan signature evaluated at two DISTINCT nodes of one plan —
    each evaluation pays the scan/exchange again even though the
    result-cache plane (or plain subtree sharing: build the common
    table once and derive both queries from it) could serve the
    second for free.  Shared OBJECTS are one evaluation and never
    flag; leaves (bare scans) don't either — reading a table twice is
    the cache's job, not a plan smell.  Nodes outside the logical
    grammar (plain RDDs, unsignable expressions) are skipped."""
    from dpark_tpu.query import logical
    seen = {}                   # signature -> node ids evaluating it
    for node in lineage:
        if not isinstance(node, logical.Node) \
                or not node.children:
            continue
        try:
            sig = logical.plan_signature(node)
        except Exception:
            continue
        seen.setdefault(sig, set()).add(id(node))
    dups = {s for s, ids in seen.items() if len(ids) > 1}

    def _contains(parent, child):
        return any(c == child or (isinstance(c, tuple)
                                  and _contains(c, child))
                   for c in parent)

    for sig in sorted(dups, key=repr):
        # report only MAXIMAL duplicated subtrees: a duplicated
        # Filter inside a duplicated GroupAgg is the same finding
        if any(other != sig and _contains(other, sig)
               for other in dups):
            continue
        ids = seen[sig]
        report.add(
            "repeated-subplan", "info", str(sig[0]).lower(),
            "the same %s sub-plan is evaluated %d times in this plan "
            "without reuse" % (sig[0], len(ids)),
            "derive both queries from one shared TableRDD (a logical "
            "subtree evaluates once per object), or turn on the "
            "shared result cache (DPARK_RESULT_CACHE=mem|disk) so "
            "repeated sub-plans serve from cached rows")


def lint_plan(rdd, master="local", report=None, lineage=None):
    """Run every plan rule over the lineage of `rdd`; returns a Report.

    `master` reserved for master-specific severity policy (the rules
    themselves are master-agnostic: the monoid shape is a device-path
    hazard but the plan may run under -m tpu later).  `lineage` lets
    the pre-flight gate pass its (possibly capped) walk instead of
    re-walking."""
    report = report if report is not None else Report()
    if lineage is None:
        lineage = list(iter_lineage(rdd))
    for r in lineage:
        _rule_group_agg(r, report)
        _rule_join_repartition(r, report)
        _rule_monoid_multileaf(r, report)
        _rule_host_fallback_key(r, report)
        _rule_host_fallback_group(r, report)
        _rule_adapt_stale_hint(r, report)
        _rule_trace_overhead_hint(r, report)
        _rule_window_noninv(r, report)
        _rule_table_host_fallback(r, report)
    _rule_uncached_reshuffle(lineage, report)
    _rule_repeated_subplan(lineage, report)
    excess = _excess_wide_depth(rdd)
    _rule_wide_depth(rdd, report, excess)
    _rule_unbounded_recovery(rdd, report, excess)
    _rule_static_code_hint(rdd, report)
    return report
