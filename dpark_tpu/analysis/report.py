"""Structured lint findings: rule id, severity, lineage/source site,
message, fix hint — and the severity policy that turns findings into
log lines (DPARK_LINT=warn) or a refused plan (DPARK_LINT=error).

Every rule in plan_rules/closure_rules emits Finding objects through a
Report; nothing in this module knows about RDDs or ASTs, so the CLI,
the pre-flight gate, and tests all consume the same shape.
"""

import os
import sys

SEVERITIES = ("info", "warn", "error")


def lint_mode():
    """The effective DPARK_LINT mode: off | warn | error.

    The env var wins over the conf constant so a single run can be
    escalated (DPARK_LINT=error python job.py) without editing conf.
    Unknown values degrade to "warn" — a typo must not silently turn
    the linter off."""
    from dpark_tpu import conf
    mode = os.environ.get("DPARK_LINT", getattr(conf, "DPARK_LINT", "warn"))
    mode = str(mode).strip().lower()
    if mode in ("off", "0", "none", "disable", "disabled"):
        return "off"
    if mode in ("error", "strict", "fail"):
        return "error"
    return "warn"


class Finding:
    """One lint finding.

    rule     -- stable kebab-case id ("monoid-multileaf", ...)
    severity -- "info" | "warn" | "error"
    site     -- where: an RDD scope name ("MappedRDD@file.py:12") or a
                source location ("examples/pi.py:9 inside()")
    message  -- one-line statement of the defect
    hint     -- how to fix it (may be empty)
    """

    __slots__ = ("rule", "severity", "site", "message", "hint")

    def __init__(self, rule, severity, site, message, hint=""):
        assert severity in SEVERITIES, severity
        self.rule = rule
        self.severity = severity
        self.site = site
        self.message = message
        self.hint = hint

    @property
    def key(self):
        """Dedup identity within a process/run.  (The CLI's baseline
        uses its own coarser key with line numbers stripped.)"""
        return (self.rule, self.site)

    def as_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "site": self.site, "message": self.message,
                "hint": self.hint}

    def render(self):
        out = "%s %s [%s] %s" % (self.severity.upper(), self.site,
                                 self.rule, self.message)
        if self.hint:
            out += "\n    hint: %s" % self.hint
        return out

    def __repr__(self):
        return "<Finding %s %s %s>" % (self.severity, self.rule, self.site)


class Report:
    """An ordered, deduplicated collection of findings."""

    def __init__(self):
        self.findings = []
        self._seen = set()

    def add(self, rule, severity, site, message, hint=""):
        f = Finding(rule, severity, site, message, hint)
        if f.key in self._seen:
            return None
        self._seen.add(f.key)
        self.findings.append(f)
        return f

    def extend(self, other):
        for f in other.findings:
            if f.key not in self._seen:
                self._seen.add(f.key)
                self.findings.append(f)

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def __bool__(self):
        return bool(self.findings)

    def errors(self):
        return [f for f in self.findings if f.severity == "error"]

    def worst(self):
        worst = None
        for f in self.findings:
            if worst is None or (SEVERITIES.index(f.severity)
                                 > SEVERITIES.index(worst)):
                worst = f.severity
        return worst

    def render(self, stream=None, min_severity="info"):
        stream = stream or sys.stderr
        floor = SEVERITIES.index(min_severity)
        n = 0
        for f in self.findings:
            if SEVERITIES.index(f.severity) < floor:
                continue
            print(f.render(), file=stream)
            n += 1
        return n

    def as_dicts(self):
        return [f.as_dict() for f in self.findings]


class PlanLintError(Exception):
    """Raised by the pre-flight gate under DPARK_LINT=error: the plan
    holds at least one error-severity finding and is refused before any
    task launches.  .report carries the full Report."""

    def __init__(self, report):
        self.report = report
        lines = [f.render() for f in report.errors()] or \
                [f.render() for f in report]
        super().__init__(
            "plan refused by DPARK_LINT=error (%d finding%s):\n%s"
            % (len(lines), "s" if len(lines) != 1 else "",
               "\n".join(lines)))
