"""Static concurrency sanitizer rules (ISSUE 16) — the static half of
the lockcheck plane (the runtime half is ``dpark_tpu.locks``).

One pass over the package AST inventories every lock definition and
acquisition site, builds the static lock-order graph (lexical ``with``
nesting plus a transitive closure over same-module calls), and reports:

  lock-order-cycle     two code paths acquire the same pair of locks
                       in opposite orders — the PR 3 / PR 9 deadlock
                       shape, flagged without running anything.
  blocking-under-lock  a call that can block indefinitely (socket
                       recv/connect, ``open``, zero-arg ``queue.get``/
                       ``Condition.wait``, subprocess waits,
                       ``time.sleep``) is reachable while holding the
                       MESH lock — every tenant's device work queues
                       behind it.
  unbounded-wait       ``.get()`` / ``.wait()`` / ``.join()`` with no
                       timeout anywhere in the package: a lost peer or
                       dead worker thread parks the caller forever
                       instead of surfacing a recoverable failure.
  thread-leak          a non-daemon ``threading.Thread`` with no
                       visible ``join`` path — interpreter exit hangs
                       on it.
  plane-contract       each observability plane's documented off-mode
                       seam (one attribute load + ``is None`` check on
                       the hot path, no allocation) is verified by
                       shape, not by review — the machine check behind
                       the ``<=1.03x overhead when off`` bar.

Lock identity is canonical: a lock minted by ``locks.named_lock("x")``
is node ``x`` (matching the DYNAMIC sanitizer's graph), a raw
``threading.Lock()`` bound to an attribute is ``<module>.<Class>.<attr>``,
and ``_MeshLock()`` is ``executor.mesh``.  Aliases
(``self._export_lock = self._mesh_lock``) resolve to their target.
"""

import ast
import os
import re

from dpark_tpu.analysis.report import Report

MESH_LOCKS = frozenset(["executor.mesh"])

# blocking-call classifier: dotted-tail -> human name.  Zero-arg .get/
# .wait/.join are classified separately (arg shape disambiguates them
# from dict.get / str.join).
_SOCKET_METHODS = {"recv", "recvfrom", "recv_into", "recvmsg",
                   "accept", "connect", "sendall"}
_SUBPROCESS_FNS = {"check_call", "check_output", "communicate"}

_LOCKISH = re.compile(r"lock", re.I)


class _FnInfo:
    __slots__ = ("qual", "acquires", "edges", "calls", "blocking")

    def __init__(self, qual):
        self.qual = qual
        self.acquires = []      # (lockname, lineno)
        self.edges = []         # (held, acquired, lineno) lexical
        self.calls = []         # (callee_qual, lineno, held tuple)
        self.blocking = []      # (kind, lineno, held tuple)


class _ModuleInfo:
    __slots__ = ("path", "rel", "mod", "lockdefs", "fns", "funcs",
                 "daemonized", "joined", "thread_sites")

    def __init__(self, path, rel, mod):
        self.path = path
        self.rel = rel
        self.mod = mod
        self.lockdefs = {}      # (class or "", attr) -> canonical name
        self.fns = {}           # qual ("mod.Class.meth") -> _FnInfo
        self.funcs = set()      # defined function quals
        self.daemonized = set() # names with .daemon = True / setDaemon
        self.joined = set()     # names with a .join( call
        self.thread_sites = [] # (lineno, target name or None, has_daemon)


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_factory(call):
    """Canonical suffix for a lock-minting call, or None.
    Returns ("raw", None) for threading.Lock/RLock, ("named", name)
    for locks.named_lock("name"), ("mesh", None) for _MeshLock()."""
    if not isinstance(call, ast.Call):
        return None
    dotted = _dotted(call.func) or ""
    tail = dotted.split(".")[-1]
    if tail in ("Lock", "RLock") and (
            dotted.startswith("threading.") or dotted in ("Lock",
                                                          "RLock")):
        return ("raw", None)
    if tail == "named_lock":
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return ("named", call.args[0].value)
        return ("named", None)
    if tail == "_MeshLock":
        return ("mesh", None)
    return None


class _DefCollector(ast.NodeVisitor):
    """Pass 1: lock definitions (module and class scope, including
    aliases), thread daemon/join evidence, function inventory."""

    def __init__(self, mi):
        self.mi = mi
        self._class = ""
        self._fn_depth = 0
        self._raw = []          # (scope, attr, value-expr) for aliases

    def visit_ClassDef(self, node):
        saved, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = saved

    def visit_FunctionDef(self, node):
        qual = self._qual(node.name)
        self.mi.funcs.add(qual)
        self._fn_depth += 1
        self.generic_visit(node)
        self._fn_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def _qual(self, name):
        return ("%s.%s.%s" % (self.mi.mod, self._class, name)
                if self._class else "%s.%s" % (self.mi.mod, name))

    def visit_Assign(self, node):
        for t in node.targets:
            scope = attr = None
            if isinstance(t, ast.Name):
                if not self._fn_depth:
                    # module scope, or a class-body attribute (reached
                    # as self.<name> from methods)
                    scope, attr = self._class, t.id
                else:
                    attr = t.id     # function-local: threads only
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                scope, attr = self._class, t.attr
                if t.attr == "daemon" \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value:
                    continue
            elif isinstance(t, ast.Attribute) and t.attr == "daemon" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value \
                    and isinstance(t.value, ast.Name):
                # t.daemon = True on a local thread object
                self.mi.daemonized.add(t.value.id)
                continue
            else:
                continue
            if scope is not None:
                kind = _is_lock_factory(node.value)
                if kind is not None:
                    fam, name = kind
                    if fam == "named" and name:
                        canon = name
                    elif fam == "mesh":
                        canon = "executor.mesh"
                    else:
                        canon = ("%s.%s.%s"
                                 % (self.mi.mod, scope, attr)
                                 if scope else
                                 "%s.%s" % (self.mi.mod, attr))
                    self.mi.lockdefs[(scope, attr)] = canon
                else:
                    self._raw.append((scope, attr, node.value))
            # thread assignment bookkeeping (any scope)
            if isinstance(node.value, ast.Call):
                d = _dotted(node.value.func) or ""
                if d.split(".")[-1] == "Thread" \
                        and d.startswith("threading"):
                    has_daemon = any(
                        kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value
                        for kw in node.value.keywords)
                    self.mi.thread_sites.append(
                        (node.value.lineno, attr, has_daemon))
        self.generic_visit(node)

    def visit_Call(self, node):
        d = _dotted(node.func) or ""
        tail = d.split(".")[-1]
        if tail == "join" and isinstance(node.func, ast.Attribute):
            base = node.func.value
            name = base.attr if isinstance(base, ast.Attribute) \
                else (base.id if isinstance(base, ast.Name) else None)
            if name:
                self.mi.joined.add(name)
        elif tail == "setDaemon" \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value:
            self.mi.daemonized.add(node.func.value.id)
        elif tail == "Thread" and d.startswith("threading"):
            # bare threading.Thread(...).start() with no assignment
            has_daemon = any(kw.arg == "daemon"
                             and isinstance(kw.value, ast.Constant)
                             and kw.value.value
                             for kw in node.keywords)
            self.mi.thread_sites.append((node.lineno, None, has_daemon))
        self.generic_visit(node)

    def resolve_aliases(self):
        """self.X = self.Y / X = Y where the RHS is a known lock: two
        rounds close simple forward chains."""
        for _ in range(2):
            for scope, attr, value in self._raw:
                canon = self._lock_of(value, scope)
                if canon is not None:
                    self.mi.lockdefs.setdefault((scope, attr), canon)

    def _lock_of(self, expr, scope):
        if isinstance(expr, ast.Name):
            return self.mi.lockdefs.get(("", expr.id))
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return self.mi.lockdefs.get((scope, expr.attr))
        return None


class _FnWalker:
    """Pass 2: walk one function body tracking the held-lock stack,
    recording acquisitions, lexical order edges, calls (with held
    context), and blocking calls."""

    def __init__(self, mi, cls, fn_node):
        self.mi = mi
        self.cls = cls
        qual = ("%s.%s.%s" % (mi.mod, cls, fn_node.name) if cls
                else "%s.%s" % (mi.mod, fn_node.name))
        self.fi = _FnInfo(qual)
        self.held = []

    def run(self, fn_node):
        for stmt in fn_node.body:
            self._stmt(stmt)
        return self.fi

    # -- resolution ------------------------------------------------------
    def _resolve_lock(self, expr):
        if isinstance(expr, ast.Name):
            return self.mi.lockdefs.get(("", expr.id))
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            canon = self.mi.lockdefs.get((self.cls, expr.attr))
            if canon is not None:
                return canon
            if _LOCKISH.search(expr.attr):
                # lockish attribute with no visible definition (set by
                # a collaborator): still a node, scoped to the class
                return ("%s.%s.%s" % (self.mi.mod, self.cls, expr.attr)
                        if self.cls else
                        "%s.%s" % (self.mi.mod, expr.attr))
        return None

    def _resolve_callee(self, call):
        fn = call.func
        if isinstance(fn, ast.Name):
            qual = "%s.%s" % (self.mi.mod, fn.id)
            return qual if qual in self.mi.funcs else None
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self" and self.cls:
            qual = "%s.%s.%s" % (self.mi.mod, self.cls, fn.attr)
            return qual if qual in self.mi.funcs else None
        return None

    # -- walk ------------------------------------------------------------
    def _stmt(self, node):
        if isinstance(node, ast.With):
            locks = []
            for item in node.items:
                canon = self._resolve_lock(item.context_expr)
                self._expr(item.context_expr)
                if canon is None:
                    continue
                self.fi.acquires.append((canon, node.lineno))
                for h in self.held:
                    if h != canon:
                        self.fi.edges.append((h, canon, node.lineno))
                self.held.append(canon)
                locks.append(canon)
            for stmt in node.body:
                self._stmt(stmt)
            for canon in reversed(locks):
                self.held.pop()
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: walked with an EMPTY held stack — it runs
            # later, not here (closures that demonstrably run inline
            # are beyond a static pass; the dynamic sanitizer covers
            # them)
            saved, self.held = self.held, []
            for stmt in node.body:
                self._stmt(stmt)
            self.held = saved
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._expr(child)

    def _expr(self, node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub)

    def _call(self, node):
        held = tuple(self.held)
        callee = self._resolve_callee(node)
        if callee is not None:
            self.fi.calls.append((callee, node.lineno, held))
        kind = _blocking_kind(node)
        if kind is not None:
            self.fi.blocking.append((kind, node.lineno, held))


def _blocking_kind(call):
    """Human name of a potentially-unbounded blocking call, or None."""
    fn = call.func
    dotted = _dotted(fn) or ""
    tail = dotted.split(".")[-1] if dotted else (
        fn.attr if isinstance(fn, ast.Attribute) else "")
    nargs = len(call.args)
    kwargs = {kw.arg for kw in call.keywords}
    if isinstance(fn, ast.Name) and fn.id == "open":
        return "open()"
    if tail in _SOCKET_METHODS:
        return "socket .%s()" % tail
    if tail in _SUBPROCESS_FNS or (
            dotted.startswith("subprocess.") and tail in ("run",
                                                          "call")):
        return "subprocess %s()" % tail
    if dotted == "time.sleep":
        return "time.sleep()"
    if not isinstance(fn, ast.Attribute):
        return None
    # zero-arg shapes: dict.get/str.join always take positional args,
    # so an argless .get()/.join()/.wait() is the queue/thread/
    # condition form.  A timeout= keyword (or block+timeout) bounds it.
    if tail == "get" and nargs == 0 and "timeout" not in kwargs \
            and kwargs <= {"block"}:
        return "queue .get() without timeout"
    if tail == "wait" and nargs == 0 and "timeout" not in kwargs \
            and not kwargs:
        return ".wait() without timeout"
    if tail == "join" and nargs == 0 and "timeout" not in kwargs \
            and not kwargs \
            and not isinstance(fn.value, ast.Constant):
        return ".join() without timeout"
    return None


# ---------------------------------------------------------------------------
# plane-contract verification
# ---------------------------------------------------------------------------

# Every observability plane's hot-path seam: (file, function qualname,
# plane-global expression).  The rule verifies each is EXACTLY the
# documented off-mode shape — one load of the global, immediately
# guarded by a pure `is None` / `is not None` test, with nothing
# allocated or called on the off path.  A seam that cannot be found
# fails too: manifest drift must be loud.
PLANE_SEAMS = (
    ("faults.py", "hit", "_PLANE"),
    ("trace.py", "span", "_PLANE"),
    ("trace.py", "event", "_PLANE"),
    ("trace.py", "emit", "_PLANE"),
    ("trace.py", "ctx", "_PLANE"),
    ("trace.py", "TracePlane.record", "_health._SINK"),
    ("trace.py", "TracePlane.record", "_ledger._SINK"),
    ("locks.py", "_NamedLock.__enter__", "_SANITIZER"),
    ("locks.py", "_NamedLock.__exit__", "_SANITIZER"),
    ("locks.py", "note_acquire", "_SANITIZER"),
    ("locks.py", "note_release", "_SANITIZER"),
    ("aotcache.py", "set_current_sig", "_PLANE"),
    ("aotcache.py", "stats", "_PLANE"),
    ("resultcache.py", "probe", "_PLANE"),
    ("resultcache.py", "offer", "_PLANE"),
    ("resultcache.py", "stats", "_PLANE"),
    ("backend/tpu/executor.py", "_ProgramCache.__setitem__",
     "aotcache._PLANE"),
)


def _match_global(node, dotted):
    if "." in dotted:
        head, _, tail = dotted.partition(".")
        return (isinstance(node, ast.Attribute) and node.attr == tail
                and isinstance(node.value, ast.Name)
                and node.value.id == head)
    return isinstance(node, ast.Name) and node.id == dotted \
        and isinstance(node.ctx, ast.Load)


def _find_fn(tree, qualname):
    cls, _, meth = qualname.rpartition(".")
    if cls:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef) \
                            and sub.name == meth:
                        return sub
        return None
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == qualname:
            return node
    return None


def _stmt_lists(fn_node):
    yield fn_node.body
    for node in ast.walk(fn_node):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(node, field, None)
            if isinstance(block, list) and node is not fn_node \
                    and block and isinstance(block[0], ast.stmt):
                yield block


def _is_simple(expr):
    """No allocation/calls: Constant, Name, or a plain attribute."""
    if expr is None:
        return True
    return isinstance(expr, (ast.Constant, ast.Name, ast.Attribute))


def _guard_test(test, local):
    """(form, ok): test must be `<local> is None` / `is not None`."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and test.left.id == local
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None):
        return None
    if isinstance(test.ops[0], ast.Is):
        return "is-none"
    if isinstance(test.ops[0], ast.IsNot):
        return "is-not-none"
    return None


def check_plane_seam(tree, qualname, dotted):
    """None when the seam is exactly the documented shape, else a
    (lineno, problem) tuple.

    The contract is about the OFF path: the FIRST load of the plane
    global must be a pure ``is None`` guard whose off branch does no
    work, and every later load must be reachable only when the plane
    is on (after an is-None guard that returned, or inside an
    is-not-None body)."""
    fn = _find_fn(tree, qualname)
    if fn is None:
        return (1, "hot-path function %r not found (manifest drift?)"
                % qualname)
    loads = sorted((n for n in ast.walk(fn)
                    if _match_global(n, dotted)),
                   key=lambda n: (n.lineno, n.col_offset))
    if not loads:
        return (fn.lineno, "no load of %s on the hot path (manifest "
                "drift?)" % dotted)
    load = loads[0]
    rest = loads[1:]
    for block in _stmt_lists(fn):
        for i, stmt in enumerate(block):
            # form (b): `if GLOBAL is None: return <simple>`
            if isinstance(stmt, ast.If) \
                    and isinstance(stmt.test, ast.Compare) \
                    and len(stmt.test.ops) == 1 \
                    and isinstance(stmt.test.ops[0], ast.Is) \
                    and stmt.test.left is load:
                comp = stmt.test.comparators[0]
                if not (isinstance(comp, ast.Constant)
                        and comp.value is None):
                    return (stmt.lineno, "guard compares %s against a "
                            "non-None value" % dotted)
                if not (stmt.body
                        and isinstance(stmt.body[0], ast.Return)
                        and _is_simple(stmt.body[0].value)):
                    return (stmt.lineno, "off path is not a plain "
                            "return (allocation on the off path)")
                # later loads run only after the guard returned: on-path
                for n in rest:
                    if n.lineno <= stmt.lineno:
                        return (n.lineno, "extra load of %s before "
                                "the off-mode guard" % dotted)
                return None
            # form (a): `x = GLOBAL` + adjacent guard on x
            if isinstance(stmt, ast.Assign) and stmt.value is load:
                if rest:
                    return (rest[0].lineno, "%s loaded again after "
                            "being bound to a local — use the local"
                            % dotted)
                if len(stmt.targets) != 1 \
                        or not isinstance(stmt.targets[0], ast.Name):
                    return (stmt.lineno, "plane global must bind to "
                            "one plain local")
                local = stmt.targets[0].id
                if i + 1 >= len(block) \
                        or not isinstance(block[i + 1], ast.If):
                    return (stmt.lineno, "load of %s is not "
                            "immediately guarded" % dotted)
                guard = block[i + 1]
                form = _guard_test(guard.test, local)
                if form is None:
                    return (guard.lineno, "guard is not a pure "
                            "`%s is None` test" % local)
                if form == "is-none":
                    if guard.orelse:
                        return (guard.lineno, "is-None guard carries "
                                "an else branch")
                    if not (guard.body
                            and isinstance(guard.body[0], ast.Return)
                            and _is_simple(guard.body[0].value)):
                        return (guard.lineno, "off path is not a "
                                "plain return")
                    return None
                # is-not-none: every other use of the local must live
                # inside this guard (the off path falls through doing
                # nothing)
                inside = {id(n) for n in ast.walk(guard)}
                for n in ast.walk(fn):
                    if isinstance(n, ast.Name) and n.id == local \
                            and n is not stmt.targets[0] \
                            and n is not guard.test.left \
                            and id(n) not in inside:
                        return (n.lineno, "local %r escapes its "
                                "is-not-None guard" % local)
                return None
    return (load.lineno, "load of %s is neither bound to a guarded "
            "local nor tested directly" % dotted)


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

class ConcurrencyPass:
    def __init__(self, root=None, mesh_locks=MESH_LOCKS):
        self.root = root
        self.mesh_locks = frozenset(mesh_locks)
        self.modules = []
        self._parse_errors = []

    def add_source(self, path, text=None):
        if text is None:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                text = f.read()
        try:
            tree = ast.parse(text, filename=path)
        except SyntaxError:
            return                  # closure_rules already reports it
        rel = os.path.relpath(path, self.root).replace(os.sep, "/") \
            if self.root else path
        mod = os.path.splitext(os.path.basename(path))[0]
        mi = _ModuleInfo(path, rel, mod)
        coll = _DefCollector(mi)
        coll.visit(tree)
        coll.resolve_aliases()
        self._walk_functions(mi, tree)
        self.modules.append(mi)

    @staticmethod
    def _walk_functions(mi, tree):
        def walk(nodes, cls):
            for node in nodes:
                if isinstance(node, ast.ClassDef):
                    walk(node.body, node.name)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    fi = _FnWalker(mi, cls, node).run(node)
                    mi.fns[fi.qual] = fi
        walk(tree.body, "")

    # -- closures --------------------------------------------------------
    def _closures(self):
        """Transitive (locks, blocking-kinds) acquired/reached by each
        function through same-module calls — bounded fixpoint."""
        own_locks, own_block, callees, fn_mod = {}, {}, {}, {}
        for mi in self.modules:
            for qual, fi in mi.fns.items():
                own_locks[qual] = {l for l, _ in fi.acquires}
                own_block[qual] = {k for k, _, _ in fi.blocking}
                callees[qual] = {c for c, _, _ in fi.calls}
                fn_mod[qual] = mi
        clo_locks = {q: set(s) for q, s in own_locks.items()}
        clo_block = {q: set(s) for q, s in own_block.items()}
        for _ in range(16):
            changed = False
            for q, cs in callees.items():
                for c in cs:
                    if c in clo_locks:
                        before = len(clo_locks[q]) + len(clo_block[q])
                        clo_locks[q] |= clo_locks[c]
                        clo_block[q] |= clo_block[c]
                        if len(clo_locks[q]) + len(clo_block[q]) \
                                != before:
                            changed = True
            if not changed:
                break
        return clo_locks, clo_block

    def finish(self, report=None):
        report = report if report is not None else Report()
        clo_locks, clo_block = self._closures()

        # -- global lock-order graph ------------------------------------
        edges = {}              # (a, b) -> site

        def add_edge(a, b, site):
            if a != b and (a, b) not in edges:
                edges[(a, b)] = site

        for mi in self.modules:
            for fi in mi.fns.values():
                for a, b, lineno in fi.edges:
                    add_edge(a, b, "%s:%d" % (mi.rel, lineno))
                for callee, lineno, held in fi.calls:
                    if not held:
                        continue
                    for l in clo_locks.get(callee, ()):
                        if l in held:
                            # a lock the caller already holds is a
                            # reentrant re-acquire in the callee (the
                            # mesh RLock under _export_bucket), not a
                            # fresh ordering edge
                            continue
                        for h in held:
                            add_edge(h, l,
                                     "%s:%d" % (mi.rel, lineno))

        succ = {}
        for (a, b) in edges:
            succ.setdefault(a, []).append(b)
        from dpark_tpu.locks import _tarjan
        nodes = sorted(set(succ)
                       | {b for bs in succ.values() for b in bs})
        for scc in _tarjan(nodes, succ):
            group = set(scc)
            cyc = None
            if len(scc) > 1:
                cyc = _scc_path(min(scc), group, succ)
            elif scc[0] in succ.get(scc[0], ()):
                cyc = [scc[0], scc[0]]
            if not cyc:
                continue
            sites = [edges.get((cyc[i], cyc[i + 1]), "?")
                     for i in range(len(cyc) - 1)]
            report.add(
                "lock-order-cycle", "error",
                "%s cycle(%s)" % (sites[0], ",".join(sorted(group))),
                "static lock-order cycle: %s (edge sites: %s) — two "
                "threads interleaving these paths deadlock"
                % (" -> ".join(cyc), ", ".join(sites)),
                "pick one global order (see locks.DOCUMENTED_ORDER) "
                "and release the earlier lock before taking the later "
                "one on every path")

        # -- blocking-under-lock / unbounded-wait / thread-leak ---------
        for mi in self.modules:
            for fi in mi.fns.values():
                for kind, lineno, held in fi.blocking:
                    site = "%s:%d" % (mi.rel, lineno)
                    if any(h in self.mesh_locks for h in held):
                        report.add(
                            "blocking-under-lock", "warn", site,
                            "%s while holding the mesh lock: every "
                            "tenant's device dispatch queues behind "
                            "this call" % kind,
                            "move the blocking operation outside the "
                            "lock, or bound it with a timeout")
                    if "without timeout" in kind:
                        report.add(
                            "unbounded-wait", "warn", site,
                            "%s: a dead peer or worker parks this "
                            "thread forever instead of surfacing a "
                            "recoverable failure" % kind,
                            "pass timeout= and translate expiry into "
                            "the caller's failure path (FetchFailed, "
                            "retry, or abort)")
                for callee, lineno, held in fi.calls:
                    if not any(h in self.mesh_locks for h in held):
                        continue
                    kinds = clo_block.get(callee, ())
                    if kinds:
                        report.add(
                            "blocking-under-lock", "warn",
                            "%s:%d" % (mi.rel, lineno),
                            "call to %s() under the mesh lock reaches "
                            "a blocking operation (%s)"
                            % (callee, ", ".join(sorted(kinds))),
                            "hoist the blocking work out of the "
                            "locked region")
            named_lines = {l for l, t, _ in mi.thread_sites
                           if t is not None}
            for lineno, target, has_daemon in mi.thread_sites:
                if has_daemon:
                    continue
                if target is None and lineno in named_lines:
                    continue    # same call seen via its assignment

                if target is not None and (target in mi.daemonized
                                           or target in mi.joined):
                    continue
                report.add(
                    "thread-leak", "warn", "%s:%d" % (mi.rel, lineno),
                    "non-daemon thread%s has no visible join path: "
                    "interpreter exit hangs on it"
                    % ("" if target is None else " %r" % target),
                    "pass daemon=True, or join it on the shutdown "
                    "path")

        # -- plane contracts --------------------------------------------
        self._check_planes(report)
        return report

    def _check_planes(self, report):
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        cache = {}
        for relfile, qualname, dotted in PLANE_SEAMS:
            path = os.path.join(pkg, relfile)
            tree = cache.get(path)
            if tree is None:
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=path)
                except (OSError, SyntaxError) as e:
                    report.add("plane-contract", "error",
                               "dpark_tpu/%s %s" % (relfile, qualname),
                               "plane module unreadable: %s" % e)
                    continue
                cache[path] = tree
            bad = check_plane_seam(tree, qualname, dotted)
            if bad is not None:
                lineno, problem = bad
                report.add(
                    "plane-contract", "error",
                    "dpark_tpu/%s:%d %s[%s]" % (relfile, lineno,
                                                qualname, dotted),
                    "off-mode seam violated: %s" % problem,
                    "the hot path must be exactly one load of the "
                    "plane global guarded by a pure `is None` check "
                    "with nothing allocated when off — the <=1.03x "
                    "overhead bar depends on it")


def _scc_path(start, group, succ):
    seen = {start}
    frontier = [[start]]
    while frontier:
        nxt = []
        for path in frontier:
            for b in succ.get(path[-1], ()):
                if b == start:
                    return path + [start]
                if b in group and b not in seen:
                    seen.add(b)
                    nxt.append(path + [b])
        frontier = nxt
    return None


def lint_concurrency(paths, report=None, root=None):
    """Run the concurrency rule families over `paths` (files); the
    plane-contract manifest is always checked against the installed
    package regardless of `paths`.  Returns the Report."""
    report = report if report is not None else Report()
    p = ConcurrencyPass(root=root)
    for path in paths:
        p.add_source(path)
    p.finish(report)
    return report
