"""dlint — run the closure + concurrency rules over source files/trees.

    python -m dpark_tpu.analysis file.py dir/ ...
    python -m dpark_tpu.analysis --self            # dpark_tpu/ + examples/
    python -m dpark_tpu.analysis --locks           # concurrency rules only
    tools/dlint examples/wordcount.py              # thin wrapper

Exit codes: 0 clean (or every finding baselined / warnings only without
a baseline), 1 new findings (errors always; warn+ when a baseline is in
play), 2 usage error.

The committed baseline (tools/dlint_baseline.json) freezes today's
known findings so CI fails only on NEW anti-patterns: a baseline key is
"<relpath>::<rule>::<site-minus-line-numbers>", deliberately coarse so
unrelated edits to a file do not churn it.  The file maps each key to a
one-line justification for WHY the finding is accepted (legacy bare
lists still load).  Refresh deliberately with --write-baseline after
fixing or accepting findings; existing justifications are preserved.
"""

import argparse
import json
import os
import re
import sys

from dpark_tpu.analysis.report import SEVERITIES, Report
from dpark_tpu.analysis.closure_rules import lint_source
from dpark_tpu.analysis.concurrency import ConcurrencyPass


def _repo_root():
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if not d.startswith(".")
                               and d != "__pycache__"]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)
        elif p.endswith(".py"):
            yield p
        else:
            raise SystemExit("dlint: not a .py file or directory: %s" % p)


def baseline_key(root, finding):
    """Stable identity for the committed baseline: relative path + rule
    + site with every :<line> stripped."""
    site = re.sub(r":\d+", "", finding.site)
    parts = site.split(" ", 1)
    rel = parts[0]
    if os.path.isabs(rel):
        rel = os.path.relpath(rel, root)
    rel = rel.replace(os.sep, "/")
    tail = (" " + parts[1]) if len(parts) > 1 else ""
    return "%s%s::%s" % (rel, tail, finding.rule)


def load_baseline(path):
    """Baseline file -> {key: justification}.  Accepts the legacy bare
    list form (justification defaults to empty)."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, list):
        return {k: "" for k in data}
    return dict(data)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dlint", description="dpark_tpu closure linter")
    ap.add_argument("paths", nargs="*", help=".py files or directories")
    ap.add_argument("--self", dest="self_lint", action="store_true",
                    help="lint the dpark_tpu package and examples/")
    ap.add_argument("--locks", action="store_true",
                    help="run ONLY the concurrency rule families "
                         "(lock-order-cycle, blocking-under-lock, "
                         "unbounded-wait, thread-leak, plane-contract);"
                         " with no paths, defaults to --self scope")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted finding keys "
                         "(default with --self: tools/dlint_baseline"
                         ".json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--tpu", action="store_true",
                    help="treat closures as routed to the tpu master "
                         "(tracer rules escalate info -> warn)")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable findings on stdout")
    args = ap.parse_args(argv)

    root = _repo_root()
    paths = list(args.paths)
    baseline_path = args.baseline
    if args.locks and not paths and not args.self_lint:
        args.self_lint = True       # bare `dlint --locks` = self scope
    if args.self_lint:
        paths += [os.path.join(root, "dpark_tpu"),
                  os.path.join(root, "examples")]
        if baseline_path is None:
            baseline_path = os.path.join(root, "tools",
                                         "dlint_baseline.json")
    if not paths:
        ap.print_usage(sys.stderr)
        return 2

    run_closure = not args.locks
    run_locks = args.locks or args.self_lint
    report = Report()
    conc = ConcurrencyPass(root=root) if run_locks else None
    nfiles = 0
    for path in _py_files(paths):
        nfiles += 1
        if run_closure:
            lint_source(path, report=report, tpu=args.tpu)
        if conc is not None:
            conc.add_source(path)
    if conc is not None:
        # the lock-order graph is global: finish() merges edges across
        # every file fed above, then checks cycles + plane contracts
        conc.finish(report)

    keys = {baseline_key(root, f): f for f in report}
    if args.write_baseline and baseline_path:
        old = {}
        if os.path.exists(baseline_path):
            old = load_baseline(baseline_path)
        merged = {k: old.get(k, "") for k in sorted(keys)}
        if not run_closure:
            # partial run (--locks): keep the closure-rule keys intact
            for k, v in old.items():
                merged.setdefault(k, v)
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        with open(baseline_path, "w") as f:
            json.dump(merged, f, indent=1, sort_keys=True)
            f.write("\n")
        print("dlint: wrote %d baseline keys -> %s"
              % (len(keys), baseline_path), file=sys.stderr)
        return 0

    baseline = set()
    if baseline_path and os.path.exists(baseline_path):
        baseline = set(load_baseline(baseline_path))

    fresh = [f for k, f in sorted(keys.items()) if k not in baseline]
    suppressed = len(report) - len(fresh)

    if args.as_json:
        json.dump([f.as_dict() for f in fresh], sys.stdout, indent=1)
        print()
    else:
        for f in fresh:
            print(f.render())

    errors = sum(1 for f in fresh if f.severity == "error")
    warns = sum(1 for f in fresh if f.severity == "warn")
    print("dlint: %d file%s, %d finding%s (%d error%s, %d warning%s)"
          "%s" % (nfiles, "s" if nfiles != 1 else "",
                  len(fresh), "s" if len(fresh) != 1 else "",
                  errors, "s" if errors != 1 else "",
                  warns, "s" if warns != 1 else "",
                  ", %d baselined" % suppressed if suppressed else ""),
          file=sys.stderr)

    if errors:
        return 1
    if warns and baseline:
        # a baseline is the CI contract: NEW warnings fail the build
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
