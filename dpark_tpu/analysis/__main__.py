"""dlint — run the closure rules over source files/trees.

    python -m dpark_tpu.analysis file.py dir/ ...
    python -m dpark_tpu.analysis --self            # dpark_tpu/ + examples/
    tools/dlint examples/wordcount.py              # thin wrapper

Exit codes: 0 clean (or every finding baselined / warnings only without
a baseline), 1 new findings (errors always; warn+ when a baseline is in
play), 2 usage error.

The committed baseline (tools/dlint_baseline.json) freezes today's
known findings so CI fails only on NEW anti-patterns: a baseline key is
"<relpath>::<rule>::<site-minus-line-numbers>", deliberately coarse so
unrelated edits to a file do not churn it.  Refresh deliberately with
--write-baseline after fixing or accepting findings.
"""

import argparse
import json
import os
import re
import sys

from dpark_tpu.analysis.report import SEVERITIES, Report
from dpark_tpu.analysis.closure_rules import lint_source


def _repo_root():
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if not d.startswith(".")
                               and d != "__pycache__"]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)
        elif p.endswith(".py"):
            yield p
        else:
            raise SystemExit("dlint: not a .py file or directory: %s" % p)


def baseline_key(root, finding):
    """Stable identity for the committed baseline: relative path + rule
    + site with every :<line> stripped."""
    site = re.sub(r":\d+", "", finding.site)
    parts = site.split(" ", 1)
    rel = os.path.relpath(parts[0], root).replace(os.sep, "/")
    tail = (" " + parts[1]) if len(parts) > 1 else ""
    return "%s%s::%s" % (rel, tail, finding.rule)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="dlint", description="dpark_tpu closure linter")
    ap.add_argument("paths", nargs="*", help=".py files or directories")
    ap.add_argument("--self", dest="self_lint", action="store_true",
                    help="lint the dpark_tpu package and examples/")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline of accepted finding keys "
                         "(default with --self: tools/dlint_baseline"
                         ".json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    ap.add_argument("--tpu", action="store_true",
                    help="treat closures as routed to the tpu master "
                         "(tracer rules escalate info -> warn)")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable findings on stdout")
    args = ap.parse_args(argv)

    root = _repo_root()
    paths = list(args.paths)
    baseline_path = args.baseline
    if args.self_lint:
        paths += [os.path.join(root, "dpark_tpu"),
                  os.path.join(root, "examples")]
        if baseline_path is None:
            baseline_path = os.path.join(root, "tools",
                                         "dlint_baseline.json")
    if not paths:
        ap.print_usage(sys.stderr)
        return 2

    report = Report()
    nfiles = 0
    for path in _py_files(paths):
        nfiles += 1
        lint_source(path, report=report, tpu=args.tpu)

    keys = {baseline_key(root, f): f for f in report}
    if args.write_baseline and baseline_path:
        os.makedirs(os.path.dirname(baseline_path), exist_ok=True)
        with open(baseline_path, "w") as f:
            json.dump(sorted(keys), f, indent=1)
            f.write("\n")
        print("dlint: wrote %d baseline keys -> %s"
              % (len(keys), baseline_path), file=sys.stderr)
        return 0

    baseline = set()
    if baseline_path and os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = set(json.load(f))

    fresh = [f for k, f in sorted(keys.items()) if k not in baseline]
    suppressed = len(report) - len(fresh)

    if args.as_json:
        json.dump([f.as_dict() for f in fresh], sys.stdout, indent=1)
        print()
    else:
        for f in fresh:
            print(f.render())

    errors = sum(1 for f in fresh if f.severity == "error")
    warns = sum(1 for f in fresh if f.severity == "warn")
    print("dlint: %d file%s, %d finding%s (%d error%s, %d warning%s)"
          "%s" % (nfiles, "s" if nfiles != 1 else "",
                  len(fresh), "s" if len(fresh) != 1 else "",
                  errors, "s" if errors != 1 else "",
                  warns, "s" if warns != 1 else "",
                  ", %d baselined" % suppressed if suppressed else ""),
          file=sys.stderr)

    if errors:
        return 1
    if warns and baseline:
        # a baseline is the CI contract: NEW warnings fail the build
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
