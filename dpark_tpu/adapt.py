"""Adaptive execution (ISSUE 7): a persistent feedback store + cost
model that closes the loop from recorded stats to plan choices.

PRs 1-6 built rich telemetry — per-stage phase tables, pipeline idle
fractions, fallback/degrade reasons, decode counters — but every plan
decision was static at trace time, so each job re-discovered the same
budgets and re-paid the same mispredictions.  This module persists
per-(program, shape class) observations ACROSS jobs and feeds four
decision points ("Partial Partial Aggregates" is the theory anchor for
pricing aggregation choices by observed cost):

  1. wave budget     conf.stream_chunk_rows seeds from the last-known
                     -good budget of the (row-width) class — recorded
                     by the OOM degradation ladder — instead of
                     re-deriving HBM/16 and re-walking the halving
                     ladder every job.
  2. device vs host  the tpu scheduler prices the array path against
                     the object path from OBSERVED per-program ms and
                     declines the device when the host is recorded
                     cheaper (`adapt_reason` per stage, the cost-model
                     sibling of fallback_reason/degrade_reason).
  3. partition count a dominant key group (from the bucket histograms
                     SegMapOp already computes) widens the reduce side
                     of the next run of that program.
  4. map-side combine the groupByKey aggregate rewrite is priced from
                     the observed combine ratio (distinct keys /
                     rows): a ratio near 1 means pre-aggregation buys
                     nothing, so the rewrite is declined and the
                     device SegAggOp serves the chain — the PR-1
                     linter's `group-agg` advisory as an actual
                     optimizer choice.

Modes (conf.DPARK_ADAPT):
  off      no reads, no writes, zero hot-path cost beyond a flag check
  observe  record observations (and log would-be choices, applied:
           false) but NEVER steer — bit-identical to off; the CI-safe
           default
  on       record AND steer

Store: JSON-lines under conf.DPARK_ADAPT_DIR (one ``stats.jsonl``).
Each line is framed ``<crc32 hex> <json>`` with the same checksum the
spill runs use (shuffle.spill_crc), appended with a single O_APPEND
write so concurrent processes interleave whole lines; corrupt or
truncated lines are skipped at load (never an error).  Reset by
deleting the directory (``rm -rf $DPARK_ADAPT_DIR``) or via
``adapt.configure(...)`` / ``adapt.reset_store()``.

Every public entry point is guarded: adaptation must never break a
job, so failures log at debug and fall back to the static behavior.
"""

import json
import os
import threading

from dpark_tpu import conf
from dpark_tpu.utils.log import get_logger

logger = get_logger("adapt")

MODES = ("off", "observe", "on")

STORE_FILE = "stats.jsonl"

# decisions kept in the process-global log (older entries age out; the
# absolute position survives trimming so per-job deltas stay correct)
_LOG_CAP = 512
# exponential-moving-average weight for ms / ratio observations
_EMA = 0.5

_lock = threading.RLock()
_mode = None                  # resolved mode, or None = read conf lazily
_dir = None                   # resolved store dir, or None = read conf
_loaded = False
_agg = {"wave_budget": {}, "stage": {}, "skew": {}, "combine": {},
        "pane": {}, "site": {}, "prog": {}, "reuse": {}, "xch": {},
        "replan": {}}
_counters = {"store_hits": 0, "store_misses": 0, "steered": 0,
             "recorded": 0, "skipped_lines": 0}
_decisions = []
_decisions_base = 0           # absolute position of _decisions[0]
_logged = set()               # (point, key, choice) de-dup for the log
_pending = {}                 # stage key -> decision awaiting observed ms
# per-thread job attribution (ISSUE 9): the resident job server's
# slot threads set the job id they are executing for, so a decision
# taken during a CONCURRENT stage lands in the right job's record
_job_tls = threading.local()


def set_current_job(job):
    """Tag decisions taken on THIS thread with a job id (None clears).
    Only the resident service sets this; single-job schedulers leave
    decisions untagged, and decisions_since(pos) returns them all —
    the pre-service behavior, bit for bit."""
    _job_tls.job = job


def _current_job():
    return getattr(_job_tls, "job", None)


# ---------------------------------------------------------------------------
# configuration / lifecycle
# ---------------------------------------------------------------------------

def mode():
    """The resolved mode (validates conf.DPARK_ADAPT on first read)."""
    global _mode
    if _mode is None:
        m = str(getattr(conf, "DPARK_ADAPT", "observe")).lower()
        if m not in MODES:
            raise ValueError(
                "DPARK_ADAPT=%r (expected off|observe|on)" % m)
        _mode = m
    return _mode


def enabled():
    """True when observations should be recorded (observe or on)."""
    return mode() != "off"


def steering():
    """True only when recorded stats may CHANGE plan choices."""
    return mode() == "on"


def store_dir():
    global _dir
    if _dir is None:
        _dir = getattr(conf, "DPARK_ADAPT_DIR", None) or os.path.join(
            conf.DPARK_WORK_DIR, "adapt")
    return _dir


def configure(mode=None, store_dir=None):
    """Re-point the adaptive plane (tests/benchmarks): resets ALL
    in-memory state (aggregates, counters, decision log) and resolves
    mode/dir from the arguments, falling back to conf for whichever is
    None.  The on-disk store is untouched — use reset_store() to wipe
    it."""
    global _mode, _dir, _loaded, _decisions_base
    with _lock:
        _mode = None
        _dir = None
        _loaded = False
        for d in _agg.values():
            d.clear()
        for k in _counters:
            _counters[k] = 0
        _decisions.clear()
        _decisions_base = 0
        _logged.clear()
        _pending.clear()
        if mode is not None:
            if str(mode).lower() not in MODES:
                raise ValueError(
                    "adapt mode %r (expected off|observe|on)" % mode)
            _mode = str(mode).lower()
        if store_dir is not None:
            _dir = str(store_dir)


def reset_store():
    """Delete the on-disk store (the documented reset) and the
    in-memory aggregates, keeping the configured mode/dir."""
    with _lock:
        path = _store_path()
        try:
            os.unlink(path)
        except OSError:
            pass
        global _loaded
        _loaded = False
        for d in _agg.values():
            d.clear()


# ---------------------------------------------------------------------------
# the store: crc-framed JSON lines, process-safe append
# ---------------------------------------------------------------------------

def _crc(blob):
    from dpark_tpu.shuffle import spill_crc
    return spill_crc(blob)


def _store_path():
    return os.path.join(store_dir(), STORE_FILE)


def _ensure_loaded():
    """Load the store file into the in-memory aggregates once per
    process (records apply in file order = chronological order)."""
    global _loaded
    if _loaded:
        return
    with _lock:
        if _loaded:
            return
        _loaded = True               # even when the file is absent
        path = _store_path()
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        from dpark_tpu.utils import unframe_jsonl
        recs, skipped = unframe_jsonl(raw)
        _counters["skipped_lines"] += skipped
        for rec in recs:
            try:
                _apply(rec)
            except Exception:
                # foreign / malformed record: skip, never fail
                _counters["skipped_lines"] += 1
        cap = int(getattr(conf, "ADAPT_STORE_MAX_BYTES", 0) or 0)
        if cap and len(raw) > cap:
            _compact_locked(path)


def _compact_locked(path):
    """Rewrite the store as its folded aggregates — one line per key —
    so the append-only file stays bounded (conf.ADAPT_STORE_MAX_BYTES).
    Best-effort tmp+rename: lines another process appends during the
    rewrite are lost, which is acceptable for advisory statistics (the
    EMA sample counts also reset to the compacted snapshot)."""
    recs = []
    for key, ent in _agg["wave_budget"].items():
        for slot, ok in (("good", True), ("bad", False)):
            if ent.get(slot):
                recs.append({"k": "wb", "key": key,
                             "budget": int(ent[slot]), "ok": ok,
                             "src": "compact"})
    for key, ent in _agg["stage"].items():
        for p in ("device", "host"):
            if ent.get(p + "_ms") is not None:
                recs.append({"k": "stage", "key": key, "path": p,
                             "ms": round(ent[p + "_ms"], 2)})
        for _ in range(min(int(ent.get("device_errors", 0)), 3)):
            recs.append({"k": "stage", "key": key, "path": "device",
                         "error": True})
    for key, ent in _agg["skew"].items():
        recs.append(dict(ent, k="skew", key=key))
    for key, ent in _agg["combine"].items():
        if ent.get("ratio") is not None:
            recs.append({"k": "combine", "key": key,
                         "rows_in": 1000000,
                         "rows_out": int(ent["ratio"] * 1000000)})
    for key, ent in _agg["pane"].items():
        for mode in ("tree", "flat", "inv"):
            if ent.get(mode + "_ms") is not None:
                recs.append({"k": "pane", "key": key, "mode": mode,
                             "ms": round(ent[mode + "_ms"], 2),
                             "w": int(ent.get("w", 0))})
    for key, ent in _agg["site"].items():
        recs.append({"k": "site", "key": key, "digest": dict(ent)})
    for key, ent in _agg["prog"].items():
        recs.append({"k": "prog", "key": key, "profile": dict(ent)})
    for key, ent in _agg["reuse"].items():
        recs.append(dict(ent, k="reuse", key=key))
    for key, ent in _agg["xch"].items():
        rec = {"k": "xch", "key": key,
               "peers": {p: dict(c)
                         for p, c in ent.get("peers", {}).items()}}
        if ent.get("fetch_ms") is not None:
            rec["fetch_ms"] = round(float(ent["fetch_ms"]), 2)
        recs.append(rec)
    for key, ent in _agg["replan"].items():
        recs.append(dict(ent, k="replan", key=key))
    try:
        from dpark_tpu.utils import frame_jsonl
        tmp = path + ".compact.%d" % os.getpid()
        with open(tmp, "wb") as f:
            f.write(b"".join(frame_jsonl(rec) for rec in recs))
        os.replace(tmp, path)
        logger.debug("adapt store compacted to %d records", len(recs))
    except Exception as e:
        logger.debug("adapt store compaction failed: %s", e)


def _append(rec):
    """Persist one observation: update the in-memory aggregates and
    append one crc-framed line with a single O_APPEND write (whole
    lines interleave safely across processes)."""
    _ensure_loaded()
    with _lock:
        _apply(rec)
        _counters["recorded"] += 1
        try:
            from dpark_tpu.utils import frame_jsonl
            line = frame_jsonl(rec)
            os.makedirs(store_dir(), exist_ok=True)
            fd = os.open(_store_path(),
                         os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except Exception as e:
            logger.debug("adapt store append failed: %s", e)


def _apply(rec):
    """Fold one record into the in-memory aggregates."""
    kind = rec.get("k")
    key = rec.get("key")
    if not key:
        return
    if kind == "wb":
        ent = _agg["wave_budget"].setdefault(
            key, {"good": None, "bad": None})
        budget = int(rec.get("budget", 0))
        if budget > 0:
            ent["good" if rec.get("ok") else "bad"] = budget
    elif kind == "stage":
        ent = _agg["stage"].setdefault(
            key, {"device_ms": None, "host_ms": None,
                  "device_n": 0, "host_n": 0, "device_errors": 0})
        path = rec.get("path")
        if rec.get("error"):
            ent["device_errors"] += 1
        elif path in ("device", "host"):
            ms = float(rec.get("ms", 0.0))
            cur = ent[path + "_ms"]
            ent[path + "_ms"] = ms if cur is None \
                else cur * (1 - _EMA) + ms * _EMA
            ent[path + "_n"] += 1
    elif kind == "skew":
        _agg["skew"][key] = {
            "rows": int(rec.get("rows", 0)),
            "groups": int(rec.get("groups", 0)),
            "max_group": int(rec.get("max_group", 0)),
            "parts": int(rec.get("parts", 0))}
    elif kind == "combine":
        rows_in = max(1, int(rec.get("rows_in", 1)))
        ratio = min(1.0, int(rec.get("rows_out", 0)) / rows_in)
        ent = _agg["combine"].setdefault(key, {"ratio": None, "n": 0})
        cur = ent["ratio"]
        ent["ratio"] = ratio if cur is None \
            else cur * (1 - _EMA) + ratio * _EMA
        ent["n"] += 1
    elif kind == "site":
        # per-site latency-tail digest delta (health plane, ISSUE 14):
        # the log-bucketed sketch shape health.Sketch.to_dict writes —
        # folding is bucket-wise addition, so deltas from any number
        # of processes/persists accumulate into one honest histogram
        # (the ROADMAP item 5 handoff: straggler-adaptive coding will
        # price (k, m) per exchange from these)
        from dpark_tpu import health
        _agg["site"][key] = health.merge_digests(
            _agg["site"].get(key), rec.get("digest"))
    elif kind == "prog":
        # program cost profile (ledger plane, ISSUE 15; AOT plane,
        # ISSUE 17): static flops / bytes / peak-HBM captured at
        # compile time PLUS the observed compile ms and resolution
        # hit count the AOT cache's boot warming ranks by, keyed by
        # the cross-process-stable plan signature.  Field-wise merge:
        # "hits" accumulates across records (compaction folds the
        # running total into one line, so reload stays honest),
        # "compile_ms" smooths by EMA (a noisy box must not own the
        # ranking), every other field is latest-wins (static profiles
        # are a pure function of the program + shape class; a newer
        # jax may refine the numbers).
        prof = rec.get("profile")
        if isinstance(prof, dict):
            ent = _agg["prog"].setdefault(key, {})
            for k, v in prof.items():
                if not isinstance(v, (int, float)):
                    continue
                v = float(v) if isinstance(v, float) else int(v)
                if k == "hits":
                    ent[k] = int(ent.get(k, 0)) + int(v)
                elif k == "compile_ms" and ent.get(k):
                    ent[k] = round(float(ent[k]) * (1 - _EMA)
                                   + float(v) * _EMA, 3)
                else:
                    ent[k] = v
    elif kind == "reuse":
        # result-cache hit-rate profile (ISSUE 18), keyed by the
        # cache entry key: pure running counts (compaction folds them
        # into one line, so reload stays honest).  The disk tier's
        # boot preload ranks entries by these hits — the same
        # observed-demand ranking the AOT warming uses.
        ent = _agg["reuse"].setdefault(
            key, {"hits": 0, "misses": 0, "partials": 0})
        for k in ("hits", "misses", "partials"):
            ent[k] = int(ent.get(k, 0)) + int(rec.get(k, 0) or 0)
    elif kind == "xch":
        # per-exchange peer profile (ISSUE 19): which peers served one
        # shuffle call site, with per-peer fetch counts and decode
        # outcomes accumulated across runs — the straggler-adaptive
        # code policy joins these peers against the "site" tail
        # sketches to price (k, m) for the NEXT run of this exchange
        ent = _agg["xch"].setdefault(key, {"peers": {}, "n": 0})
        for p, counts in (rec.get("peers") or {}).items():
            pc = ent["peers"].setdefault(str(p), {})
            for ck, cv in (counts or {}).items():
                try:
                    pc[ck] = int(pc.get(ck, 0)) + int(cv)
                except (TypeError, ValueError):
                    pass
        ent["n"] = int(ent.get("n", 0)) + 1
        if rec.get("fetch_ms") is not None:
            ms = float(rec["fetch_ms"])
            cur = ent.get("fetch_ms")
            ent["fetch_ms"] = ms if cur is None \
                else cur * (1 - _EMA) + ms * _EMA
    elif kind == "replan":
        # mid-job re-plan outcome (ISSUE 19): the salted re-split the
        # scheduler performed (or, in observe mode, would have) for a
        # shuffle call site — latest-wins, consumed by suggest_salt()
        # so the next run of the shape salts at PLAN time instead of
        # paying the mid-job re-split again
        _agg["replan"][key] = {
            "parts": int(rec.get("parts", 0)),
            "salt": int(rec.get("salt", 0)),
            "frac": float(rec.get("frac", 0.0))}
    elif kind == "pane":
        # per-(stream signature) windowed-emit tick cost by pane
        # strategy ("tree" | "flat" | "inv"): the split-point pricing
        # substrate (ISSUE 10)
        mode = rec.get("mode")
        if mode not in ("tree", "flat", "inv", "pane"):
            return
        ent = _agg["pane"].setdefault(key, {"w": 0})
        slot = mode + "_ms"
        ms = float(rec.get("ms", 0.0))
        cur = ent.get(slot)
        ent[slot] = ms if cur is None \
            else cur * (1 - _EMA) + ms * _EMA
        ent["w"] = int(rec.get("w", ent.get("w", 0)))


# ---------------------------------------------------------------------------
# decision log (rides job records as record["adapt"] and the bench JSON)
# ---------------------------------------------------------------------------

def _decide(point, key, choice, reason, predicted_ms=None,
            applied=True):
    """Log one (de-duplicated) decision; returns the dict so callers
    can later attach the observed outcome."""
    job = _current_job()
    with _lock:
        # the job id is part of the de-dup identity: two concurrent
        # jobs taking the same choice must EACH log it (each record
        # filters the log by its own id — ISSUE 9)
        dedup = (point, str(key), str(choice), bool(applied), job)
        if dedup in _logged:
            for d in reversed(_decisions):
                if (d["point"], str(d["key"]), str(d["choice"]),
                        d["applied"], d.get("job")) == dedup:
                    return d
            # aged out of the log: fall through and re-log
        _logged.add(dedup)
        d = {"point": point, "key": str(key), "choice": choice,
             "reason": reason, "applied": bool(applied)}
        if job is not None:
            d["job"] = job
        if predicted_ms is not None:
            d["predicted_ms"] = round(float(predicted_ms), 2)
        from dpark_tpu import trace
        if trace._PLANE is not None:
            # trace-plane twin (ISSUE 8): cost-model choices land on
            # the timeline next to the stages they steered
            trace.event("adapt.decision", "adapt", point=point,
                        choice=str(choice), applied=bool(applied))
        _decisions.append(d)
        if applied:
            _counters["steered"] += 1
        global _decisions_base
        if len(_decisions) > _LOG_CAP:
            drop = len(_decisions) - _LOG_CAP
            del _decisions[:drop]
            _decisions_base += drop
        return d


def log_position():
    with _lock:
        return _decisions_base + len(_decisions)


def begin_job():
    """Mark a job boundary: returns the current log position AND
    resets the decision de-dup epoch, so a job that takes the same
    steered choice as its predecessor still logs it (its
    record["adapt"] delta and the `steered` counter would otherwise
    silently undercount repeat steering).  Within one job the de-dup
    stands — a streamed stage consulting the store once per wave logs
    one decision, not hundreds.

    Only UNTAGGED entries clear: job-TAGGED de-dup tuples (resident
    service, ISSUE 9) already scope per job via the id in the tuple,
    and clearing them here would wipe a CONCURRENT job's epoch — its
    streamed stage would then re-log the same decision every wave.
    Tagged entries for long-gone jobs are pruned by rebuilding from
    the capped decision log once the set outgrows it."""
    with _lock:
        stale = {d for d in _logged if d[-1] is None}
        _logged.difference_update(stale)
        if len(_logged) > 4 * _LOG_CAP:
            live = {(d["point"], str(d["key"]), str(d["choice"]),
                     d["applied"], d.get("job")) for d in _decisions}
            _logged.intersection_update(live)
        return _decisions_base + len(_decisions)


def decisions_since(pos, job=None):
    """Decisions logged at or after `pos`.  With `job` set (resident
    service, ISSUE 9), only decisions tagged with that job id return —
    untagged decisions (made outside any slot thread) stay visible to
    every job, matching the single-job behavior."""
    with _lock:
        start = max(0, int(pos) - _decisions_base)
        out = [dict(d) for d in _decisions[start:]]
    if job is not None:
        out = [d for d in out if d.get("job") in (None, job)]
    return out


def summary():
    """The `adapt` section for bench artifacts / job records: mode,
    store location, hit/steer counters, persisted site-tail keys,
    recent decisions with predicted-vs-observed ms."""
    if enabled():
        _ensure_loaded()        # a fresh process reports STORED sites
    with _lock:
        return {"mode": mode(), "store": _store_path(),
                "store_hits": _counters["store_hits"],
                "store_misses": _counters["store_misses"],
                "steered": _counters["steered"],
                "recorded": _counters["recorded"],
                # per-site latency-tail keys the health plane has
                # persisted (ISSUE 14): the item-5 handoff's proof a
                # fresh process sees what earlier ones observed
                "sites": sorted(_agg["site"]),
                # persisted static program cost profiles (ledger
                # plane, ISSUE 15): the acceptance proof a fresh
                # process can price a program before running it
                "programs": sorted(_agg["prog"]),
                "decisions": [dict(d) for d in _decisions[-32:]]}


# ---------------------------------------------------------------------------
# stable cross-process identity for plan program keys
# ---------------------------------------------------------------------------

def stable_key(obj):
    """Hash an arbitrary program-key structure to a short id that is
    STABLE ACROSS PROCESSES: code objects hash by bytecode + consts
    (fuse.fn_key carries live code objects whose repr embeds a memory
    address), functions by their code, bytes by digest; the generic
    fallback strips ``at 0x...`` addresses from reprs."""
    import hashlib
    return hashlib.sha1(
        _stable_repr(obj).encode("utf-8", "replace")).hexdigest()[:16]


def _stable_repr(o, depth=0):
    import hashlib
    import re
    import types
    if depth > 12:
        return "..."
    if isinstance(o, types.CodeType):
        return "code(%s,%s,%s)" % (
            o.co_name, hashlib.sha1(o.co_code).hexdigest()[:12],
            _stable_repr(o.co_consts, depth + 1))
    if isinstance(o, types.FunctionType):
        return "fn(%s)" % _stable_repr(o.__code__, depth + 1)
    if isinstance(o, (bytes, bytearray)):
        return "b(%s)" % hashlib.sha1(bytes(o)).hexdigest()[:12]
    if isinstance(o, (tuple, list)):
        return "(%s)" % ",".join(_stable_repr(x, depth + 1) for x in o)
    if isinstance(o, dict):
        return "{%s}" % ",".join(
            "%s:%s" % (_stable_repr(k, depth + 1),
                       _stable_repr(v, depth + 1))
            for k, v in sorted(o.items(), key=lambda kv: repr(kv[0])))
    if isinstance(o, (str, int, float, bool)) or o is None:
        return repr(o)
    return re.sub(r" at 0x[0-9a-f]+", "", repr(o))


# ---------------------------------------------------------------------------
# decision point 1: wave budget (conf.stream_chunk_rows)
# ---------------------------------------------------------------------------

def _wb_key(row_bytes):
    return "rb%d" % int(row_bytes)


def record_wave_budget(row_bytes, budget, ok, source="stream"):
    """Persist the outcome of running (or failing) a wave budget for a
    row-width class.  Known-good budgets seed the next run; a failing
    budget makes the next run start BELOW the rung that OOM'd.
    Identical consecutive outcomes are not re-appended."""
    try:
        if not enabled() or not budget:
            return
        _ensure_loaded()
        key = _wb_key(row_bytes)
        with _lock:
            ent = _agg["wave_budget"].get(key)
            slot = "good" if ok else "bad"
            if ent is not None and ent.get(slot) == int(budget):
                return
        _append({"k": "wb", "key": key, "budget": int(budget),
                 "ok": bool(ok), "src": source})
    except Exception as e:
        logger.debug("record_wave_budget failed: %s", e)


def steer_wave_budget(base, row_bytes):
    """The effective auto wave budget: the store's last-known-good
    budget for this row-width class when it is SMALLER than the
    freshly derived base (a learned budget larger than base never
    applies — base is already the memory-derived ceiling).  With only
    a failing budget on record, start at half that rung.  Never
    steers outside DPARK_ADAPT=on."""
    try:
        if not steering():
            return base
        _ensure_loaded()
        key = _wb_key(row_bytes)
        with _lock:
            ent = _agg["wave_budget"].get(key)
        if ent is None:
            _counters["store_misses"] += 1
            return base
        _counters["store_hits"] += 1
        good, bad = ent.get("good"), ent.get("bad")
        cand = good if good else (max(64, bad // 2) if bad else None)
        if cand is None or cand >= base:
            return base
        _decide("wave_budget", key, cand,
                "seeded wave budget %d rows/device from the store "
                "(last known good for %s; derived base %d)"
                % (cand, key, base))
        return int(cand)
    except Exception as e:
        logger.debug("steer_wave_budget failed: %s", e)
        return base


def wave_budget_row_widths():
    """Row-width classes (ints, bytes/row) with stored budgets — the
    adapt-stale-hint lint rule compares these against the plan's
    actual columnar row width."""
    try:
        if not enabled():
            return set()
        _ensure_loaded()
        with _lock:
            return {int(k[2:]) for k in _agg["wave_budget"]
                    if k.startswith("rb")}
    except Exception:
        return set()


# ---------------------------------------------------------------------------
# decision point 2: device vs object path by predicted cost
# ---------------------------------------------------------------------------

def _stage_key(sig):
    return "%s|%s" % (sig[0], sig[1])


def choose_path(sig):
    """Cost-model path choice for an analyzable stage: given the plan
    signature (program id, shape class) from fuse.plan_adapt_signature,
    return a decision dict ({"choice": "object"|"device", "reason",
    "predicted_ms"}) when BOTH paths have recorded ms for this program
    class, else None (no history -> static behavior: the array path).
    The host must beat the device by conf.ADAPT_PATH_MARGIN to win —
    ties keep the device (its compile cost amortizes).  Observe mode
    logs the would-be choice (applied: false) and returns None."""
    try:
        if sig is None or not enabled():
            return None
        _ensure_loaded()
        key = _stage_key(sig)
        with _lock:
            ent = _agg["stage"].get(key)
        if ent is None:
            _counters["store_misses"] += 1
            return None
        d_ms, h_ms = ent.get("device_ms"), ent.get("host_ms")
        if d_ms is None or h_ms is None:
            _counters["store_misses"] += 1
            return None
        _counters["store_hits"] += 1
        margin = float(getattr(conf, "ADAPT_PATH_MARGIN", 0.8))
        if h_ms < d_ms * margin:
            choice, predicted = "object", h_ms
            reason = ("cost model: object path predicted cheaper "
                      "(host ~%.1fms vs device ~%.1fms observed for "
                      "this program class)" % (h_ms, d_ms))
        else:
            choice, predicted = "device", d_ms
            reason = ("cost model: array path confirmed (device "
                      "~%.1fms vs host ~%.1fms observed)"
                      % (d_ms, h_ms))
        if not steering():
            _decide("path", key, choice, reason, predicted_ms=predicted,
                    applied=False)
            return None
        d = _decide("path", key, choice, reason, predicted_ms=predicted)
        with _lock:
            _pending[key] = d
        return dict(d)
    except Exception as e:
        logger.debug("choose_path failed: %s", e)
        return None


def observe_path(sig, path, ms=None, error=False):
    """Record an observed stage run (path = "device" | "host", wall
    ms) for the plan signature, and complete any pending path decision
    with the observed outcome."""
    try:
        if sig is None or not enabled():
            return
        key = _stage_key(sig)
        rec = {"k": "stage", "key": key, "path": path}
        if error:
            rec["error"] = True
        else:
            rec["ms"] = round(float(ms), 2)
        _append(rec)
        with _lock:
            d = _pending.pop(key, None)
            if d is not None and not error:
                d["observed_ms"] = round(float(ms), 2)
    except Exception as e:
        logger.debug("observe_path failed: %s", e)


def stage_history():
    """Copy of the per-program stage aggregates (tests / debugging)."""
    _ensure_loaded()
    with _lock:
        return {k: dict(v) for k, v in _agg["stage"].items()}


# ---------------------------------------------------------------------------
# decision point 3: partition count re-planned on observed skew
# ---------------------------------------------------------------------------

def record_skew(site, rows, groups, max_group, parts):
    """Persist a bucket-histogram observation for a grouping site (the
    segment layout SegMapOp computes anyway): total rows, group count,
    the largest group's approximate size, and the reduce width it ran
    at."""
    try:
        if not enabled() or not site or not rows:
            return
        _append({"k": "skew", "key": str(site), "rows": int(rows),
                 "groups": int(groups), "max_group": int(max_group),
                 "parts": int(parts)})
    except Exception as e:
        logger.debug("record_skew failed: %s", e)


def suggest_partitions(site, default_n):
    """Reduce-side width for a combineByKey/groupByKey whose caller
    took the DEFAULT parallelism: when the last recorded histogram for
    this call site shows one dominant key group (max_group/rows >=
    conf.ADAPT_SKEW_FRAC), widen by conf.ADAPT_SKEW_WIDEN so the
    non-dominant keys spread thinner around the hot partition.
    Explicit user numSplits are never overridden (callers only consult
    this on the default path)."""
    try:
        if not enabled() or not site:
            return default_n
        _ensure_loaded()
        with _lock:
            ent = _agg["skew"].get(str(site))
        if ent is None or not ent.get("rows"):
            return default_n
        frac = ent["max_group"] / max(1, ent["rows"])
        if frac < float(getattr(conf, "ADAPT_SKEW_FRAC", 0.5)):
            return default_n
        _counters["store_hits"] += 1
        widened = max(default_n + 1, default_n * int(
            getattr(conf, "ADAPT_SKEW_WIDEN", 2)))
        reason = ("observed skew at %s: dominant group ~%d of %d rows "
                  "(%.0f%%) — widening the reduce side %d -> %d"
                  % (site, ent["max_group"], ent["rows"], frac * 100,
                     default_n, widened))
        if not steering():
            _decide("partitions", site, widened, reason, applied=False)
            return default_n
        _decide("partitions", site, widened, reason)
        return widened
    except Exception as e:
        logger.debug("suggest_partitions failed: %s", e)
        return default_n


# ---------------------------------------------------------------------------
# decision point 4: map-side combine priced from the combine ratio
# ---------------------------------------------------------------------------

def record_combine_ratio(site, rows_in, rows_out):
    """Persist an observed combine ratio (rows after map-side combine,
    or distinct groups, over input rows) for a grouping/combining call
    site."""
    try:
        if not enabled() or not site or not rows_in:
            return
        _append({"k": "combine", "key": str(site),
                 "rows_in": int(rows_in), "rows_out": int(rows_out)})
    except Exception as e:
        logger.debug("record_combine_ratio failed: %s", e)


def map_side_combine(site, kind):
    """Should the groupByKey aggregate rewrite apply map-side combine
    for this site?  True (the static default) without history; False
    when the OBSERVED combine ratio says pre-aggregation barely
    shrinks the exchange (ratio > conf.ADAPT_COMBINE_MAX_RATIO —
    nearly every key is distinct, so the combine pass costs a sort and
    saves no wire bytes).  Observe mode logs the would-be choice and
    keeps the static default."""
    try:
        if not enabled() or not site:
            return True
        _ensure_loaded()
        with _lock:
            ent = _agg["combine"].get(str(site))
        if ent is None or ent.get("ratio") is None:
            return True
        ratio = ent["ratio"]
        limit = float(getattr(conf, "ADAPT_COMBINE_MAX_RATIO", 0.6))
        if ratio <= limit:
            return True
        _counters["store_hits"] += 1
        reason = ("observed combine ratio %.2f > %.2f at %s: map-side "
                  "combine for %s priced off (exchange the raw rows; "
                  "the device segment path serves the aggregate)"
                  % (ratio, limit, site, kind))
        if not steering():
            _decide("map_combine", site, "off", reason, applied=False)
            return True
        _decide("map_combine", site, "off", reason)
        return False
    except Exception as e:
        logger.debug("map_side_combine failed: %s", e)
        return True


# ---------------------------------------------------------------------------
# decision point 5: pane-tree split points from observed pane costs
# ---------------------------------------------------------------------------

def record_pane_cost(site, mode, ms, panes):
    """Persist one observed per-tick windowed-emit wall (ms) for a
    pane stream signature under a pane strategy ("tree" = dyadic merge
    tree, "flat" = union all panes, "inv" = invertible O(1) update).
    Streams sample this ONCE per stream (median of post-warmup ticks),
    so the store sees one line per (stream shape, mode) per run."""
    try:
        if not enabled() or not site:
            return
        _append({"k": "pane", "key": str(site), "mode": str(mode),
                 "ms": round(float(ms), 2), "w": int(panes)})
    except Exception as e:
        logger.debug("record_pane_cost failed: %s", e)


def steer_pane_mode(site, panes, static_tree):
    """Split-point choice for a non-invertible pane window ("Partial
    Partial Aggregates": pick the decomposition by COST, not by
    shape): `static_tree` is the conf.STREAM_PANE_TREE_MIN default;
    with DPARK_ADAPT=on and BOTH strategies' per-tick costs on record
    for this stream signature, the observed-cheaper one wins (logged
    as a `pane_split` decision).  Observe mode logs the would-be
    choice and keeps the static default."""
    try:
        if not site or not enabled():
            return static_tree
        _ensure_loaded()
        with _lock:
            ent = _agg["pane"].get(str(site))
        if ent is None:
            _counters["store_misses"] += 1
            return static_tree
        tree_ms, flat_ms = ent.get("tree_ms"), ent.get("flat_ms")
        if tree_ms is None or flat_ms is None:
            _counters["store_misses"] += 1
            return static_tree
        _counters["store_hits"] += 1
        use_tree = tree_ms <= flat_ms
        reason = ("observed pane costs for w=%d: tree ~%.1fms vs flat "
                  "~%.1fms per tick — %s merge"
                  % (panes, tree_ms, flat_ms,
                     "dyadic-tree" if use_tree else "flat"))
        if not steering():
            if use_tree != static_tree:
                _decide("pane_split", site,
                        "tree" if use_tree else "flat", reason,
                        applied=False)
            return static_tree
        _decide("pane_split", site, "tree" if use_tree else "flat",
                reason, applied=(use_tree != static_tree))
        return use_tree
    except Exception as e:
        logger.debug("steer_pane_mode failed: %s", e)
        return static_tree


def pane_history():
    """Copy of the per-stream pane cost aggregates (tests / debug)."""
    _ensure_loaded()
    with _lock:
        return {k: dict(v) for k, v in _agg["pane"].items()}


# ---------------------------------------------------------------------------
# per-site latency tails (health plane, ISSUE 14 — the item-5 handoff)
# ---------------------------------------------------------------------------

def record_site_tail(site, digest):
    """Persist one per-site latency-sketch DELTA (the health plane's
    log-bucketed histogram shape).  The store folds deltas by bucket
    addition, so repeated persists from any process accumulate into
    one distribution per site — the observed straggler/tail data
    ROADMAP item 5's adaptive coder reads back."""
    try:
        if not enabled() or not site or not digest:
            return
        _append({"k": "site", "key": str(site),
                 "digest": dict(digest)})
    except Exception as e:
        logger.debug("record_site_tail failed: %s", e)


def record_program_cost(key, profile):
    """Persist one static program cost profile (ledger plane, ISSUE
    15): flops / bytes-accessed / arg-bytes (and, when captured via
    the compiled path, measured peak-HBM bytes) keyed by the
    cross-process-stable plan signature "progid|shapeclass" — the
    pricing prior ROADMAP items 2/3 read before a program's first
    observed run."""
    try:
        if not enabled() or not key or not profile:
            return
        _append({"k": "prog", "key": str(key),
                 "profile": dict(profile)})
    except Exception as e:
        logger.debug("record_program_cost failed: %s", e)


def program_cost(key):
    """The persisted cost profile for one plan signature, or None."""
    try:
        if not enabled():
            return None
        _ensure_loaded()
        with _lock:
            ent = _agg["prog"].get(str(key))
            return dict(ent) if ent is not None else None
    except Exception:
        return None


def program_costs():
    """{signature: profile} — every persisted program cost profile.
    A fresh process calling this prices programs it never ran."""
    try:
        if not enabled():
            return {}
        _ensure_loaded()
        with _lock:
            return {k: dict(v) for k, v in _agg["prog"].items()}
    except Exception:
        return {}


def record_reuse(key, hits=0, misses=0, partials=0):
    """Persist one result-cache probe outcome (shared-computation
    plane, ISSUE 18) keyed by the cache entry key: the hit-rate
    profile the disk tier's boot preload ranks entries by."""
    try:
        if not enabled() or not key:
            return
        if not (hits or misses or partials):
            return
        _append({"k": "reuse", "key": str(key), "hits": int(hits),
                 "misses": int(misses), "partials": int(partials)})
    except Exception as e:
        logger.debug("record_reuse failed: %s", e)


def reuse_profiles():
    """{cache key: {hits, misses, partials}} — every persisted
    result-cache hit-rate profile.  A fresh process calling this
    ranks entries it never served."""
    try:
        if not enabled():
            return {}
        _ensure_loaded()
        with _lock:
            return {k: dict(v) for k, v in _agg["reuse"].items()}
    except Exception:
        return {}


def site_tails():
    """{site: digest} — every persisted per-site latency sketch
    (folded across all recorded deltas).  A fresh process calling
    this reads back what earlier processes observed."""
    try:
        if not enabled():
            return {}
        _ensure_loaded()
        with _lock:
            return {k: dict(v) for k, v in _agg["site"].items()}
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# decision point 6: per-exchange (k, m) from recorded peer tails
# (ISSUE 19 tentpole 1 — the ROADMAP item-4 consumer of the site tails)
# ---------------------------------------------------------------------------

def choose_shuffle_code(site, static_spec=None):
    """Price the erasure code for one exchange (identified by its
    shuffle call site) from the store: the peers recorded serving it
    ("xch" records), their fetch-tail sketches ("site" records, keyed
    fetch.bucket:<peer>), and their accumulated decode outcomes.
    Returns the chosen spec string when the policy should steer this
    run, else None (no history / policy off / observe mode — the
    static DPARK_SHUFFLE_CODE stands).  Every actionable choice logs
    as decision point "code"; a steered one stays pending until
    observe_exchange() attaches the observed fetch wall."""
    try:
        from dpark_tpu import coding
        if not enabled() or not site \
                or not getattr(conf, "CODE_ADAPT", False):
            return None
        _ensure_loaded()
        with _lock:
            ent = _agg["xch"].get(str(site))
        if ent is None or not ent.get("peers"):
            _counters["store_misses"] += 1
            return None
        peers = sorted(ent["peers"])
        all_tails = site_tails()
        tails = {p: all_tails.get("fetch.bucket:%s" % p)
                 for p in peers}
        spec, reason, predicted = coding.choose_code(
            peers, tails, ent["peers"], static_spec)
        if spec is None:
            _counters["store_misses"] += 1
            return None
        _counters["store_hits"] += 1
        if not steering():
            _decide("code", site, spec, reason,
                    predicted_ms=predicted, applied=False)
            coding.record_choice(str(site), spec, reason, False,
                                 predicted)
            return None
        d = _decide("code", site, spec, reason,
                    predicted_ms=predicted)
        coding.record_choice(str(site), spec, reason, True, predicted)
        with _lock:
            _pending["code|%s" % site] = d
        return spec
    except Exception as e:
        logger.debug("choose_shuffle_code failed: %s", e)
        return None


def observe_exchange(site, peers, fetch_ms=None):
    """Persist which peers served one exchange this run — `peers` is
    {peer: {"fetches"/"repair"/"straggler_win"/"decode_failures": n}}
    — and complete a pending code decision with the observed fetch
    wall, so the policy is graded by its own telemetry (predicted vs
    observed ms on the job record)."""
    try:
        if not enabled() or not site or not peers:
            return
        rec_peers = {}
        for p, counts in peers.items():
            cc = {k: int(v) for k, v in (counts or {}).items()
                  if isinstance(v, (int, float)) and v}
            if cc:
                rec_peers[str(p)] = cc
        if not rec_peers:
            return
        rec = {"k": "xch", "key": str(site), "peers": rec_peers}
        if fetch_ms is not None:
            rec["fetch_ms"] = round(float(fetch_ms), 2)
        _append(rec)
        with _lock:
            d = _pending.pop("code|%s" % site, None)
        if d is not None and fetch_ms is not None:
            d["observed_ms"] = round(float(fetch_ms), 2)
    except Exception as e:
        logger.debug("observe_exchange failed: %s", e)


def exchange_profiles():
    """{site: {"peers": {peer: counts}, "n", "fetch_ms"}} — every
    persisted per-exchange peer profile (tests / debugging)."""
    try:
        if not enabled():
            return {}
        _ensure_loaded()
        with _lock:
            return {k: {"peers": {p: dict(c)
                                  for p, c in v.get("peers",
                                                    {}).items()},
                        "n": v.get("n", 0),
                        "fetch_ms": v.get("fetch_ms")}
                    for k, v in _agg["xch"].items()}
    except Exception:
        return {}


# ---------------------------------------------------------------------------
# decision point 7: mid-job re-plan of a skewed reduce side
# ---------------------------------------------------------------------------

def note_replan(site, parts, salt, frac, applied):
    """Log the mid-job re-plan decision (decision point 7) taken — or,
    in observe mode, declined — by the scheduler at a stage boundary,
    and persist the replan record so the NEXT run of this call site
    salts its partitioner at plan time (suggest_salt) instead of
    paying the re-split again.  Returns the reason string the
    scheduler records as the consumer stage's `replan_reason`."""
    reason = ("map-side bucket histogram at %s: dominant bucket "
              "%.0f%% of exchange bytes across width %d — re-keying "
              "the reduce side through a salted re-split (salt=%d), "
              "no map task recomputed" % (site, frac * 100, parts,
                                          salt))
    try:
        if not enabled() or not site:
            return reason
        _decide("replan", site, "resplit(salt=%d)" % int(salt),
                reason, applied=bool(applied))
        _append({"k": "replan", "key": str(site), "parts": int(parts),
                 "salt": int(salt), "frac": round(float(frac), 4)})
    except Exception as e:
        logger.debug("note_replan failed: %s", e)
    return reason


def suggest_salt(site):
    """Plan-time twin of the mid-job re-plan: a recorded re-plan for
    this call site returns its salt so combineByKey builds the salted
    partitioner up front — the map side then writes balanced buckets
    and the mid-job probe finds nothing to re-split (the "skip
    already-replanned shapes" contract).  0 = no salt / not steering."""
    try:
        if not enabled() or not site \
                or not getattr(conf, "REPLAN", False):
            return 0
        _ensure_loaded()
        with _lock:
            ent = _agg["replan"].get(str(site))
        if not ent or not ent.get("salt"):
            return 0
        _counters["store_hits"] += 1
        reason = ("recorded re-plan at %s (dominant bucket %.0f%% of "
                  "exchange bytes): salting the partitioner at plan "
                  "time" % (site, ent.get("frac", 0.0) * 100))
        if not steering():
            _decide("replan", site, "salt=%d" % ent["salt"], reason,
                    applied=False)
            return 0
        _decide("replan", site, "salt=%d" % ent["salt"], reason)
        return int(ent["salt"])
    except Exception as e:
        logger.debug("suggest_salt failed: %s", e)
        return 0


def replan_profiles():
    """{site: {"parts", "salt", "frac"}} — every persisted re-plan
    record (tests / debugging)."""
    try:
        if not enabled():
            return {}
        _ensure_loaded()
        with _lock:
            return {k: dict(v) for k, v in _agg["replan"].items()}
    except Exception:
        return {}
