"""Partition cache for rdd.cache() / persist().

Reference parity: dpark/cache.py — per-process memory cache + disk cache of
computed partitions, with a CacheTracker recording locations so the
scheduler prefers cached hosts (SURVEY.md sections 2.1 and 3.5).

Single-host design: memory dict in each process + a disk tier in the shared
workdir, so a partition cached by one worker process is readable by all.
The TPU backend keeps stage outputs HBM-resident instead (backend/tpu/).
"""

import os
import pickle
import threading

from dpark_tpu.utils import atomic_file, compress, decompress

# device-resident caches register an eviction callback here so
# rdd.unpersist() reaches HBM as well as the host tiers
DEVICE_CACHES = {}


class Cache:
    def __init__(self, workdir):
        self.memory = {}
        self.disk_dir = os.path.join(workdir, "cache")
        self.lock = threading.Lock()

    def _disk_path(self, key):
        rdd_id, split_index = key
        return os.path.join(self.disk_dir, "%d_%d" % (rdd_id, split_index))

    def get(self, key):
        with self.lock:
            if key in self.memory:
                return self.memory[key]
        path = self._disk_path(key)
        if os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    items = pickle.loads(decompress(f.read()))
            except (OSError, pickle.PickleError):
                return None
            with self.lock:
                self.memory[key] = items
            return items
        return None

    def put(self, key, items, disk=True):
        items = list(items)
        with self.lock:
            self.memory[key] = items
        if disk:
            try:
                with atomic_file(self._disk_path(key)) as f:
                    f.write(compress(pickle.dumps(items, -1)))
            except OSError:
                pass
        return items

    def drop(self, rdd_id, n_splits):
        for i in range(n_splits):
            key = (rdd_id, i)
            with self.lock:
                self.memory.pop(key, None)
            try:
                os.unlink(self._disk_path(key))
            except OSError:
                pass


def get_or_compute(rdd, split):
    """iterator() hook: consult the cache before compute (SURVEY 3.5)."""
    from dpark_tpu.env import env
    key = (rdd.id, split.index)
    cached = env.cache.get(key)
    if cached is not None:
        return iter(cached)
    items = env.cache.put(key, rdd.compute(split))
    return iter(items)
