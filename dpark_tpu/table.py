"""Table DSL: schema'd RDDs of named rows with expression select/where,
grouped aggregation, sort and joins.

Reference parity: dpark/table.py (SURVEY.md section 2.3) — a TableRDD wraps
an RDD of namedtuple rows; string expressions are compiled with eval
against the row's fields; groupBy supports sum/count/avg/min/max and
approximate distinct count (HyperLogLog, dpark/hyperloglog.py analog in
dpark_tpu/hyperloglog.py).  Exact method shapes follow this framework's
conventions; the surface (select/where/groupBy/sort/top/join/collect) is
the reference's.

Columnar query plane (ISSUE 13): every DSL call ALSO lowers into a
logical plan (dpark_tpu/query/) when its source is a columnar scan
(tabular part files, parallelize slices) and its expressions parse.
Actions (collect/take/count/top) then ask the rule-driven physical
planner to compile the plan onto the device path — pruned vectorized
scans, device exchanges for group-by/join, egest-side result finishing
— and fall back to the eager host RDD chain below (which is always
built, lazily, alongside) whenever any operator declines; the decline
reasons ride `_query_fallbacks` for the `table-host-fallback` lint
rule and the planner's decision log.  `DPARK_QUERY=0` pins every
action to the host chain (the pre-plan behavior, and the bench A/B's
baseline side).
"""

import re
import time
from collections import namedtuple

from dpark_tpu.utils.log import get_logger

logger = get_logger("table")

_AGG_RE = re.compile(
    r"^\s*(count|sum|avg|min|max|adcount|first|group_concat)\s*"
    r"\(\s*(.*?)\s*\)\s*$", re.I)
_AS_RE = re.compile(r"^(.*?)\s+as\s+(\w+)\s*$", re.I)


def _compile_expr(expr, fields):
    """Compile a string expression over row fields into row -> value.

    SECURITY NOTE: expression strings are CODE, at the same trust level
    as a lambda passed to .map() — the restricted-builtins dict below
    blocks accidents, not adversaries (attribute traversal escapes any
    eval sandbox).  Never feed untrusted input to ctx.sql / where /
    select; this matches the reference, whose table layer also evals
    user expressions (dpark/table.py [L])."""
    code = compile(expr, "<table:%s>" % expr, "eval")

    def run(row):
        env = dict(zip(fields, row))
        return eval(code, {"__builtins__": _SAFE_BUILTINS}, env)
    run.expr = expr
    return run


_SAFE_BUILTINS = {
    "abs": abs, "min": min, "max": max, "len": len, "round": round,
    "int": int, "float": float, "str": str, "bool": bool, "sum": sum,
    "True": True, "False": False, "None": None,
}


def _branchless_min(a, b):
    """min that the device tracer can see through: `a if a <= b else b`
    forces a concrete bool, so group-by min/max would demote the whole
    aggregate shuffle to the host object path (VERDICT r3 #8 — the
    Table DSL must inherit the core's device speed).  Host objects
    (strings, dates) keep exact Python comparison semantics."""
    try:
        import jax
        if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
            import jax.numpy as jnp
            # jnp.where(a <= b, a, b), NOT jnp.minimum: minimum
            # propagates NaN where the host comparison returns b —
            # device and host float min must agree on NaN rows
            # (ADVICE r4)
            return jnp.where(a <= b, a, b)
    except ImportError:
        pass
    return a if a <= b else b


def _branchless_max(a, b):
    try:
        import jax
        if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
            import jax.numpy as jnp
            # mirror the host's `a if a >= b else b` NaN behavior
            # (ADVICE r4; see _branchless_min)
            return jnp.where(a >= b, a, b)
    except ImportError:
        pass
    return a if a >= b else b


def _branchless_div(a, b):
    """avg's finalize without a concrete-bool branch (device rows only
    exist for observed keys, so the count is never 0 there; the host
    path keeps the divide-by-zero -> None convention)."""
    try:
        import jax
        if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
            return a / b
    except ImportError:
        pass
    return a / b if b else None


class _Agg:
    """One aggregate column: (create, merge, combine, finalize)."""

    def __init__(self, func, arg_fn, name):
        self.func = func
        self.arg_fn = arg_fn
        self.name = name

    def create(self, row):
        f = self.func
        if f == "count":
            if self.arg_fn is None:
                return 1
            return 0 if self.arg_fn(row) is None else 1
        v = self.arg_fn(row)
        if f == "sum":
            return v
        if f == "avg":
            return (v, 1)
        if f in ("min", "max", "first"):
            return v
        if f == "adcount":
            from dpark_tpu.hyperloglog import HyperLogLog
            h = HyperLogLog()
            h.add(v)
            return h
        if f == "group_concat":
            return [v]
        raise ValueError("unknown aggregate %r" % f)

    def merge(self, acc, row):
        return self.combine(acc, self.create(row))

    def combine(self, a, b):
        f = self.func
        if f in ("count", "sum"):
            return a + b
        if f == "avg":
            return (a[0] + b[0], a[1] + b[1])
        if f == "min":
            return _branchless_min(a, b)
        if f == "max":
            return _branchless_max(a, b)
        if f == "first":
            return a
        if f == "adcount":
            a.update(b)
            return a
        if f == "group_concat":
            a.extend(b)
            return a
        raise ValueError(f)

    def finalize(self, acc):
        f = self.func
        if f == "avg":
            return _branchless_div(acc[0], acc[1])
        if f == "adcount":
            return len(acc)
        if f == "group_concat":
            return ",".join(str(x) for x in acc)
        return acc


def _parse_column(col, fields, index):
    """'expr as name' | 'agg(expr)' | 'name' -> (name, fn_or_agg)."""
    name = None
    m = _AS_RE.match(col)
    if m:
        col, name = m.group(1), m.group(2)
    m = _AGG_RE.match(col)
    if m:
        func, arg = m.group(1).lower(), m.group(2)
        arg_fn = None
        if arg and arg != "*":
            arg_fn = _compile_expr(arg, fields)
        agg_name = name or ("%s_%s" % (func, arg.replace("*", "all")
                                       .replace("(", "").replace(")", "")
                                       .strip() or "all"))
        agg_name = re.sub(r"\W+", "_", agg_name).strip("_") or \
            ("agg%d" % index)
        return agg_name, _Agg(func, arg_fn, agg_name)
    if col in fields:
        return name or col, _compile_expr(col, fields)
    return (name or ("col%d" % index)), _compile_expr(col, fields)


class _UDA:
    """User-defined aggregate marker for groupBy: a traceable
    per-group function over one argument column's value list.  On the
    host path the values fold as a Python list; on the device plan the
    same function rides the SegMapOp segmented apply (admission:
    traceable + padding-invariant, see fuse.classify_seg_map)."""

    def __init__(self, expr, fn, name=None):
        self.expr = expr
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "uda")


def uda(expr, fn, name=None):
    """A groupBy aggregate column computed by `fn(values_list)` over
    the per-group values of `expr` — e.g.
    ``t.groupBy("k", uda("v", lambda vs: sum(x * x for x in vs),
    "sumsq"))``."""
    return _UDA(expr, fn, name)


class TableRDD:
    def __init__(self, rdd, fields, name="table", plan=None,
                 plan_fallbacks=None):
        if isinstance(fields, str):
            fields = [f.strip() for f in fields.replace(",", " ").split()]
        self.rdd = rdd
        self.fields = list(fields)
        self.name = name
        self._row_type = namedtuple("Row", self.fields, rename=True)
        self._plan_fallbacks = list(plan_fallbacks or ())
        self.plan = plan if plan is not None else self._scan_plan()
        self._planned_q = False     # False = not planned yet
        self._reuse = True          # result-cache probe allowed

    # -- query-plane lowering -------------------------------------------
    def _scan_plan(self):
        """A Scan node when this table's source is columnar (tabular
        part files / driver-resident parallelize slices), else None —
        the host chain then serves every action."""
        try:
            from dpark_tpu.query.logical import Scan
            from dpark_tpu.rdd import ParallelCollection
            from dpark_tpu.tabular import TabularRDD
            if isinstance(self.rdd, TabularRDD):
                if list(self.fields) == list(self.rdd.wanted):
                    return Scan(self.rdd, self.fields, self.name)
                self._note_fallback(
                    "scan", "table fields rename the tabular columns")
            elif isinstance(self.rdd, ParallelCollection) \
                    and self.rdd._slices is not None:
                return Scan(self.rdd, self.fields, self.name)
        except Exception as e:
            logger.debug("no scan plan: %s", e)
        return None

    def _note_fallback(self, op, reason):
        self._plan_fallbacks.append({"op": op, "reason": reason})

    def _qexprs(self, texts):
        """Compile expression texts for the logical plan.  Returns
        (exprs, None) or (None, reason) — the caller threads the
        reason into the DERIVED table's fallback provenance (mutating
        self here would stamp one query's decline onto every sibling
        query built from the same base table)."""
        from dpark_tpu.query.exprs import compile_expr
        out = []
        for t in texts:
            ce = compile_expr(t, self.fields)
            if ce.parse_error:
                return None, ce.parse_error
            out.append(ce)
        return out, None

    def _derive(self, rdd, fields, plan, op=None, reason=None):
        """A downstream TableRDD carrying plan + fallback provenance."""
        fb = list(self._plan_fallbacks)
        if plan is None and reason is not None:
            fb.append({"op": op or "plan", "reason": reason})
        return TableRDD(rdd, fields, self.name, plan=plan,
                        plan_fallbacks=fb)

    def _planned(self):
        """The PlannedQuery serving this table's actions, or None (host
        chain).  Planned once; the physical RDD pipeline and scan
        results are reused across repeated actions — like any cached
        RDD lineage."""
        if self._planned_q is not False:
            return self._planned_q
        self._planned_q = None
        from dpark_tpu import conf
        if not getattr(conf, "QUERY_PLAN", True) or self.plan is None:
            if self.plan is None and self._plan_fallbacks:
                self.rdd._query_fallbacks = list(self._plan_fallbacks)
            return None
        try:
            from dpark_tpu.query.planner import plan_query
            pq = plan_query(self.plan, self.rdd.ctx,
                            reuse=self._reuse)
        except Exception as e:
            logger.debug("query planning unavailable: %s", e)
            return None
        if pq.ok:
            self._planned_q = pq
        else:
            # host path serves the query; the planner's reasons ride
            # the lineage for the table-host-fallback lint rule (the
            # pre-flight twin of the runtime fallback_reason)
            self.rdd._query_fallbacks = (list(self._plan_fallbacks)
                                         + list(pq.fallbacks))
            self._host_sig = pq.adapt_sig
        return self._planned_q

    def _host_observe(self, t0):
        """Feed the cost model the host side's observed wall ms when a
        priced/declined plan ran the object path (adapt decision
        point 2 at query granularity)."""
        sig = getattr(self, "_host_sig", None)
        if sig is None:
            return
        try:
            from dpark_tpu import adapt
            adapt.observe_path(sig, "host", (time.time() - t0) * 1e3)
        except Exception:
            pass

    def shared(self, flag=True):
        """Per-QUERY result-cache opt-out: ``t.shared(False).collect()``
        neither probes nor stores into the shared-computation plane
        (resultcache.py) for this table's actions.  Tenant-wide
        opt-out lives on the JobServer (``resultcache.opt_out``);
        this is the query-granularity escape hatch.  Call it LAST —
        derived tables (select/where/...) start back at the
        default."""
        self._reuse = bool(flag)
        self._planned_q = False     # re-plan under the new setting
        return self

    def explain(self):
        """The logical plan + every planner rule decision (device or
        host, with reasons) — '' when no plan lowered."""
        pq = self._planned()
        if pq is not None:
            return pq.explain()
        lines = ["plan: host object path"]
        for f in self._plan_fallbacks:
            lines.append("  [%s] %s" % (f["op"], f["reason"]))
        return "\n".join(lines)

    # -- basic relational ops -------------------------------------------
    def select(self, *cols):
        cols = _split_cols(cols)
        parsed = [_parse_column(c, self.fields, i)
                  for i, c in enumerate(cols)]
        if any(isinstance(fn, _Agg) for _, fn in parsed):
            return self._aggregate_all(parsed)
        names = [n for n, _ in parsed]
        fns = [fn for _, fn in parsed]
        out = self.rdd.map(_SelectFn(fns))
        plan = None
        err = None
        if self.plan is not None:
            from dpark_tpu.query.logical import Project
            ces, err = self._qexprs([fn.expr for fn in fns])
            if ces is not None:
                plan = Project(self.plan, list(zip(names, ces)))
        return self._derive(out, names, plan, op="select", reason=err)

    def where(self, *conditions):
        texts = _split_cols(conditions)
        conds = [_compile_expr(c, self.fields) for c in texts]
        out = self.rdd.filter(_WhereFn(conds))
        plan = None
        err = None
        if self.plan is not None:
            from dpark_tpu.query.logical import Filter
            ces, err = self._qexprs(texts)
            if ces is not None:
                plan = Filter(self.plan, ces)
        return self._derive(out, self.fields, plan, op="where",
                            reason=err)

    filter = where

    def groupBy(self, keys, *aggs, **named_aggs):
        key_cols = _split_cols((keys,) if isinstance(keys, str) else keys)
        key_fns = [_compile_expr(k, self.fields) for k in key_cols]
        udas = [a for a in aggs if isinstance(a, _UDA)]
        if udas:
            return self._group_uda(key_cols, key_fns, aggs, named_aggs)
        parsed = [_parse_column(a, self.fields, i)
                  for i, a in enumerate(_split_cols(aggs))]
        for name, expr in sorted(named_aggs.items()):
            n, fn = _parse_column(expr, self.fields, 0)
            parsed.append((name, fn))
        for n, fn in parsed:
            if not isinstance(fn, _Agg):
                raise ValueError("groupBy columns must be aggregates: %r"
                                 % n)
        aggs_only = [fn for _, fn in parsed]
        keyed = self.rdd.map(_PairKeyFn(key_fns))
        combined = keyed.combineByKey(
            _AggCreate(aggs_only), _AggMerge(aggs_only),
            _AggCombine(aggs_only))
        out = combined.map(_AggFinalize(aggs_only, len(key_cols)))
        names = [re.sub(r"\W+", "_", k).strip("_") or ("k%d" % i)
                 for i, k in enumerate(key_cols)]
        names += [n for n, _ in parsed]
        plan, err = self._group_plan(key_cols, names[:len(key_cols)],
                                     parsed)
        return self._derive(out, names, plan, op="group-agg",
                            reason=err)

    def _group_plan(self, key_cols, key_names, parsed):
        """(GroupAgg node, None) or (None, decline reason)."""
        if self.plan is None:
            return None, None
        from dpark_tpu.query.logical import GroupAgg
        kces, err = self._qexprs(key_cols)
        if kces is None:
            return None, err
        agg_specs = []
        for name, agg in parsed:
            arg_ce = None
            if agg.arg_fn is not None:
                ces, err = self._qexprs([agg.arg_fn.expr])
                if ces is None:
                    return None, err
                arg_ce = ces[0]
            elif agg.func != "count":
                return None, ("aggregate %s(*) needs an argument "
                              "column for the device plan" % agg.func)
            agg_specs.append((name, agg.func, arg_ce, None))
        return GroupAgg(self.plan, list(zip(key_names, kces)),
                        agg_specs), None

    def _group_uda(self, key_cols, key_fns, aggs, named_aggs):
        """groupBy with a user-defined aggregate: the per-group value
        list of ONE argument column folds through fn(values) — host
        via groupByKey().mapValues, device via the SegMapOp segmented
        apply over the same graph."""
        if named_aggs or len(aggs) != 1:
            raise ValueError("a uda() must be the only groupBy "
                             "aggregate")
        (u,) = aggs
        arg_fn = _compile_expr(u.expr, self.fields)
        keyed = self.rdd.map(_UDAPairFn(key_fns, arg_fn))
        out = keyed.groupByKey().mapValues(u.fn) \
            .map(_UDAFlatten(len(key_cols)))
        names = [re.sub(r"\W+", "_", k).strip("_") or ("k%d" % i)
                 for i, k in enumerate(key_cols)]
        names += [u.name]
        plan = None
        err = None
        if self.plan is not None:
            from dpark_tpu.query.logical import GroupAgg
            kces, e1 = self._qexprs(key_cols)
            aces, e2 = self._qexprs([u.expr])
            err = e1 or e2
            if kces is not None and aces is not None:
                plan = GroupAgg(
                    self.plan,
                    list(zip(names[:len(key_cols)], kces)),
                    [(u.name, "uda", aces[0], u.fn)])
        return self._derive(out, names, plan, op="group-agg",
                            reason=err)

    def _aggregate_all(self, parsed):
        aggs = [fn for _, fn in parsed]
        for n, fn in parsed:
            if not isinstance(fn, _Agg):
                raise ValueError("mixing aggregates with plain columns "
                                 "requires groupBy")
        zero = None
        create, combine = _AggCreate(aggs), _AggCombine(aggs)
        parts = [p for p in self.rdd.ctx.runJob(
            self.rdd, _AggPartition(aggs)) if p is not None]
        if parts:
            acc = parts[0]
            for p in parts[1:]:
                acc = combine(acc, p)
            row = tuple(a.finalize(v) for a, v in zip(aggs, acc))
        else:
            row = tuple(None for _ in aggs)
        out = self.rdd.ctx.parallelize([row], 1)
        return TableRDD(out, [n for n, _ in parsed], self.name)

    def sort(self, key, reverse=False, numSplits=None):
        texts = _split_cols((key,) if isinstance(key, str) else key)
        fns = [_compile_expr(k, self.fields) for k in texts]
        out = self.rdd.sort(key=_GroupKeyFn(fns), reverse=reverse,
                            numSplits=numSplits)
        plan = None
        err = None
        if self.plan is not None:
            from dpark_tpu.query.logical import Sort
            ces, err = self._qexprs(texts)
            if ces is not None:
                plan = Sort(self.plan, ces, reverse=reverse)
        return self._derive(out, self.fields, plan, op="sort",
                            reason=err)

    def top(self, n=10, key=None, reverse=False):
        if key is None:
            key_fn = None
        else:
            fns = [_compile_expr(k, self.fields)
                   for k in _split_cols((key,) if isinstance(key, str)
                                        else key)]
            key_fn = _GroupKeyFn(fns)
        rows = self._plan_rows()
        if rows is not None:
            import heapq
            pick = heapq.nsmallest if reverse else heapq.nlargest
            return [self._row_type(*r) for r in pick(n, rows, key_fn)]
        t0 = time.time()
        out = [self._row_type(*r)
               for r in self.rdd.top(n, key=key_fn, reverse=reverse)]
        self._host_observe(t0)
        return out

    def join(self, other, on, numSplits=None):
        """Equi-join on a column name present in both tables."""
        if on not in self.fields or on not in other.fields:
            raise ValueError("join column %r must be a plain field of "
                             "both tables" % on)
        li, ri = self.fields.index(on), other.fields.index(on)
        lf = _compile_expr(on, self.fields)
        rf = _compile_expr(on, other.fields)
        left = self.rdd.map(_JoinKeyFn(lf))
        right = other.rdd.map(_JoinKeyFn(rf))
        joined = left.join(right, numSplits)
        out = joined.map(_JoinMerge(li, ri))
        fields = ([on] + [f for f in self.fields if f != on]
                  + [f if f not in self.fields else other.name + "_" + f
                     for f in other.fields if f != on])
        # ensure uniqueness, tracking which source column each output
        # name came from (the plan's join column map)
        srcs = ([("on", on)]
                + [("l", f) for f in self.fields if f != on]
                + [("r", f) for f in other.fields if f != on])
        seen, uniq = set(), []
        for f in fields:
            while f in seen:
                f = f + "_"
            seen.add(f)
            uniq.append(f)
        plan = None
        if self.plan is not None and other.plan is not None:
            from dpark_tpu.query.logical import Join
            colmap = [(out_name, side, src) for out_name, (side, src)
                      in zip(uniq, srcs)]
            plan = Join(self.plan, other.plan, on, uniq)
            plan.colmap = colmap
            return self._derive(out, uniq, plan)
        reason = None
        if self.plan is not None and other.plan is None:
            reason = ("join input %r has no columnar plan"
                      % other.name)
        return self._derive(out, uniq, None, op="join", reason=reason)

    # -- actions ---------------------------------------------------------
    def _plan_call(self, method, *args):
        """(result, served) via the physical plan; (None, False) means
        the host path serves.  EVERY plan action funnels through here
        so a run-time plan failure (mixed-type column, missing file)
        records its reason on the lineage for the table-host-fallback
        lint rule regardless of which action tripped it."""
        pq = self._planned()
        if pq is None:
            return None, False
        try:
            return getattr(pq, method)(*args), True
        except Exception as e:
            # the host chain is always correct — serve from it and
            # record why
            logger.warning("query plan failed at run time (%s); "
                           "host path", e)
            self._note_fallback("run", "plan execution failed: %s"
                                % str(e)[:160])
            self._planned_q = None
            self.rdd._query_fallbacks = list(self._plan_fallbacks)
            return None, False

    def _plan_rows(self):
        """Rows via the physical plan, or None (host path serves)."""
        rows, served = self._plan_call("rows")
        return rows if served else None

    def collect(self):
        rows = self._plan_rows()
        if rows is not None:
            return [self._row_type(*r) for r in rows]
        t0 = time.time()
        out = [self._row_type(*r) if isinstance(r, tuple)
               else self._row_type(r) for r in self.rdd.collect()]
        self._host_observe(t0)
        return out

    def take(self, n):
        rows = self._plan_rows()
        if rows is not None:
            return [self._row_type(*r) for r in rows[:n]]
        return [self._row_type(*r) for r in self.rdd.take(n)]

    def count(self):
        got, served = self._plan_call("count")
        if served:
            return got
        t0 = time.time()
        out = self.rdd.count()
        self._host_observe(t0)
        return out

    def save(self, path):
        return self.rdd.saveAsCSVFile(path)

    def indexBy(self, key):
        fn = _compile_expr(key, self.fields)
        return self.rdd.map(_JoinKeyFn(fn))

    def __repr__(self):
        return "<TableRDD %s(%s)>" % (self.name, ", ".join(self.fields))


_SQL_RE = re.compile(
    r"^\s*select\s+(?P<cols>.+?)\s+from\s+(?P<table>\w+)"
    r"(?:\s+join\s+(?P<jtable>\w+)\s+on\s+(?P<jon>.+?))?"
    r"(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+group\s+by\s+(?P<group>.+?))?"
    r"(?:\s+having\s+(?P<having>.+?))?"
    r"(?:\s+order\s+by\s+(?P<order>.+?)(?P<dir>\s+(?:asc|desc))?)?"
    r"(?:\s+limit\s+(?P<limit>\d+))?\s*$",
    re.I | re.S)

_JOIN_ON_RE = re.compile(
    r"^\s*(?:\w+\s*\.\s*)?(\w+)\s*(?:=\s*(?:\w+\s*\.\s*)?(\w+)\s*)?$")

# an aggregate CALL embedded in a larger expression (one paren-nesting
# level in the argument, e.g. avg(abs(x)))
_AGG_CALL_RE = re.compile(
    r"\b(count|sum|avg|min|max|adcount|first|group_concat)\s*"
    r"\(([^()]*(?:\([^()]*\)[^()]*)*)\)", re.I)


def _sub_aggs(expr, add_agg):
    """Replace every aggregate call in `expr` with the column name
    `add_agg(call_text)` returns.  Enables aggregate EXPRESSIONS in
    SELECT and HAVING (``sum(v) / count(*) as r``, ``having count(*)
    > 3``): the calls compute in the grouped aggregation, the
    surrounding expression evaluates over the aggregated row.
    Returns (rewritten_expr, any_found)."""
    found = []

    def repl(m):
        found.append(True)
        return add_agg(m.group(0))

    return _AGG_CALL_RE.sub(repl, expr), bool(found)


def _mask_literals(sql):
    """Same-length copy of `sql` with quoted-string contents blanked, so
    clause keywords inside literals don't split the query.  Handles
    BOTH escape spellings inside a literal: backslash (``'don\\'t'``)
    and the SQL doubled quote (``'don''t'``) — a doubled quote
    continues the literal instead of closing and reopening it, so an
    expression like ``item == 'don''t, group by'`` masks as ONE
    literal and its embedded clause keywords/commas never split the
    query."""
    out = list(sql)
    i = 0
    while i < len(out):
        q = out[i]
        if q in "'\"":
            i += 1
            while i < len(out):
                if out[i] == "\\" and i + 1 < len(out):
                    out[i] = "x"
                    out[i + 1] = "x"    # escaped char incl. quote
                    i += 2
                    continue
                if out[i] == q:
                    if i + 1 < len(out) and out[i + 1] == q:
                        out[i] = "x"    # SQL '' escape: still inside
                        out[i + 1] = "x"
                        i += 2
                        continue
                    break
                out[i] = "x"
                i += 1
        i += 1
    return "".join(out)


def _sql_quote_escapes(text):
    """SQL doubled-quote escapes translated to Python backslash form,
    so an extracted clause like ``item == 'don''t'`` compiles with
    eval to the string ``don't`` instead of the implicit concatenation
    ``dont``.  Backslash escapes pass through untouched."""
    out = []
    i = 0
    n = len(text)
    while i < n:
        ch = text[i]
        out.append(ch)
        i += 1
        if ch not in "'\"":
            continue
        q = ch
        while i < n:
            c2 = text[i]
            if c2 == "\\" and i + 1 < n:
                out.append(c2)
                out.append(text[i + 1])
                i += 2
                continue
            if c2 == q:
                if i + 1 < n and text[i + 1] == q:
                    out.append("\\")
                    out.append(q)
                    i += 2
                    continue
                out.append(q)
                i += 1
                break
            out.append(c2)
            i += 1
    return "".join(out)


def execute(sql, tables):
    """Minimal SQL-ish front over TableRDD (reference: dpark table's
    `execute` [SURVEY.md 2.3, low-confidence item]).  Supports
    SELECT cols FROM t [JOIN t2 ON col] [WHERE expr] [GROUP BY keys]
    [HAVING expr] [ORDER BY col [DESC]] [LIMIT n]; column expressions
    and aggregates use the DSL's syntax.  SELECT and HAVING may use
    aggregate EXPRESSIONS (``sum(v) / count(*)``); JOIN ... ON lowers
    to TableRDD.join (the device-riding equi-join) and accepts ``col``
    or ``a.col = b.col`` with the same column name on both sides.

    `tables`: dict name -> TableRDD.  Returns a TableRDD, or a row list
    when LIMIT is given.
    """
    m = _SQL_RE.match(_mask_literals(sql))
    if not m:
        raise ValueError("unsupported SQL: %r" % sql)

    def part(name):
        span = m.span(name)
        if span == (-1, -1):
            return None
        # clause text is extracted from the ORIGINAL sql (the masked
        # copy only guides the split); SQL '' escapes inside string
        # literals translate to Python form before any eval/compile
        return _sql_quote_escapes(sql[span[0]:span[1]])

    t = tables.get(m.group("table"))
    if t is None:
        raise ValueError("unknown table %r" % m.group("table"))
    if m.group("jtable"):
        other = tables.get(m.group("jtable"))
        if other is None:
            raise ValueError("unknown table %r" % m.group("jtable"))
        jm = _JOIN_ON_RE.match(part("jon"))
        if not jm:
            raise ValueError("unsupported JOIN ON: %r" % part("jon"))
        lcol, rcol = jm.group(1), jm.group(2) or jm.group(1)
        if lcol != rcol:
            raise ValueError(
                "JOIN ON must equate the same column name "
                "(%r vs %r)" % (lcol, rcol))
        t = t.join(other, lcol)
    if part("where"):
        t = t.where(part("where"))

    order = (part("order") or "").strip()
    desc = (m.group("dir") or "").strip().lower() == "desc"
    cols = part("cols").strip()

    if part("having") and not part("group"):
        raise ValueError("HAVING requires GROUP BY")
    if part("group"):
        group_keys = _split_cols((part("group"),))
        sel = _split_cols((cols,))
        aggs, out_exprs, out_names = [], [], []
        key_names = [re.sub(r"\W+", "_", k).strip("_") or ("k%d" % i)
                     for i, k in enumerate(group_keys)]

        def add_agg(text):
            # helper column for one aggregate call (leading underscores
            # would be stripped by _parse_column's sanitizer); dodge
            # user columns of the same name
            name = "agg%d" % len(aggs)
            while name in t.fields or name in key_names:
                name += "x"
            aggs.append("%s as %s" % (text, name))
            return name

        for c in sel:
            am = _AS_RE.match(c)
            expr, alias = (am.group(1), am.group(2)) if am \
                else (c, None)
            # _AGG_RE alone would also "match" compound expressions
            # (its lazy arg + end anchor spans `sum(a) * 2 + count(*)`)
            # — a BARE call is a fullmatch of the balanced call regex
            if _AGG_CALL_RE.fullmatch(expr.strip()):
                name = alias or _parse_column(c, t.fields, 0)[0]
                out_exprs.append("%s as %s" % (add_agg(expr), name))
                out_names.append(name)
            elif expr.strip() in group_keys:
                kn = key_names[group_keys.index(expr.strip())]
                name = alias or kn
                out_exprs.append("%s as %s" % (kn, name))
                out_names.append(name)
            else:
                new, found = _sub_aggs(expr, add_agg)
                if not found:
                    raise ValueError(
                        "non-aggregate select column %r is not a "
                        "group key" % c)
                name = alias or ("col%d" % len(out_exprs))
                out_exprs.append("%s as %s" % (new, name))
                out_names.append(name)
        hav = None
        if part("having"):
            hav, _ = _sub_aggs(part("having"), add_agg)
        t = t.groupBy(group_keys, *aggs)
        if hav is not None:
            t = t.where(hav)
        if order and order not in out_names:
            # ORDER BY a grouped column that the SELECT list drops or
            # renames: sort on the aggregated table before projecting
            t = t.sort(order, reverse=desc)
            order = ""
        t = t.select(*out_exprs)
        if order:
            t = t.sort(order, reverse=desc)
            order = ""
    else:
        # ORDER BY may reference either the source columns or a projected
        # output name: sort on whichever side actually holds it
        if order and cols != "*":
            projected = [
                _parse_column(c, t.fields, i)[0]
                for i, c in enumerate(_split_cols((cols,)))]
            if order not in projected:
                t = t.sort(order, reverse=desc)
                order = ""
        if cols != "*":
            t = t.select(cols)
        if order:
            t = t.sort(order, reverse=desc)
    if m.group("limit"):
        return t.take(int(m.group("limit")))
    return t


def _split_cols(cols):
    out = []
    for c in cols:
        if isinstance(c, (list, tuple)):
            out.extend(_split_cols(c))
        elif isinstance(c, _UDA):
            out.append(c)
        else:
            # split on top-level commas — not inside parens and not
            # inside string literals (a comma embedded in 'a, b' or a
            # ''-escaped literal must not split the expression)
            depth, cur, q = 0, "", None
            i = 0
            while i < len(c):
                ch = c[i]
                if q is not None:
                    cur += ch
                    if ch == "\\" and i + 1 < len(c):
                        cur += c[i + 1]
                        i += 2
                        continue
                    if ch == q:
                        if i + 1 < len(c) and c[i + 1] == q:
                            cur += c[i + 1]     # '' escape
                            i += 2
                            continue
                        q = None
                    i += 1
                    continue
                if ch in "'\"":
                    q = ch
                elif ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                if ch == "," and depth == 0:
                    out.append(cur.strip())
                    cur = ""
                else:
                    cur += ch
                i += 1
            if cur.strip():
                out.append(cur.strip())
    return out


class _UDAPairFn:
    def __init__(self, key_fns, arg_fn):
        self.key_fns = key_fns
        self.arg_fn = arg_fn

    def __call__(self, row):
        if len(self.key_fns) == 1:
            return (self.key_fns[0](row), self.arg_fn(row))
        return (tuple(fn(row) for fn in self.key_fns),
                self.arg_fn(row))


class _UDAFlatten:
    def __init__(self, n_keys):
        self.n_keys = n_keys

    def __call__(self, kv):
        k, v = kv
        keys = k if isinstance(k, tuple) and self.n_keys > 1 else (k,)
        return tuple(keys) + (v,)


class _SelectFn:
    def __init__(self, fns):
        self.fns = fns

    def __call__(self, row):
        return tuple(fn(row) for fn in self.fns)


class _WhereFn:
    def __init__(self, conds):
        self.conds = conds

    def __call__(self, row):
        return all(c(row) for c in self.conds)


class _GroupKeyFn:
    def __init__(self, fns):
        self.fns = fns

    def __call__(self, row):
        if len(self.fns) == 1:
            return self.fns[0](row)
        return tuple(fn(row) for fn in self.fns)


class _PairKeyFn(_GroupKeyFn):
    def __call__(self, row):
        return (super().__call__(row), row)


class _JoinKeyFn(_GroupKeyFn):
    def __init__(self, fn):
        self.fn = fn

    def __call__(self, row):
        return (self.fn(row), row)


class _JoinMerge:
    def __init__(self, li, ri):
        self.li = li
        self.ri = ri

    def __call__(self, kv):
        k, (l, r) = kv
        l = tuple(x for i, x in enumerate(l) if i != self.li)
        r = tuple(x for i, x in enumerate(r) if i != self.ri)
        return (k,) + l + r


class _AggCreate:
    def __init__(self, aggs):
        self.aggs = aggs

    def __call__(self, row):
        return tuple(a.create(row) for a in self.aggs)


class _AggMerge:
    def __init__(self, aggs):
        self.aggs = aggs

    def __call__(self, acc, row):
        return tuple(a.merge(v, row) for a, v in zip(self.aggs, acc))


class _AggCombine:
    def __init__(self, aggs):
        self.aggs = aggs

    def __call__(self, a, b):
        return tuple(g.combine(x, y) for g, x, y in zip(self.aggs, a, b))


class _AggFinalize:
    def __init__(self, aggs, n_keys):
        self.aggs = aggs
        self.n_keys = n_keys

    def __call__(self, kv):
        k, acc = kv
        keys = k if isinstance(k, tuple) and self.n_keys > 1 else (k,)
        return tuple(keys) + tuple(
            a.finalize(v) for a, v in zip(self.aggs, acc))


class _AggPartition:
    def __init__(self, aggs):
        self.aggs = aggs

    def __call__(self, it):
        acc = None
        merge = _AggMerge(self.aggs)
        create = _AggCreate(self.aggs)
        for row in it:
            acc = create(row) if acc is None else merge(acc, row)
        return acc
