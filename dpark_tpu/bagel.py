"""Bagel: Pregel-style BSP graph processing.

Reference parity: dpark/bagel.py (SURVEY.md sections 2.3 and 3.2) — the
superstep loop cogroups vertices with inbound messages, applies the user
compute(vertex, messages, aggregated, superstep), emits (new vertex, out
messages), optionally pre-combines messages per target (Combiner) and
reduces a global Aggregator over all vertices each superstep; halts when
every vertex is inactive and no messages remain.

Two execution models:

* `Bagel.run` — the reference's object contract (Vertex/Message/Edge
  Python objects, arbitrary compute).  On the tpu master, NUMERIC
  object programs are auto-columnarized onto the device Pregel
  (`_run_columnar`: per-degree-class vmap of the user compute,
  supersteps as fused mesh programs); everything else warns and runs
  the host paths (driver-resident fast loop, then RDD algebra).
* `run_pregel` — the TPU-native contract (SURVEY.md 3.2 [H] mapping):
  columnar vertex state, edge-centric vectorized compute/send, monoid
  message combine.  On the tpu master each superstep runs as fused
  shard_map programs (hash-dst all_to_all for messages, segment reduce
  for the combine, psum for the aggregator and halting counters); on
  local/process masters an equivalent vectorized numpy loop is the
  golden model.
"""

import numpy as np

from dpark_tpu.utils.log import get_logger

logger = get_logger("bagel")


class Vertex:
    def __init__(self, id, value, outEdges=None, active=True):
        self.id = id
        self.value = value
        self.outEdges = outEdges or []
        self.active = active

    def __repr__(self):
        return "<Vertex(%s, %r, active=%s)>" % (
            self.id, self.value, self.active)


class Edge:
    def __init__(self, target_id, value=None):
        self.target_id = target_id
        self.value = value


class Message:
    def __init__(self, target_id, value):
        self.target_id = target_id
        self.value = value


class Combiner:
    """Pre-shuffle message combine (reference: Bagel Combiner)."""

    def createCombiner(self, msg):
        return [msg]

    def mergeValue(self, combiner, msg):
        combiner.append(msg)
        return combiner

    def mergeCombiners(self, a, b):
        a.extend(b)
        return a


class BasicCombiner(Combiner):
    """Combine message values with a binary op (e.g. operator.add)."""

    def __init__(self, op):
        self.op = op

    def createCombiner(self, msg):
        return msg

    def mergeValue(self, combiner, msg):
        return self.op(combiner, msg)

    def mergeCombiners(self, a, b):
        return self.op(a, b)


class Aggregator:
    """Global per-superstep reduce over all vertices; the result is
    visible to every vertex in the NEXT superstep."""

    def createAggregator(self, vert):
        raise NotImplementedError

    def mergeAggregators(self, a, b):
        raise NotImplementedError


# the Bagel.run fast path keeps the graph driver-resident and skips the
# per-superstep shuffle jobs; set to False (or DPARK_BAGEL_FAST=0) to
# force the reference-shaped RDD algebra (e.g. graphs larger than
# driver memory)
import os as _os
FAST_OBJECT_RUN = _os.environ.get("DPARK_BAGEL_FAST", "1") != "0"
# graphs beyond this many vertices stay on the RDD path (the fast path
# collects the graph to the driver; collect-then-OOM is not a fallback)
FAST_MAX_VERTICES = int(_os.environ.get("DPARK_BAGEL_FAST_MAX",
                                        str(4_000_000)))


class _ObjectPathNeeded(Exception):
    """Raised inside the fast object run when the program does
    something only the RDD path models (vertex id rebinding, per-key
    growth we mis-tracked); inputs are untouched, so the caller simply
    re-runs the classic path."""


class _NotColumnarizable(Exception):
    """Raised while deciding whether an OBJECT Bagel program can ride
    the device run_pregel path; inputs untouched, callers fall back."""


# auto-columnarize numeric object-Bagel programs onto the device Pregel
# (VERDICT r3 #7); DPARK_BAGEL_DEVICE=0 forces the host object paths
DEVICE_OBJECT_RUN = _os.environ.get("DPARK_BAGEL_DEVICE", "1") != "0"
# compile-cost bounds (VERDICT r4 #4 lifted the old degree-8 /
# own-edges-only subset): user compute is traced once per DISTINCT
# out-degree, so the class count bounds trace/compile work; degree
# itself only sizes the per-class edge-target table.  Graphs beyond
# either bound fall back to the host object paths.
MAX_DEGREE_CLASSES = int(_os.environ.get("DPARK_BAGEL_MAX_CLASSES",
                                         "24"))
MAX_DEGREE = int(_os.environ.get("DPARK_BAGEL_MAX_DEGREE", "1024"))
# power-of-two degree BUCKETS (ISSUE 4): vertices pad their edge lists
# to the next power of two with masked dummy edges, so the class count
# collapses from <= MAX_DEGREE_CLASSES arbitrary degrees to
# <= 1 + log2(MAX_DEGREE) buckets (11 at the default cap) and the
# power-law cap disappears.  Soundness is verified per (class,
# superstep) by an exact-vs-bucket canary (bagel_obj._bucket_canary);
# degree-dependent computes (len(outEdges), tail reads) fall back to
# exact degree classes, then to the host paths.  "0" disables.
DEGREE_BUCKETS = _os.environ.get("DPARK_BAGEL_BUCKETS", "1") != "0"
# compile-budget guard: each degree class costs two traces (mail /
# no-mail) per superstep; a graph whose row count (vertices + edges)
# is below (classes x 2 x this) falls back to the host loop instead of
# spending more wall time compiling than computing.  0 disables.
BAGEL_MIN_ROWS_PER_TRACE = int(_os.environ.get(
    "DPARK_BAGEL_MIN_ROWS_PER_TRACE", "0") or 0)


class Bagel:
    @classmethod
    def run(cls, ctx, verts, msgs, compute,
            combiner=None, aggregator=None,
            max_superstep=80, numSplits=None, checkpoint_interval=10):
        """verts: RDD of (id, Vertex); msgs: RDD of (id, message_value).

        compute(vertex, messages_or_combined, aggregated, superstep)
          -> (new_vertex, [Message, ...])
        Returns the final verts RDD.

        Execution: by default the superstep loop runs DRIVER-RESIDENT
        (`_run_fast`): the graph is collected once, each superstep is a
        tight host loop with vectorized message delivery, and no
        shuffle/cogroup jobs are scheduled at all — per-superstep cost
        drops from three RDD jobs to one Python pass, on every master.
        The arbitrary per-vertex compute contract (ragged outEdges,
        data-dependent message lists, `msg or 0.0` idioms) is what
        makes this API untraceable for XLA — blockwise programs should
        use run_pregel for fused device supersteps; this adapter makes
        reference-shaped programs fast without a rewrite (VERDICT r2
        ask #4).  Falls back to the reference-shaped RDD algebra when
        the fast path cannot model the program — in which case compute
        RE-EXECUTES from superstep 0, so compute must tolerate
        re-execution (the same contract every task already has under
        retry/lineage recovery: side effects may repeat).
        """
        superstep = 0
        combiner = combiner or Combiner()
        numSplits = numSplits or len(verts.splits)
        ctx.start()
        # both driver-resident paths (device columnar, host fast loop)
        # consume the same bounded collect — do it once, not per path
        collected = None
        want_columnar = DEVICE_OBJECT_RUN \
            and getattr(ctx.scheduler, "executor", None) is not None
        if want_columnar or FAST_OBJECT_RUN:
            try:
                collected = cls._collect_bounded(verts, msgs)
            except (_ObjectPathNeeded, MemoryError) as e:
                logger.warning("object Bagel driver-resident paths "
                               "unavailable (%s); running the RDD "
                               "path", e)
        if collected is not None and want_columnar:
            try:
                return cls._run_columnar(ctx, collected, compute,
                                         combiner, aggregator,
                                         max_superstep, numSplits)
            except _NotColumnarizable as e:
                logger.warning("object Bagel program is not "
                               "device-columnarizable (%s); "
                               "driver-resident host path", e)
        if collected is not None and FAST_OBJECT_RUN:
            try:
                return cls._run_fast(ctx, collected, compute,
                                     combiner, aggregator,
                                     max_superstep, numSplits)
            except (_ObjectPathNeeded, MemoryError) as e:
                logger.warning("object Bagel fast path unavailable "
                               "(%s); running the RDD path", e)
        if getattr(ctx.scheduler, "executor", None) is not None:
            logger.warning(
                "Bagel.run with object vertices executes on the HOST "
                "path even on the tpu master; use bagel.run_pregel for "
                "the device-native superstep")

        while superstep < max_superstep:
            logger.debug("superstep %d", superstep)
            aggregated = None
            if aggregator is not None:
                parts = [p for p in verts.ctx.runJob(
                    verts.map(_AggCreate(aggregator)),
                    _PartReduceBy(aggregator.mergeAggregators))
                    if p is not _NO_VALUE]
                if parts:
                    aggregated = parts[0]
                    for p in parts[1:]:
                        aggregated = aggregator.mergeAggregators(
                            aggregated, p)

            combined = msgs.combineByKey(
                combiner.createCombiner, combiner.mergeValue,
                combiner.mergeCombiners, numSplits)
            grouped = verts.groupWith(combined, numSplits=numSplits)
            processed = grouped.flatMapValue(
                _ComputeFn(compute, aggregated, superstep)).cache()

            # force evaluation; count active vertices and pending messages
            num_active, num_msgs = processed.map(_stats).fold(
                (0, 0), _merge_stats)

            verts = processed.mapValue(_fst_of_pair)
            msgs = processed.flatMap(_OutMessages())
            superstep += 1
            if checkpoint_interval and superstep % checkpoint_interval == 0 \
                    and ctx.checkpoint_dir:
                verts = verts.mapValue(_identity)
                verts.checkpoint()
            if num_msgs == 0 and num_active == 0:
                break
        return verts

    @classmethod
    def _run_columnar(cls, ctx, collected, compute, combiner,
                      aggregator, max_superstep, numSplits):
        """Auto-columnarize an object-Bagel program onto the device
        (VERDICT r3 #7, generalized per VERDICT r4 #4 — see
        backend/tpu/bagel_obj.py for the execution model: class-sliced
        vmap of the user compute, CSR-style message flattening, and a
        hash(dst) exchange, so targets may be ANY integer id, degree
        runs to MAX_DEGREE, and Vertex.value may be any numeric
        pytree).

        The detectable subset: integer vertex ids and message targets,
        numeric pytree vertex values (consistent structure), numeric
        scalar message values, Edge.value all-None or all-numeric,
        BasicCombiner with a provable monoid op, no Aggregator, at most
        MAX_DEGREE out-edges and MAX_DEGREE_CLASSES distinct degrees
        (each distinct degree is a separate trace).  Anything else
        raises _NotColumnarizable and the host object paths run instead
        — warn-and-fallback, never silent wrong answers: shape and
        dtype checks run at trace time, each superstep, before that
        superstep executes."""
        from dpark_tpu.backend.tpu.fuse import classify_merge
        import jax.tree_util as jtu
        if aggregator is not None:
            raise _NotColumnarizable("object Aggregator contract")
        if type(combiner) is BasicCombiner:
            # a provable monoid combines through single-pass segment
            # scatters; any other op rides IF it traces as a
            # treedef-preserving merge over the message value pytree
            # (DeviceObjectPregel verifies at discovery time) — the
            # per-leaf-monoid-or-traced-merge contract of vector
            # message values
            monoid = classify_merge(combiner.op)
        elif type(combiner) is Combiner:
            raise _NotColumnarizable("list-combining default Combiner")
        else:
            raise _NotColumnarizable("custom Combiner %r"
                                     % type(combiner).__name__)
        graph, pend = collected
        n = len(graph)
        if n == 0:
            raise _NotColumnarizable("empty graph")

        ids_l, act_l, deg_l = [], [], []
        vdef = None
        vleaf_lists = None
        tgt_chunks, ev_vals = [], []
        ev_state = None       # None undecided / False all-None / True
        out_edges = {}
        for vid, v in graph.items():
            if not isinstance(v, Vertex):
                raise _NotColumnarizable("vertex is %r, not Vertex"
                                         % type(v).__name__)
            if isinstance(vid, bool) or not isinstance(
                    vid, (int, np.integer)):
                raise _NotColumnarizable("non-integer vertex id %r"
                                         % (vid,))
            leaves, treedef = jtu.tree_flatten(v.value)
            if vdef is None:
                vdef = treedef
                vleaf_lists = [[] for _ in leaves]
            elif treedef != vdef:
                raise _NotColumnarizable(
                    "vertex value structure varies across vertices")
            if not leaves:
                raise _NotColumnarizable(
                    "vertex value has no numeric leaves")
            for li, leaf in enumerate(leaves):
                if isinstance(leaf, bool):
                    raise _NotColumnarizable(
                        "non-numeric vertex value leaf %r" % (leaf,))
                arr = np.asarray(leaf)
                if arr.dtype.kind not in "if":
                    raise _NotColumnarizable(
                        "non-numeric vertex value leaf %r" % (leaf,))
                vleaf_lists[li].append(arr)
            edges = list(v.outEdges)
            if len(edges) > MAX_DEGREE:
                raise _NotColumnarizable("degree %d > %d"
                                         % (len(edges), MAX_DEGREE))
            tg = np.empty(len(edges), np.int64)
            for i, e in enumerate(edges):
                t = e.target_id
                if isinstance(t, bool) or not isinstance(
                        t, (int, np.integer)):
                    raise _NotColumnarizable("non-integer edge target")
                tg[i] = int(t)
                val = getattr(e, "value", None)
                if val is None:
                    if ev_state is True:
                        raise _NotColumnarizable(
                            "mixed None/numeric edge values")
                    ev_state = False
                else:
                    if ev_state is False:
                        raise _NotColumnarizable(
                            "mixed None/numeric edge values")
                    if isinstance(val, bool) or not isinstance(
                            val, (int, float, np.integer, np.floating)):
                        raise _NotColumnarizable(
                            "non-numeric edge value %r" % (val,))
                    ev_state = True
                    ev_vals.append(val)
            tgt_chunks.append(tg)
            out_edges[int(vid)] = v.outEdges
            ids_l.append(int(vid))
            act_l.append(bool(v.active))
            deg_l.append(len(edges))
        for t, _ in pend:
            if isinstance(t, bool) or not isinstance(t, (int, np.integer)):
                raise _NotColumnarizable("non-integer message target")

        ids = np.asarray(ids_l, np.int64)
        degs = np.asarray(deg_l, np.int64)
        if not DEGREE_BUCKETS and len(set(deg_l)) > MAX_DEGREE_CLASSES:
            # with bucketing on, the class-count decision moves into
            # DeviceObjectPregel: buckets bound the count by
            # 1 + log2(MAX_DEGREE); only the exact-class FALLBACK
            # (degree-dependent computes) re-checks this cap
            raise _NotColumnarizable(
                "%d degree classes > %d (each distinct degree is a "
                "separate trace)" % (len(set(deg_l)),
                                     MAX_DEGREE_CLASSES))
        try:
            vleaves = [np.stack(col) for col in vleaf_lists]
        except ValueError:
            raise _NotColumnarizable("vertex value leaf shapes vary")
        for col in vleaves:
            if col.dtype.kind not in "if":
                raise _NotColumnarizable("vertex values dtype %s"
                                         % col.dtype)
        act = np.asarray(act_l, bool)
        tgt_flat = (np.concatenate(tgt_chunks) if tgt_chunks
                    else np.zeros(0, np.int64))
        ev_flat = None
        if ev_state:
            ev_flat = np.asarray(ev_vals)
            if ev_flat.dtype.kind not in "if":
                raise _NotColumnarizable("edge values dtype %s"
                                         % ev_flat.dtype)
        pend_cols = None
        if pend:
            # initial message VALUES may be any small numeric pytree
            # (consistent structure): leaves ride as separate columns,
            # exactly like emitted Message.value leaves
            mdef0 = None
            leaf_lists = None
            for _, v in pend:
                leaves, mdef = jtu.tree_flatten(v)
                if mdef0 is None:
                    mdef0, leaf_lists = mdef, [[] for _ in leaves]
                elif mdef != mdef0:
                    raise _NotColumnarizable(
                        "initial message value structure varies")
                if not leaves:
                    raise _NotColumnarizable(
                        "initial message value has no numeric leaves")
                for li, leaf in enumerate(leaves):
                    if isinstance(leaf, bool):
                        raise _NotColumnarizable(
                            "non-numeric message value leaf")
                    leaf_lists[li].append(np.asarray(leaf))
            try:
                pleaves = [np.stack(col) for col in leaf_lists]
            except ValueError:
                raise _NotColumnarizable(
                    "initial message leaf shapes vary")
            for col in pleaves:
                if col.dtype.kind not in "if":
                    raise _NotColumnarizable("non-numeric message value")
            pend_cols = (np.asarray([t for t, _ in pend], np.int64),
                         pleaves, mdef0)

        if BAGEL_MIN_ROWS_PER_TRACE:
            # compile-budget guard: traces ~= 2 x classes (mail +
            # no-mail) per superstep; buckets bound classes at
            # 1 + log2(MAX_DEGREE), exact classes at the distinct
            # count.  Below the budget the host loops win outright.
            n_classes = (1 + max(int(d).bit_length() for d in
                                 set(deg_l)) if DEGREE_BUCKETS
                         else len(set(deg_l))) or 1
            rows = len(ids_l) + int(tgt_flat.shape[0])
            if rows < BAGEL_MIN_ROWS_PER_TRACE * 2 * n_classes:
                raise _NotColumnarizable(
                    "compile budget: %d graph rows under "
                    "DPARK_BAGEL_MIN_ROWS_PER_TRACE=%d x ~%d traces"
                    % (rows, BAGEL_MIN_ROWS_PER_TRACE,
                       2 * n_classes))

        from dpark_tpu.backend.tpu.bagel_obj import DeviceObjectPregel
        try:
            dop = DeviceObjectPregel(
                ctx.scheduler.executor, compute, monoid, vdef, ids,
                vleaves, act, degs, tgt_flat, ev_flat, pend_cols,
                max_superstep, combine_op=combiner.op)
            out_ids, out_leaves, out_act = dop.run()
        except _NotColumnarizable:
            raise
        except PregelInputError as e:
            # inputs the device Pregel rejects (e.g. a vertex id equal
            # to its padding sentinel) ran fine on the object path
            # before this adapter existed — keep them running there
            raise _NotColumnarizable(
                "device Pregel rejected inputs (%s)" % e)
        except Exception as e:
            raise _NotColumnarizable(
                "device object Pregel failed (%s)" % str(e)[:200])
        ctx.scheduler._pregel_device_used = True
        out = []
        for i, vid in enumerate(out_ids.tolist()):
            leaves_i = []
            for col in out_leaves:
                x = col[i]
                if x.ndim == 0:
                    x = float(x) if x.dtype.kind == "f" else int(x)
                leaves_i.append(x)
            val = jtu.tree_unflatten(vdef, leaves_i)
            out.append((vid, Vertex(vid, val, out_edges[vid],
                                    bool(out_act[i]))))
        return ctx.parallelize(out, numSplits)

    @classmethod
    def _collect_bounded(cls, verts, msgs):
        """(graph dict, pending list) for the driver-resident paths —
        count first so an oversized graph never collect-then-OOMs."""
        n = verts.count()
        if n > FAST_MAX_VERTICES:
            raise _ObjectPathNeeded(
                "%d vertices > DPARK_BAGEL_FAST_MAX=%d"
                % (n, FAST_MAX_VERTICES))
        return dict(verts.collect()), list(msgs.collect())

    @classmethod
    def _run_fast(cls, ctx, collected, compute, combiner, aggregator,
                  max_superstep, numSplits):
        """Driver-resident object supersteps: semantics identical to
        the RDD loop above (same pass-through rule for inactive
        no-mail vertices, same unknown-target drop, same halting
        condition), with delivery done by per-target fold through the
        user's Combiner."""
        graph, pending = collected           # from _collect_bounded
        graph = dict(graph)                  # loop mutates its copy
        pending = list(pending)
        superstep = 0
        while superstep < max_superstep:
            aggregated = None
            if aggregator is not None:
                it = iter(graph.values())
                first = next(it, None)
                if first is not None:
                    aggregated = aggregator.createAggregator(first)
                    for v in it:
                        aggregated = aggregator.mergeAggregators(
                            aggregated, aggregator.createAggregator(v))

            mail = {}
            for target, value in pending:
                if target not in graph:
                    continue                 # parity: unknown ids drop
                if target in mail:
                    mail[target] = combiner.mergeValue(
                        mail[target], value)
                else:
                    mail[target] = combiner.createCombiner(value)

            pending = []
            num_active = 0
            new_graph = {}
            for vid, vert in graph.items():
                vmail = mail.get(vid)
                if vmail is None and not vert.active:
                    new_graph[vid] = vert    # untouched pass-through
                    continue
                out = compute(vert, vmail, aggregated, superstep)
                new_vert, out_msgs = out
                if new_vert.id != vid:
                    raise _ObjectPathNeeded(
                        "compute rebound vertex id %r -> %r"
                        % (vid, new_vert.id))
                new_graph[vid] = new_vert
                for m in out_msgs:
                    pending.append((m.target_id, m.value))
            graph = new_graph
            num_active = sum(1 for v in graph.values() if v.active)
            superstep += 1
            logger.debug("fast superstep %d: active=%d msgs=%d",
                         superstep, num_active, len(pending))
            if not pending and num_active == 0:
                break
        return ctx.parallelize(list(graph.items()), numSplits)


_NO_VALUE = "__bagel_no_value__"


class _PartReduceBy:
    def __init__(self, merge):
        self.merge = merge

    def __call__(self, it):
        out = _NO_VALUE
        for x in it:
            out = x if out is _NO_VALUE else self.merge(out, x)
        return out


class _AggCreate:
    def __init__(self, aggregator):
        self.aggregator = aggregator

    def __call__(self, kv):
        return self.aggregator.createAggregator(kv[1])


class _ComputeFn:
    """grouped value = ([vertex...], [combined_messages...]); vertices
    without an entry (messages to unknown ids) are dropped, inactive
    vertices with no mail are passed through untouched."""

    def __init__(self, compute, aggregated, superstep):
        self.compute = compute
        self.aggregated = aggregated
        self.superstep = superstep

    def __call__(self, groups):
        vs, cs = groups
        if not vs:
            return []
        vert = vs[0]
        mail = cs[0] if cs else None
        if mail is None and not vert.active:
            return [(vert, [])]
        out = self.compute(vert, mail, self.aggregated, self.superstep)
        return [out]


class _OutMessages:
    def __call__(self, kv):
        _, (vert, out_msgs) = kv
        return [(m.target_id, m.value) for m in out_msgs]


def _stats(kv):
    vert, out_msgs = kv[1]
    return (1 if vert.active else 0, len(out_msgs))


def _merge_stats(a, b):
    return (a[0] + b[0], a[1] + b[1])


def _fst_of_pair(pair):
    return pair[0]


def _identity(x):
    return x


# ----------------------------------------------------------------------
# TPU-native Pregel (SURVEY.md 3.2 [H] mapping): columnar vertex state,
# vectorized edge-centric compute/send, monoid message combine
# ----------------------------------------------------------------------

PREGEL_MONOIDS = ("add", "min", "max", "mul")


class PregelInputError(ValueError):
    """Invalid run_pregel input (bad ids/edges/messages).  Never triggers
    the silent device->host fallback: the input is wrong on both paths."""


def as_leaves(x):
    """(leaves, was_tuple) for a single-array-or-tuple user value."""
    if isinstance(x, (tuple, list)):
        return list(x), True
    return [x], False


def rewrap(leaves, was_tuple):
    return tuple(leaves) if was_tuple else leaves[0]


def monoid_identity(kind, dtype):
    """Identity element so absent messages are a no-op under combine."""
    dt = np.dtype(dtype)
    if kind == "add":
        return dt.type(0)
    if kind == "mul":
        return dt.type(1)
    if dt.kind == "f":
        return dt.type(np.inf if kind == "min" else -np.inf)
    return np.iinfo(dt).max if kind == "min" else np.iinfo(dt).min


_NP_COMBINE = {"add": np.add, "min": np.minimum,
               "max": np.maximum, "mul": np.multiply}
_NP_REDUCE = {"add": np.sum, "min": np.min,
              "max": np.max, "mul": np.prod}


def run_pregel(ctx, ids, values, edges, compute, send, combine="add",
               edge_values=None, active=None, initial_messages=None,
               aggregator=None, max_superstep=80,
               static_superstep=False, send_gate_leaf=None):
    """Vectorized Pregel — the device-native Bagel.

    ids:     (n,) int array of unique vertex ids
    values:  (n,) array or tuple of (n, ...) arrays — vertex state
    edges:   (src_ids, dst_ids) int arrays; each edge lives with its
             source, messages flow along it to dst
    compute(values, msg, has_msg, active, aggregated, superstep)
             -> (new_values, new_active): applied BLOCKWISE — every
             argument is an array over a whole block of vertices (all of
             them on the host path, one device's block on the tpu
             master), so it must be written with vectorized/elementwise
             array ops (jnp or np arithmetic, where(), comparisons) —
             no Python control flow on the data.  `msg` holds the
             combined inbound message per vertex (the monoid identity
             where has_msg is False); `superstep` is a scalar.
    send(src_values, edge_values, src_degree) -> per-edge message value
             (scalar leaf or tuple of scalar leaves), same blockwise
             contract over edges; only edges whose source is active
             after compute actually send — unless `send_gate_leaf` is
             given: the index of a bool vertex-state leaf that REPLACES
             post-compute active as the send mask (for contracts where
             a halting vertex still delivers, or an active one emits
             nothing — the columnarized object Bagel needs both).
    combine: message-combine monoid: "add" | "min" | "max" | "mul"
    aggregator: None or (create(values) -> leaf/tuple, monoid): global
             per-superstep reduce over the PRE-compute vertex state,
             visible to compute as `aggregated` the same superstep
    initial_messages: None or (dst_ids, msg_values) delivered at
             superstep 0

    Halts when no vertex is active and no messages are pending, or at
    max_superstep.  Returns (ids, values, active) sorted by id (numpy).

    On the tpu master the superstep runs as fused shard_map programs
    over the device mesh (backend/tpu/bagel.py); other masters use the
    equivalent vectorized numpy loop below (the golden model).
    """
    if combine not in PREGEL_MONOIDS:
        raise ValueError("combine must be one of %s" % (PREGEL_MONOIDS,))
    if np.asarray(ids).shape[0] == 0 \
            and np.asarray(edges[0]).shape[0] == 0:
        vleaves, v_tuple = as_leaves(values)
        return (np.zeros(0, np.int64),
                rewrap([np.asarray(l)[:0] for l in vleaves], v_tuple),
                np.zeros(0, bool))
    ctx.start()
    ex = getattr(ctx.scheduler, "executor", None)
    if ex is not None:
        try:
            from dpark_tpu.backend.tpu.bagel import DevicePregel
            out = DevicePregel(
                ex, ids, values, edges, compute, send, combine=combine,
                edge_values=edge_values, active=active,
                initial_messages=initial_messages, aggregator=aggregator,
                max_superstep=max_superstep,
                static_superstep=static_superstep,
                send_gate_leaf=send_gate_leaf).run()
            ctx.scheduler._pregel_device_used = True
            return out
        except PregelInputError:
            raise                  # wrong on both paths: surface it
        except _NotColumnarizable:
            raise                  # the host twin would raise it too:
            #                        let the object fallback run instead
        except Exception as e:
            logger.warning("device Pregel unavailable (%s); host path", e)
            ctx.scheduler._pregel_device_used = False
    return _pregel_host(ids, values, edges, compute, send, combine,
                        edge_values, active, initial_messages,
                        aggregator, max_superstep, send_gate_leaf)


def _pregel_host(ids, values, edges, compute, send, combine,
                 edge_values, active, initial_messages, aggregator,
                 max_superstep, send_gate_leaf=None):
    """Single-host vectorized Pregel: the golden model for the device
    implementation.  The framework side is pure numpy, but user
    compute/send may use jnp — whose first call initializes the default
    jax backend, so honor DPARK_TPU_PLATFORM here too (a wedged device
    tunnel must not hang the LOCAL master)."""
    from dpark_tpu.utils import apply_platform_override
    apply_platform_override()
    ids = np.asarray(ids, np.int64)
    n = ids.shape[0]
    if np.unique(ids).shape[0] != n:
        raise PregelInputError("vertex ids must be unique")
    order = np.argsort(ids)
    ids = ids[order]
    vleaves, v_tuple = as_leaves(values)
    vleaves = [np.asarray(l)[order] for l in vleaves]
    act = np.ones(n, bool) if active is None \
        else np.asarray(active, bool)[order]

    src = np.asarray(edges[0], np.int64)
    dst = np.asarray(edges[1], np.int64)
    eleaves, e_tuple = ((None, False) if edge_values is None
                        else as_leaves(edge_values))
    eleaves = [np.asarray(l) for l in eleaves] if eleaves else []
    src_idx = np.searchsorted(ids, src)
    src_idx = np.clip(src_idx, 0, max(0, n - 1))
    if src.size and (n == 0
                     or not np.array_equal(ids[src_idx], src)):
        raise PregelInputError("edge source not in vertex ids")
    deg = np.bincount(src_idx, minlength=n) if src.size \
        else np.zeros(n, np.int64)

    # message dtypes AND trailing shapes (leaves may be scalars or
    # small fixed-size vectors — the sum-vector exchange), discovered
    # by probing `send` on empty slices (the host twin of the device
    # path's eval_shape)
    try:
        probe = send(rewrap([l[:0] for l in vleaves], v_tuple),
                     rewrap([l[:0] for l in eleaves], e_tuple)
                     if eleaves else None, deg[:0])
        m_probe, m_tuple = as_leaves(probe)
        msg_dtypes = [np.asarray(l).dtype for l in m_probe]
        msg_shapes = [np.asarray(l).shape[1:] for l in m_probe]
    except Exception:
        m_tuple = False
        msg_dtypes = [np.dtype(np.float64)]
        msg_shapes = [()]

    def deliver(pdst, pvals):
        """Combine pending messages per target; unknown targets drop
        (parity with the object path).  Vector leaves combine
        elementwise — the per-leaf monoid."""
        pos = np.searchsorted(ids, pdst)
        pos = np.clip(pos, 0, max(0, n - 1))
        known = ids[pos] == pdst
        pos = pos[known]
        bufs = []
        for l in pvals:
            buf = np.full((n,) + l.shape[1:],
                          monoid_identity(combine, l.dtype), l.dtype)
            _NP_COMBINE[combine].at(buf, pos, l[known])
            bufs.append(buf)
        has = np.bincount(pos, minlength=n) > 0
        return bufs, has

    pending = None
    if initial_messages is not None:
        idst = np.asarray(initial_messages[0], np.int64)
        ivls, _ = as_leaves(initial_messages[1])
        if idst.size and len(ivls) != len(msg_dtypes):
            raise PregelInputError(
                "initial message leaves mismatch: got %d, send "
                "produces %d" % (len(ivls), len(msg_dtypes)))
        pending = (idst, [np.asarray(l, dt)
                          for l, dt in zip(ivls, msg_dtypes)])

    s = 0
    while s < max_superstep:
        aggregated = None
        if aggregator is not None:
            create, amon = aggregator
            a_leaves, a_tuple = as_leaves(
                create(rewrap(vleaves, v_tuple)))
            aggregated = rewrap(
                [_NP_REDUCE[amon](np.asarray(l)) for l in a_leaves],
                a_tuple)

        if pending is not None and pending[0].size:
            msg_leaves, has = deliver(*pending)
        else:
            msg_leaves = [np.full((n,) + shp,
                                  monoid_identity(combine, dt), dt)
                          for dt, shp in zip(msg_dtypes, msg_shapes)]
            has = np.zeros(n, bool)
        nv_, na_ = compute(rewrap(vleaves, v_tuple),
                           rewrap(msg_leaves, m_tuple), has, act,
                           aggregated, s)
        new_leaves, _ = as_leaves(nv_)
        vleaves = [np.broadcast_to(np.asarray(l), (n,) +
                                   np.asarray(l).shape[1:]).copy()
                   if np.asarray(l).shape[:1] != (n,)
                   else np.asarray(l) for l in new_leaves]
        act = np.broadcast_to(np.asarray(na_, bool), (n,)).copy()

        gate = (np.asarray(vleaves[send_gate_leaf], bool)
                if send_gate_leaf is not None else act)
        src_mask = gate[src_idx] if src.size else np.zeros(0, bool)
        if src.size:
            msg = send(rewrap([l[src_idx] for l in vleaves], v_tuple),
                       rewrap([l for l in eleaves], e_tuple)
                       if eleaves else None,
                       deg[src_idx])
            m_leaves, m_tuple = as_leaves(msg)
            m_leaves = [np.broadcast_to(
                np.asarray(l),
                (src.size,) + np.asarray(l).shape[1:]).copy()
                for l in m_leaves]
            pending = (dst[src_mask],
                       [l[src_mask] for l in m_leaves])
        else:
            pending = (np.zeros(0, np.int64), [])
        n_active = int(act.sum())
        n_msgs = int(src_mask.sum())
        s += 1
        logger.debug("host superstep %d: active=%d msgs=%d",
                     s, n_active, n_msgs)
        if n_active == 0 and n_msgs == 0:
            break
    return ids, rewrap(vleaves, v_tuple), act
