"""Bagel: Pregel-style BSP graph processing on RDDs.

Reference parity: dpark/bagel.py (SURVEY.md sections 2.3 and 3.2) — the
superstep loop cogroups vertices with inbound messages, applies the user
compute(vertex, messages, aggregated, superstep), emits (new vertex, out
messages), optionally pre-combines messages per target (Combiner) and
reduces a global Aggregator over all vertices each superstep; halts when
every vertex is inactive and no messages remain.

TPU mapping (SURVEY.md 3.2): each superstep is ordinary RDD algebra —
cogroup (shuffle) + mapValue + flatMap — so on the tpu master the message
combine rides the device segmented-reduce and the halting counters are a
psum-style accumulator.  The Python loop stays on the host, exactly like
the reference.
"""

from dpark_tpu.utils.log import get_logger

logger = get_logger("bagel")


class Vertex:
    def __init__(self, id, value, outEdges=None, active=True):
        self.id = id
        self.value = value
        self.outEdges = outEdges or []
        self.active = active

    def __repr__(self):
        return "<Vertex(%s, %r, active=%s)>" % (
            self.id, self.value, self.active)


class Edge:
    def __init__(self, target_id, value=None):
        self.target_id = target_id
        self.value = value


class Message:
    def __init__(self, target_id, value):
        self.target_id = target_id
        self.value = value


class Combiner:
    """Pre-shuffle message combine (reference: Bagel Combiner)."""

    def createCombiner(self, msg):
        return [msg]

    def mergeValue(self, combiner, msg):
        combiner.append(msg)
        return combiner

    def mergeCombiners(self, a, b):
        a.extend(b)
        return a


class BasicCombiner(Combiner):
    """Combine message values with a binary op (e.g. operator.add)."""

    def __init__(self, op):
        self.op = op

    def createCombiner(self, msg):
        return msg

    def mergeValue(self, combiner, msg):
        return self.op(combiner, msg)

    def mergeCombiners(self, a, b):
        return self.op(a, b)


class Aggregator:
    """Global per-superstep reduce over all vertices; the result is
    visible to every vertex in the NEXT superstep."""

    def createAggregator(self, vert):
        raise NotImplementedError

    def mergeAggregators(self, a, b):
        raise NotImplementedError


class Bagel:
    @classmethod
    def run(cls, ctx, verts, msgs, compute,
            combiner=None, aggregator=None,
            max_superstep=80, numSplits=None, checkpoint_interval=10):
        """verts: RDD of (id, Vertex); msgs: RDD of (id, message_value).

        compute(vertex, messages_or_combined, aggregated, superstep)
          -> (new_vertex, [Message, ...])
        Returns the final verts RDD.
        """
        superstep = 0
        combiner = combiner or Combiner()
        numSplits = numSplits or len(verts.splits)

        while superstep < max_superstep:
            logger.debug("superstep %d", superstep)
            aggregated = None
            if aggregator is not None:
                parts = [p for p in verts.ctx.runJob(
                    verts.map(_AggCreate(aggregator)),
                    _PartReduceBy(aggregator.mergeAggregators))
                    if p is not _NO_VALUE]
                if parts:
                    aggregated = parts[0]
                    for p in parts[1:]:
                        aggregated = aggregator.mergeAggregators(
                            aggregated, p)

            combined = msgs.combineByKey(
                combiner.createCombiner, combiner.mergeValue,
                combiner.mergeCombiners, numSplits)
            grouped = verts.groupWith(combined, numSplits=numSplits)
            processed = grouped.flatMapValue(
                _ComputeFn(compute, aggregated, superstep)).cache()

            # force evaluation; count active vertices and pending messages
            num_active, num_msgs = processed.map(_stats).fold(
                (0, 0), _merge_stats)

            verts = processed.mapValue(_fst_of_pair)
            msgs = processed.flatMap(_OutMessages())
            superstep += 1
            if checkpoint_interval and superstep % checkpoint_interval == 0 \
                    and ctx.checkpoint_dir:
                verts = verts.mapValue(_identity)
                verts.checkpoint()
            if num_msgs == 0 and num_active == 0:
                break
        return verts


_NO_VALUE = "__bagel_no_value__"


class _PartReduceBy:
    def __init__(self, merge):
        self.merge = merge

    def __call__(self, it):
        out = _NO_VALUE
        for x in it:
            out = x if out is _NO_VALUE else self.merge(out, x)
        return out


class _AggCreate:
    def __init__(self, aggregator):
        self.aggregator = aggregator

    def __call__(self, kv):
        return self.aggregator.createAggregator(kv[1])


class _ComputeFn:
    """grouped value = ([vertex...], [combined_messages...]); vertices
    without an entry (messages to unknown ids) are dropped, inactive
    vertices with no mail are passed through untouched."""

    def __init__(self, compute, aggregated, superstep):
        self.compute = compute
        self.aggregated = aggregated
        self.superstep = superstep

    def __call__(self, groups):
        vs, cs = groups
        if not vs:
            return []
        vert = vs[0]
        mail = cs[0] if cs else None
        if mail is None and not vert.active:
            return [(vert, [])]
        out = self.compute(vert, mail, self.aggregated, self.superstep)
        return [out]


class _OutMessages:
    def __call__(self, kv):
        _, (vert, out_msgs) = kv
        return [(m.target_id, m.value) for m in out_msgs]


def _stats(kv):
    vert, out_msgs = kv[1]
    return (1 if vert.active else 0, len(out_msgs))


def _merge_stats(a, b):
    return (a[0] + b[0], a[1] + b[1])


def _fst_of_pair(pair):
    return pair[0]


def _identity(x):
    return x
