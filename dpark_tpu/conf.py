"""Configuration for dpark_tpu.

Reference parity: dpark/conf.py (module constants + optional user conf file
via the DPARK_CONF env var).  Reference mount was empty at build time; survey
cites are at file-granularity only (SURVEY.md section 2.1).

TPU additions beyond the reference: mesh shape, HBM budget knobs, and the
device-bucket padding policy used by the all_to_all shuffle.
"""

import os
import importlib.util

# ---------------------------------------------------------------------------
# Reference-parity knobs (dpark/conf.py)
# ---------------------------------------------------------------------------

MEM_PER_TASK = 200.0          # MB per task (process/mesos masters)
MAX_TASK_FAILURES = 4         # retries before a job aborts
# parent-stage resubmissions (FetchFailed lineage recovery) per stage
# before the job aborts with a chained error: a shuffle source that
# keeps failing must not loop the DAG forever (ISSUE 5 satellite)
MAX_STAGE_FAILURES = 4
SCHEDULER_STALL_TIMEOUT = 60  # s between event-queue deadlock checks; a
                              # check only aborts when NO task is in flight

# speculative re-launch of stragglers (reference: dpark/job.py): once
# SPECULATION_QUANTILE of a stage's tasks finished, any task running
# longer than SPECULATION_MULTIPLIER x the median duration gets a
# duplicate; first completion wins
SPECULATION = True
SPECULATION_QUANTILE = 0.75
SPECULATION_MULTIPLIER = 2.0
MAX_TASK_MEMORY = 15 << 10    # MB hard ceiling when escalating retries

# shuffle behaviour (the reference's `rddconf`)
SORT_SHUFFLE = False          # sort-based shuffle path instead of hash-dict
SPILL_DIR_THRESHOLD = 0.8     # fraction of MEM_PER_TASK before disk spill
SHUFFLE_CHUNK_RECORDS = 1 << 16

# workdir candidates: first writable wins (dpark: DPARK_WORK_DIR)
DPARK_WORK_DIR = os.environ.get("DPARK_WORK_DIR", "/tmp/dpark_tpu")

# compression codec for shuffle files / broadcast blocks: zlib always
# available; lz4 used when importable (reference prefers lz4).
COMPRESS = "auto"

# ---------------------------------------------------------------------------
# chaos plane + recovery knobs (dpark_tpu/faults.py — ISSUE 5)
# ---------------------------------------------------------------------------

# deterministic fault injection spec, e.g.
#   "shuffle.fetch:p=0.2,seed=7;executor.dispatch:nth=3,kind=oom"
# empty = no injection (zero hot-path cost).  See faults.py for the
# full grammar and the list of named sites.
DPARK_FAULTS = os.environ.get("DPARK_FAULTS", "")

# device-path graceful degradation: an XlaRuntimeError /
# RESOURCE_EXHAUSTED from a stage program first retries the stage with
# a HALVED wave budget (stream_chunk_rows), then falls back to the
# object path for that stage only — recorded as a per-stage
# `degrade_reason`, never a job abort.  "0" disables (the error then
# still falls back to the object path, without the halved retry).
DEGRADE = os.environ.get("DPARK_DEGRADE", "1") != "0"

# erasure-coded shuffle exchange (dpark_tpu/coding.py — ISSUE 6):
#   off      no parity (default; zero hot-path cost)
#   xor      4 data shards + 1 XOR parity per bucket/spill payload
#   xor(k)   same with k data shards
#   rs(k,m)  k data + m Reed-Solomon GF(2^8) parity shards
# With coding on, shuffle buckets and spill runs carry parity shards;
# the fetch side reads all n shards concurrently and DECODES from the
# fastest k — a failed or straggling fetch costs a decode, not a
# lineage recompute.  Counters surface as `decodes` in job records,
# recovery_summary(), and the bench JSON.
DPARK_SHUFFLE_CODE = os.environ.get("DPARK_SHUFFLE_CODE", "off")

# per-shard fetch attempts before a shard counts as lost (coded mode
# only; attempts past the first cycle through replica uris).  Retries
# are cheap relative to a decode failure's lineage fallback, so keep
# this >= 2 under fault injection.
SHUFFLE_SHARD_ATTEMPTS = int(os.environ.get(
    "DPARK_SHUFFLE_SHARD_ATTEMPTS", "3") or 1)

# straggler-adaptive per-exchange code selection (ISSUE 19): "1" lets
# the scheduler price (k,m) PER SHUFFLE from the adapt store's
# per-peer fetch-tail sketches and observed decode/fault rates instead
# of paying DPARK_SHUFFLE_CODE's static parity tax everywhere —
# exchanges whose recorded peers straggle (p99/p50 over
# CODE_ADAPT_TAIL_RATIO) or decoded from parity before escalate to
# CODE_ADAPT_ESCALATE, exchanges whose peers are uniformly tight drop
# to uncoded.  Requires DPARK_ADAPT=on to steer; under
# DPARK_ADAPT=observe choices are logged (applied=false) and the
# static code runs, bit-identical.  The writer's self-describing frame
# geometry makes mixed per-shuffle codes safe on the wire.
CODE_ADAPT = os.environ.get("DPARK_CODE_ADAPT", "0") == "1"

# a recorded peer counts as a straggler when its persisted fetch-tail
# sketch shows p99/p50 at or above this ratio (and at least
# CODE_ADAPT_MIN_SAMPLES observations); below it with a bounded p99
# the exchange is priced tight and runs uncoded
CODE_ADAPT_TAIL_RATIO = float(os.environ.get(
    "DPARK_CODE_ADAPT_TAIL_RATIO", "3.0"))
CODE_ADAPT_MIN_SAMPLES = int(os.environ.get(
    "DPARK_CODE_ADAPT_MIN_SAMPLES", "8") or 1)

# the code an escalated exchange runs (parse_code grammar); the
# no-history / insufficient-samples default stays DPARK_SHUFFLE_CODE
CODE_ADAPT_ESCALATE = os.environ.get(
    "DPARK_CODE_ADAPT_ESCALATE", "rs(4,2)")

# ---------------------------------------------------------------------------
# adaptive execution (dpark_tpu/adapt.py — ISSUE 7)
# ---------------------------------------------------------------------------

# off | observe | on.  "observe" (the CI-safe default) records
# per-(program, shape class) compute/exchange/spill ms, OOM-ladder
# outcomes, combine ratios, and skew histograms into a persistent
# store but NEVER changes a plan — bit-identical to "off".  "on"
# additionally steers four decision points (wave budget seeding,
# device-vs-object path by predicted cost, skew-widened reduce sides,
# map-side-combine pricing); every steered choice is recorded as an
# `adapt` decision in the job record and bench JSON.
DPARK_ADAPT = os.environ.get("DPARK_ADAPT", "observe")

# where the stats store lives (crc-framed JSON lines, process-safe
# append; delete the directory to reset all learned budgets/costs)
DPARK_ADAPT_DIR = os.environ.get(
    "DPARK_ADAPT_DIR", os.path.join(DPARK_WORK_DIR, "adapt"))

# the append-only store compacts down to its in-memory aggregates
# (one line per key) when the file exceeds this many bytes at load —
# unbounded growth would otherwise make every process re-read and
# crc-check an ever-longer history.  0 disables compaction.
ADAPT_STORE_MAX_BYTES = int(os.environ.get(
    "DPARK_ADAPT_STORE_MAX_BYTES", str(1 << 22)) or 0)

# the object path must beat the device path by this factor of observed
# ms before the cost model declines the array path (ties keep the
# device: its compile cost amortizes across runs)
ADAPT_PATH_MARGIN = float(os.environ.get("DPARK_ADAPT_PATH_MARGIN",
                                         "0.8"))

# dominant-group fraction (max group rows / total rows) above which an
# observed histogram counts as skewed, and the widening factor applied
# to the DEFAULT reduce width on the next run of that program
ADAPT_SKEW_FRAC = float(os.environ.get("DPARK_ADAPT_SKEW_FRAC", "0.5"))
ADAPT_SKEW_WIDEN = int(os.environ.get("DPARK_ADAPT_SKEW_WIDEN",
                                      "2") or 2)

# mid-job re-planning at the stage boundary (ISSUE 19): "1" lets the
# scheduler re-partition a reduce side BEFORE launching it when the
# completed map stage's on-disk bucket sizes show hash-collision skew
# the plan-time guess missed (dominant-bucket byte fraction >=
# REPLAN_SKEW_FRAC) — a same-width salted re-split stage re-keys the
# buckets without recomputing any map task (resubmits == recomputes ==
# 0) and the choice lands as `replan_reason` on the job record plus an
# adapt "replan" record, so the NEXT run of the same call site salts
# its partitioner at plan time and skips the mid-job re-split.
# Requires DPARK_ADAPT=on to steer; observe mode records the would-be
# re-plan (applied=false) and launches the original reduce side.
REPLAN = os.environ.get("DPARK_REPLAN", "0") == "1"

# dominant-bucket byte fraction (largest reduce bucket / total bucket
# bytes across the exchange) at or above which the completed map side
# counts as skewed enough to re-split; buckets must be file://-local
# for the driver to size them (device HBM exchanges never re-split —
# their skew signal is the SegMapOp histogram, adapt decision point 3)
REPLAN_SKEW_FRAC = float(os.environ.get(
    "DPARK_REPLAN_SKEW_FRAC", "0.6"))

# floor on total exchange bytes before a re-plan is considered: tiny
# exchanges re-split slower than they run
REPLAN_MIN_BYTES = int(os.environ.get(
    "DPARK_REPLAN_MIN_BYTES", "4096") or 0)

# observed combine ratio (distinct keys / rows) above which map-side
# pre-aggregation is priced OFF (nearly every key distinct: the
# combine pass costs a sort and saves no exchange bytes)
ADAPT_COMBINE_MAX_RATIO = float(os.environ.get(
    "DPARK_ADAPT_COMBINE_MAX_RATIO", "0.6"))

# deterministic stand-in for a device HBM ceiling (bench/test aid): a
# streamed wave budget above this many rows/device raises the same
# RESOURCE_EXHAUSTED class the degradation ladder halves on, so the
# OOM ladder and the adaptive store's learned budgets can be exercised
# on backends that report no memory limit (XLA:CPU).  0 = off.
EMULATED_WAVE_OOM_ROWS = int(os.environ.get(
    "DPARK_EMULATED_WAVE_OOM_ROWS", "0") or 0)

# ---------------------------------------------------------------------------
# resident executor service (dpark_tpu/service.py — ISSUE 9)
# ---------------------------------------------------------------------------

# When set, every DparkContext in this process attaches to ONE shared
# JobServer instead of owning a scheduler: the value is the master
# spec the server runs ("local", "tpu", "tpu:2", ...).  The server
# owns the mesh + JAXExecutor for the life of the process and
# multiplexes all contexts' jobs onto it — compiled programs and the
# HBM shuffle store amortize across jobs.  "" (the default) keeps the
# one-context-one-scheduler behavior bit-identical (one `is None`
# check per seam).  Remote clients do not use this knob: they ship
# job FUNCTIONS to a served JobServer (see service.serve /
# service.ServiceClient).
DPARK_SERVICE = os.environ.get("DPARK_SERVICE", "")

# concurrent stage-execution slots in the job server's fair
# dispatcher.  Device stages additionally serialize on the executor's
# mesh lock (two concurrently dispatched collective programs wedge
# the XLA:CPU rendezvous), so extra slots buy overlap between one
# job's device stage and another's host/object-path stage.
SERVICE_SLOTS = int(os.environ.get("DPARK_SERVICE_SLOTS", "2") or 1)

# admission control: at most this many jobs RUN concurrently; further
# submissions queue (fairness weights still apply once admitted)...
SERVICE_MAX_JOBS = int(os.environ.get("DPARK_SERVICE_MAX_JOBS",
                                      "4") or 1)

# ...and the admission queue itself is bounded: a submission that
# would make more than this many jobs wait is REFUSED with an error
# instead of growing an unbounded backlog (a resident service must
# shed load, not buffer it forever).  0 (or an empty env var) means
# UNBOUNDED — explicitly opting out of load shedding.
SERVICE_QUEUE_MAX = int(os.environ.get("DPARK_SERVICE_QUEUE_MAX",
                                       "16") or 0)

# weighted round-robin fairness: this client's jobs get this many
# stage-execution turns per cycle relative to weight-1 peers (read at
# context attach; the per-job weight rides the submission)
SERVICE_WEIGHT = int(os.environ.get("DPARK_SERVICE_WEIGHT", "1") or 1)

# compiled-program cache bound (ISSUE 9 satellite): the executor's
# per-process program cache holds at most this many entries (LRU
# eviction; hit/miss/evict counters ride /metrics and the bench
# `service` section).  A resident service compiles across many jobs
# for the life of the mesh — unbounded growth was fine for one-job
# processes, not for a server.  0 = unbounded (the pre-service
# behavior).
PROGRAM_CACHE_MAX = int(os.environ.get("DPARK_PROGRAM_CACHE_MAX",
                                       "512") or 0)

# persistent AOT executable cache (ISSUE 17): off | read | on.
# "off" (the default) costs one `is None` check at the program-cache
# seam and is bit-identical to any cached run; "read" loads serialized
# executables from DPARK_AOT_CACHE_DIR but never writes (a replica
# trusting a cache it does not own); "on" additionally stores newly
# compiled programs — tmp+rename entries plus an O_APPEND index, so
# one directory is safely shared across replicas and concurrent
# writers.  Corrupt / truncated / version-mismatched entries skip
# silently and fall back to compile (the adapt-store contract).
AOT_CACHE = os.environ.get("DPARK_AOT_CACHE", "off")

# where serialized executables live (delete the directory to reset)
AOT_CACHE_DIR = os.environ.get(
    "DPARK_AOT_CACHE_DIR", os.path.join(DPARK_WORK_DIR, "aotcache"))

# boot-warming deadline: a starting JobServer spends at most this many
# milliseconds deserializing the hottest programs (ranked by observed
# compile ms x hit count from the adapt store) before serving.  0
# disables warming without disabling the cache.
AOT_WARM_BUDGET_MS = float(os.environ.get(
    "DPARK_AOT_WARM_BUDGET_MS", "2000") or 0)

# shared-computation plane (ISSUE 18): off | mem | disk.  "off" (the
# default) costs one `is None` check at the planner's probe seam and
# is bit-identical to any cached run; "mem" serves repeated sub-plans
# (and mergeable partial aggregates) from a host-memory LRU tier;
# "disk" adds a crc-framed on-disk tier that survives restarts
# alongside the AOT cache — same corruption contract (any defect
# means recompute, never an error).  Entries invalidate by source
# fingerprint: v2 tabular footer stats, (path, mtime, size) for v1.
RESULT_CACHE = os.environ.get("DPARK_RESULT_CACHE", "off")

# where disk-tier result entries live (delete the directory to reset)
RESULT_CACHE_DIR = os.environ.get(
    "DPARK_RESULT_CACHE_DIR",
    os.path.join(DPARK_WORK_DIR, "resultcache"))

# memory-tier byte budget: least-recently-served entries evict past
# it, and a single result larger than the whole budget never stores.
RESULT_CACHE_BUDGET = int(os.environ.get(
    "DPARK_RESULT_CACHE_BUDGET", str(64 << 20)) or (64 << 20))

# dcn transient-connect retry: total attempts (1 = no retry) and the
# base backoff seconds (exponential with full jitter: attempt k sleeps
# uniform in [base*2^k/2, base*2^k]).  Application-level ServerError
# stays non-retryable — only transport connect errors back off.
DCN_CONNECT_ATTEMPTS = int(os.environ.get("DPARK_DCN_CONNECT_ATTEMPTS",
                                          "3") or 1)
DCN_CONNECT_BACKOFF = float(os.environ.get("DPARK_DCN_CONNECT_BACKOFF",
                                           "0.05"))

# dcn fetch deadline + whole-request retry (ISSUE 20 satellite — these
# replace the hardcoded 30s socket timeout): every dcn/bulkplane fetch
# and tracker call uses DCN_TIMEOUT_MS as its socket deadline, and a
# transport failure (connect refused, torn stream, timeout) retries up
# to DCN_RETRIES total attempts on a fresh connection with the same
# exponential-full-jitter schedule as the connect path
# (dcn.backoff_delays).  Application-level ServerError never retries.
DCN_TIMEOUT_MS = float(os.environ.get("DPARK_DCN_TIMEOUT_MS",
                                      "30000") or 30000)
DCN_RETRIES = int(os.environ.get("DPARK_DCN_RETRIES", "3") or 1)

# peer-liveness lease (ISSUE 20 tentpole b): every successful dcn/bulk
# transfer renews the serving peer's lease for this many milliseconds.
# A transport failure AFTER the lease lapsed marks the peer suspect
# (counted once per transition as `lease_expiries` on /metrics), and
# the coded fetch path fails that peer's shard attempts fast — racing
# the parity shards from live peers instead of waiting out socket
# timeouts — falling back to lineage recompute only when parity can't
# cover the loss.  A suspect peer is re-probed after the same interval
# so a recovered process rejoins without operator action.  0 disables
# liveness tracking entirely (every peer always "alive").
PEER_LEASE_MS = float(os.environ.get("DPARK_PEER_LEASE_MS",
                                     "5000") or 0)

# crash-consistent job journal (ISSUE 20 tentpole a): off | on.  "on"
# write-ahead-logs job submission, stage completion, and the
# shuffle-output registry as crc-framed JSON lines under
# DPARK_JOURNAL_DIR, so a restarted controller replays the journal and
# resumes accepted jobs from the last completed stage — re-running
# only stages whose outputs are gone (lineage recomputes the holes).
# Off (the default) costs one `is None` check per job and stage;
# results are bit-identical either way.
DPARK_JOURNAL = os.environ.get("DPARK_JOURNAL", "off")

# where journal files live; must SURVIVE a controller restart, so the
# default sits beside (not inside) the per-session workdir.  Delete
# the directory to forget every resumable job.
DPARK_JOURNAL_DIR = os.environ.get(
    "DPARK_JOURNAL_DIR",
    os.path.join(DPARK_WORK_DIR.split(",")[0].strip() or "/tmp",
                 "journal"))

# ---------------------------------------------------------------------------
# multi-controller bulk data plane (dpark_tpu/bulkplane.py — ISSUE 12)
# ---------------------------------------------------------------------------

# Route cross-process (tcp://) shuffle buckets, coded shard frames,
# broadcast chunks, and remote service results over the chunked,
# crc-framed bulk streaming channel instead of the single-frame pickled
# host bridge.  HBM-resident flat (k, v) buckets additionally serve RAW
# COLUMN bytes that assemble zero-copy into numpy views / device_put
# batches on the receiving controller.  "0" falls back to the plain
# single-frame protocol everywhere (bisection aid); a peer that does
# not speak the bulk protocol is fallen back to per request.
BULK_PLANE = os.environ.get("DPARK_BULK_PLANE", "1") != "0"

# payload bytes per bulk stream chunk frame (each frame carries its own
# crc32, so corruption costs one re-read, not a silently wrong answer)
BULK_CHUNK_BYTES = int(os.environ.get("DPARK_BULK_CHUNK_BYTES",
                                      str(1 << 20)) or (1 << 20))

# per-peer concurrency window: at most this many bulk streams in
# flight against one peer (a reduce fan-out of n coded shard fetches
# must not open n sockets to a single serving controller at once).
# 0 = unbounded.
BULK_STREAMS_PER_PEER = int(os.environ.get(
    "DPARK_BULK_STREAMS_PER_PEER", "4") or 0)

# bounded retry on bulk-channel reads (1 = no retry): a torn stream
# (peer restarting mid-transfer) or a crc-rejected frame re-reads on a
# FRESH connection with the same exponential-full-jitter backoff
# schedule the dcn connect path uses (dcn.backoff_delays — one
# implementation, two call sites).  Application-level ServerError
# stays non-retryable.
BULK_READ_ATTEMPTS = int(os.environ.get("DPARK_BULK_READ_ATTEMPTS",
                                        "3") or 1)

# ---------------------------------------------------------------------------
# TPU-native knobs (no reference analog)
# ---------------------------------------------------------------------------

# device mesh axis name used by shard_map programs
MESH_AXIS = "parts"

# per-device bucket padding granularity for the count-exchange all_to_all
# shuffle; buckets are padded up to a multiple of this so recompilation only
# happens when the padded size class changes (power-of-two size classes).
BUCKET_PAD_GRANULARITY = 1024

# max bytes of HBM a single shuffle round may use per device before the
# chunked multi-round path kicks in (the "external merge" equivalent).
SHUFFLE_HBM_BUDGET = 2 << 30

# out-of-core streaming: a monoid reduce over columnar input larger than
# this many rows per device runs in ingest->combine->exchange waves, so
# the working set in HBM is one chunk plus the combined state (the >HBM
# pipeline of SURVEY.md 7.2 item 4)
# "auto" sizes waves to device HBM at run time (stream_chunk_rows);
# assigning a number pins the wave size exactly (tests/benchmarks force
# small chunks to exercise multi-wave machinery at toy sizes)
STREAM_CHUNK_ROWS = "auto"
_STREAM_CHUNK_ROWS_FALLBACK = 4 << 20


def _hbm_bytes_limit():
    """Per-device accelerator memory, or 0 when unknown (CPU backends
    report none).  Only called once a backend is already live."""
    global _HBM_LIMIT_CACHE
    if _HBM_LIMIT_CACHE is None:
        limit = 0
        try:
            import jax
            dev = jax.local_devices()[0]
            if dev.platform != "cpu":
                stats = dev.memory_stats() or {}
                limit = int(stats.get("bytes_limit", 0))
        except Exception:
            limit = 0
        _HBM_LIMIT_CACHE = limit
    return _HBM_LIMIT_CACHE


_HBM_LIMIT_CACHE = None


def stream_chunk_rows(row_bytes=16):
    """Effective wave size in rows per device: an explicitly assigned
    STREAM_CHUNK_ROWS wins; "auto" sizes the wave to the device's own
    HBM (VERDICT r3 #2: waves must amortize the 66 ms dispatch tunnel
    RTT — size them to memory, not to a CPU-tuned constant).

    HBM accounting: without donation, raw wave bytes/device = HBM/16 —
    the wave working set (ingest + bucketized + receive + merge copies,
    ~6x) then peaks well under half of HBM.  With DONATE_BUFFERS on,
    the per-wave programs reuse their dead input buffers in place
    (ingest -> bucketized, received -> merged), dropping the multiplier
    by roughly two copies; the budget rises to HBM/12 — but the
    pipeline also holds up to STREAM_PIPELINE_DEPTH extra ingested
    waves in flight, which is why the divisor does not drop further.

    With DPARK_ADAPT=on the persistent stats store can SEED the
    budget below the derived value: the last-known-good budget
    recorded for this row-width class (e.g. by a previous run's OOM
    degradation ladder) wins over re-deriving the memory bound and
    re-walking the halving ladder (ISSUE 7).  An explicitly assigned
    STREAM_CHUNK_ROWS always bypasses both."""
    if STREAM_CHUNK_ROWS != "auto":
        return STREAM_CHUNK_ROWS
    limit = _hbm_bytes_limit()
    if not limit:
        base = _STREAM_CHUNK_ROWS_FALLBACK
    else:
        divisor = 12 if DONATE_BUFFERS else 16
        base = max(_STREAM_CHUNK_ROWS_FALLBACK,
                   limit // (divisor * max(1, row_bytes)))
    from dpark_tpu import adapt
    return adapt.steer_wave_budget(base, row_bytes)

# text-source stages bigger than this stream in waves of splits instead
# of materializing the whole encoded dataset (same out-of-core pipeline)
STREAM_TEXT_BYTES = 1 << 28

# ---------------------------------------------------------------------------
# pane-tree windowing (dpark_tpu/panes.py + dstream.py — ISSUE 10)
# ---------------------------------------------------------------------------

# slice windowed DStreams into slide-sized PANES whose partial
# aggregates persist across ticks (cached reduced RDDs; on the tpu
# master their shuffle outputs stay HBM-resident): invertible
# reduceByKeyAndWindow updates the window in O(1) panes per slide
# (prev + new pane - expired pane) regardless of the window/slide
# ratio, and non-invertible window reduces merge O(log w) cached
# dyadic tree nodes instead of re-reducing all w panes.  "0" disables
# — every windowed op then takes the pre-pane whole-window paths (the
# parity suite's reference side, and a bisection aid).  Pane mode
# needs window % slide == 0 and slide % batch == 0; misaligned
# windows keep the old paths regardless of this knob.
STREAM_PANES = os.environ.get("DPARK_STREAM_PANES", "1") != "0"

# non-invertible pane windows below this many panes skip the dyadic
# merge tree and union their panes flat each tick (the tree's extra
# cached intermediate shuffles only amortize once O(log w) beats w).
# With DPARK_ADAPT=on the planner overrides this static split-point
# choice from OBSERVED per-tick pane costs (adapt.steer_pane_mode).
STREAM_PANE_TREE_MIN = int(os.environ.get(
    "DPARK_STREAM_PANE_TREE_MIN", "8") or 0)

# default allowed event-time lateness in seconds for windowed ops that
# set an eventTime extractor without an explicit lateness= argument:
# the watermark trails the max observed event time by this much, and
# records older than the watermark drop (counted per stream).  Late
# records inside the bound patch ONLY their pane, never the window.
STREAM_ALLOWED_LATENESS = float(os.environ.get(
    "DPARK_STREAM_LATENESS", "0") or 0)

# bounded late-data buffer: at most this many late records are admitted
# per pane patch per tick — anything beyond drops (counted as
# late_dropped) so a storm of stragglers cannot grow a patch job
# without bound.  0 = unbounded.
STREAM_LATE_BUFFER_ROWS = int(os.environ.get(
    "DPARK_STREAM_LATE_BUFFER", "100000") or 0)

# ---------------------------------------------------------------------------
# overlapped wave pipeline (backend/tpu executor stream loops)
# ---------------------------------------------------------------------------

# how many waves the host runs AHEAD of the device: depth >= 1
# double-buffers device ingest (wave k+1 device_puts while wave k
# computes) and defers each wave's host readback/spill by one wave so
# D2H transfers ride behind the next wave's compute.  0 disables the
# overlap entirely (serial waves — the pre-pipeline behavior, useful
# when bisecting); values above 1 only deepen the host-side
# tokenize/ingest lookahead, at one extra ingested wave of HBM each.
STREAM_PIPELINE_DEPTH = int(os.environ.get("DPARK_PIPELINE_DEPTH",
                                           "1") or 0)

# donate dead input buffers to the per-wave jitted programs (ingest ->
# narrow/bucketize, received -> merge, batch -> concat): XLA reuses
# them in place, so a wave holds ONE copy of its working set in HBM
# instead of two.  Streamed paths only — in-core programs keep their
# inputs alive (result cache / shuffle store leaves must survive the
# call).  stream_chunk_rows raises the auto wave budget when this is
# on (see its HBM-accounting note).  "0" disables (e.g. when bisecting
# an aliasing bug).
DONATE_BUFFERS = os.environ.get("DPARK_DONATE_BUFFERS", "1") != "0"

# background spill writer for the spilled-run stream: compress+write of
# per-partition runs happens on a dedicated thread with a bounded
# queue, off the wave loop ("0" = write inline, serial).  Writer
# errors surface on the next enqueue or at end-of-stream flush.
SPILL_WRITER = os.environ.get("DPARK_SPILL_WRITER", "1") != "0"

# collective tests over the virtual CPU mesh need roughly one host CPU
# per mesh device: an 8-device all_to_all on a 2-CPU container wedges
# (XLA:CPU collectives rendezvous across intra-process threads).  The
# test harness skips mesh-marked tests when os.cpu_count() is below
# this; DPARK_MESH_TEST_DEVICES=0 forces them to run anyway.
MESH_TEST_DEVICES = int(os.environ.get("DPARK_MESH_TEST_DEVICES",
                                       "8") or 0)

# thread-pool width for text-split tokenize/encode (the C++ tokenizer
# releases the GIL, so splits tokenize truly concurrently; the reference
# runs hot loop #1 on every executor — SURVEY.md 3.1).  0 = cpu count.
INGEST_THREADS = int(os.environ.get("DPARK_INGEST_THREADS", "0") or 0)

# composite (tuple) keys on the device path: records keyed by a FLAT
# tuple of up to MAX_KEY_LEAVES numeric scalars — ((user, item), v),
# ((src, dst), w) — classify onto the array path end to end (hash
# destinations via the pair-extended phash, sort/segment/combine over
# all key columns, tuple repacked at egest).  "0" disables (tuple keys
# then take the host object path, the pre-PR behavior — useful when
# bisecting).  Nested key tuples and non-numeric key leaves always
# fall back; the `host-fallback-key` lint rule reports why.
TUPLE_KEYS = os.environ.get("DPARK_TUPLE_KEYS", "1") != "0"

# widest flat tuple key the device path accepts: each extra key leaf is
# one more sort operand in every shuffle program, so keep this small
# (2-3 covers the (user, item) / (src, dst) shapes real jobs use)
MAX_KEY_LEAVES = int(os.environ.get("DPARK_MAX_KEY_LEAVES", "4") or 4)

# default dtype for device-side values
DEFAULT_DTYPE = "int32"

# narrow int64 columns to int32 on the all_to_all wire when a runtime
# min/max guard proves every valid value fits (TPUs have no native i64
# datapath: XLA emulates i64 as i32 pairs, doubling ICI bytes).  Compute
# stays i64 either way; set 0 to disable (e.g. when bisecting parity).
NARROW_EXCHANGE = os.environ.get("DPARK_NARROW_EXCHANGE", "1") != "0"

# graph-build-time rewrite of groupByKey().mapValue(provable aggregate)
# to a map-side-combining combineByKey (rdd._group_agg_rewrite): the
# classic combiner optimization, exchange volume O(distinct keys).
# "0" disables; the device SegAggOp path then serves these chains.
# FLOAT CAVEAT: the rewrite reassociates the fold — sum/mean over float
# values pre-combine map-side, so low-order bits depend on partitioning
# and combine order on EVERY master (including local), where the
# un-rewritten groupByKey summed each group's list in row order.
# Integer aggregates and min/max are exact either way.
GROUP_AGG_REWRITE = os.environ.get("DPARK_GROUP_AGG_REWRITE",
                                   "1") != "0"

# device segmented apply (SegMapOp): groupByKey().mapValues(f) with an
# arbitrary TRACEABLE per-group f (beyond the five provable aggregates)
# runs on device as a vmap over power-of-two padded group buckets.
# Admission additionally verifies f is padding-invariant (zero-pad or
# repeat-last-pad, checked on seeded samples at classification time);
# functions that need the true group length (mean-like shapes beyond
# the provable forms) keep the host path with a recorded
# fallback_reason.  "0" disables (host object path, the pre-PR
# behavior — bisection aid).
SEG_MAP = os.environ.get("DPARK_SEG_MAP", "1") != "0"

# compile-budget guard for the segmented apply: each power-of-two group
# bucket is one trace/compile of the user's per-group function, so a
# tiny input with many buckets can spend more wall time compiling than
# computing.  A stage whose estimated row count is below
# (estimated buckets x this many rows) degrades to the host loop with
# fallback_reason "seg_map compile budget".  0 disables the guard
# (every eligible stage rides; the default — compiles are cached by
# structural identity, so steady-state streams pay once).
SEG_MIN_ROWS_PER_TRACE = int(os.environ.get(
    "DPARK_SEG_MIN_ROWS_PER_TRACE", "0") or 0)

# general traceable updateStateByKey on device: state rides as
# HBM-resident columns and each batch cogroups with its padded value
# segments through the same SegMapOp machinery (update(prev, values)
# traced twice — with a prev scalar and with the literal None).  "0"
# keeps the host cogroup path.
SEG_STATE = os.environ.get("DPARK_SEG_STATE", "1") != "0"

# device->host egest: int64 scalar columns at least this large are
# min/max-probed and ride the link as int32 when every valid value fits
# (the axon tunnel reads back at ~37 MB/s — BENCH_REAL_r03.md — so
# halving collect() bytes halves its wall time).  Tests shrink this to
# exercise the path at toy sizes.
EGEST_NARROW_MIN_BYTES = 8 << 20

# collect()s bigger than this log a reduce-before-collect warning
# (the reference's executor result-size limit analog, SURVEY.md
# section 2.1 executor row: oversized inline results get flagged)
EGEST_WARN_BYTES = 256 << 20

# when set, the tpu executor writes a jax.profiler trace here for the
# whole session (view with tensorboard / xprof).  NOTE: this knob was
# DPARK_TRACE_DIR before ISSUE 8; that name now belongs to the span
# trace plane's spool directory below.
XPROF_DIR = os.environ.get("DPARK_XPROF_DIR")

# ---------------------------------------------------------------------------
# trace plane (dpark_tpu/trace.py — ISSUE 8)
# ---------------------------------------------------------------------------

# off | ring | spool.  "off" (the default) costs one `is None` check
# per site and is bit-identical to any traced run; "ring" keeps spans
# in a bounded in-memory ring (served live by the web UI's
# /api/trace); "spool" additionally appends crc-framed JSON lines to
# per-process files under DPARK_TRACE_DIR — worker-process spans and
# fault/decode counters then merge back into the driver's job records,
# and tools/dtrace exports the merged Chrome trace / critical path.
DPARK_TRACE = os.environ.get("DPARK_TRACE", "off")

# where spool files live (one trace-<host>-<pid>.jsonl per process;
# delete the directory to reset)
DPARK_TRACE_DIR = os.environ.get(
    "DPARK_TRACE_DIR", os.path.join(DPARK_WORK_DIR, "trace"))

# bounded in-memory span ring per process (ring AND spool modes)
TRACE_RING_SPANS = int(os.environ.get("DPARK_TRACE_RING", "4096")
                       or 4096)

# per-process spool byte cap: span writes stop past this (counted as
# dropped); counter events always land (they are the worker-counter
# merge substrate).  0 = unbounded.
TRACE_SPOOL_MAX_BYTES = int(os.environ.get(
    "DPARK_TRACE_SPOOL_MAX_BYTES", str(32 << 20)) or 0)

# ---------------------------------------------------------------------------
# online health plane (dpark_tpu/health.py — ISSUE 14)
# ---------------------------------------------------------------------------

# off | on.  "on" (the default) installs the streaming health sink:
# every record the TRACE plane emits additionally folds into bounded
# per-site latency sketches (log2 buckets, p50/p95/p99 estimates) and
# event-rate counters — /api/health, the bench `health` section, and
# the adapt-store site-tail handoff read them.  With DPARK_TRACE=off
# nothing is emitted and the sink is inert either way; "off" removes
# even the per-record `is None` check's target (the faults/trace
# contract: off-mode job results are bit-identical to on).
DPARK_HEALTH = os.environ.get("DPARK_HEALTH", "on")

# bounded sketch registries: at most this many per-site sketches (past
# the cap, new sites fold into their base site name) and this many
# per-(job, stage) fetch sketches (oldest evicts) — streaming
# aggregation must hold bounded memory no matter how long the process
# serves
HEALTH_MAX_SITES = int(os.environ.get("DPARK_HEALTH_MAX_SITES",
                                      "256") or 256)
HEALTH_STAGE_SKETCHES = int(os.environ.get(
    "DPARK_HEALTH_STAGE_SKETCHES", "256") or 256)

# minimum seconds between site-tail persists into the adapt store
# (health.persist_site_tails runs at job finish; a streaming job
# finishing one tick-job per second must not append per tick).
# Deltas are persisted, so the throttle trades freshness, not truth.
HEALTH_PERSIST_MIN_S = float(os.environ.get(
    "DPARK_HEALTH_PERSIST_S", "30") or 0)

# /api/health grading thresholds (yellow, red) — evidence ships with
# every verdict so an operator sees the number AND the bar it crossed
HEALTH_FETCH_P99_YELLOW_MS = float(os.environ.get(
    "DPARK_HEALTH_FETCH_P99_YELLOW_MS", "250"))
HEALTH_FETCH_P99_RED_MS = float(os.environ.get(
    "DPARK_HEALTH_FETCH_P99_RED_MS", "1000"))
HEALTH_DCN_P99_YELLOW_MS = float(os.environ.get(
    "DPARK_HEALTH_DCN_P99_YELLOW_MS", "500"))
HEALTH_DCN_P99_RED_MS = float(os.environ.get(
    "DPARK_HEALTH_DCN_P99_RED_MS", "2000"))
HEALTH_WAVE_P99_YELLOW_MS = float(os.environ.get(
    "DPARK_HEALTH_WAVE_P99_YELLOW_MS", "5000"))
HEALTH_WAVE_P99_RED_MS = float(os.environ.get(
    "DPARK_HEALTH_WAVE_P99_RED_MS", "30000"))
HEALTH_SPILL_P99_YELLOW_MS = float(os.environ.get(
    "DPARK_HEALTH_SPILL_P99_YELLOW_MS", "500"))
HEALTH_SPILL_P99_RED_MS = float(os.environ.get(
    "DPARK_HEALTH_SPILL_P99_RED_MS", "5000"))
HEALTH_ERROR_RATE_YELLOW = float(os.environ.get(
    "DPARK_HEALTH_ERROR_RATE_YELLOW", "0.01"))
HEALTH_ERROR_RATE_RED = float(os.environ.get(
    "DPARK_HEALTH_ERROR_RATE_RED", "0.10"))

# per-tenant SLO accounting (service.py — ISSUE 14): the default
# per-job latency target in ms for tenants that declare none
# explicitly (ServiceClient(..., slo_ms=) / ClientScheduler slo_ms).
# 0 = no SLO tracked for undeclared tenants.
SERVICE_SLO_MS = float(os.environ.get("DPARK_SERVICE_SLO", "0") or 0)

# attainment target backing the burn-rate math: a burn of 1.0 means
# violations are consuming the (1 - target) error budget exactly as
# fast as allowed; 2.0 means twice as fast (the classic multi-window
# burn alert).  Windows are the short/long burn horizons in seconds.
SERVICE_SLO_TARGET = float(os.environ.get("DPARK_SERVICE_SLO_TARGET",
                                          "0.99"))
SERVICE_SLO_WINDOWS = tuple(
    float(w) for w in os.environ.get("DPARK_SERVICE_SLO_WINDOWS",
                                     "60,600").split(",") if w)
SERVICE_SLO_BURN_YELLOW = float(os.environ.get(
    "DPARK_SERVICE_SLO_BURN_YELLOW", "1.0"))
SERVICE_SLO_BURN_RED = float(os.environ.get(
    "DPARK_SERVICE_SLO_BURN_RED", "2.0"))

# ---------------------------------------------------------------------------
# resource attribution plane (dpark_tpu/ledger.py — ISSUE 15)
# ---------------------------------------------------------------------------

# off | on.  "on" (the default) installs the ledger sink as a second
# TracePlane.record consumer (the health.py contract — one `is None`
# check per record when off, on/off job results bit-identical): spans
# fold into bounded merge-associative per-(tenant, job, stage,
# program-signature) resource accounts — device wall ms, compile ms,
# mesh-lock wait ms, HBM byte-seconds, shuffle/bulk/spill bytes.
# /api/ledger, per-tenant /metrics counters, and the dtrace --ledger
# offline twin read them.  With DPARK_TRACE=off nothing is emitted and
# the ledger is inert either way.
DPARK_LEDGER = os.environ.get("DPARK_LEDGER", "on")

# bounded account registry: at most this many (job, stage, signature)
# account keys; past the cap, new keys fold into their job's coarse
# account (stage/sig dropped) so TOTALS stay honest no matter how many
# distinct programs a resident server serves.  0 = unbounded.
LEDGER_MAX_KEYS = int(os.environ.get("DPARK_LEDGER_MAX_KEYS",
                                     "512") or 0)

# static program cost profiles (the items-2/3 pricing prior): at first
# dispatch of a compiled stage program, capture jax cost analysis
# keyed by fuse.plan_adapt_signature and persist it to the adapt store
# (adapt.record_program_cost).
#   lower    (default) jitted.lower(args).cost_analysis() only — a
#            host-side re-trace, no extra XLA compile (safe on real
#            chips where a compile runs 30-150s)
#   compile  additionally .compile().memory_analysis() for measured
#            peak-HBM fields — ONE extra XLA compile per program
#            signature (cheap on XLA:CPU; tests/CI use this)
#   off      capture nothing
LEDGER_COST = os.environ.get("DPARK_LEDGER_COST", "lower")

# conservation grading: attributed per-tenant device-seconds must sum
# to at least this fraction of the measured mesh-busy time (the
# mesh-lock hold total) before /api/health grades attribution yellow —
# device time the ledger cannot name is untracked consumption
LEDGER_CONSERVE_YELLOW = float(os.environ.get(
    "DPARK_LEDGER_CONSERVE_YELLOW", "0.9"))

# concurrency sanitizer plane (dpark_tpu/locks.py — ISSUE 16): the
# named-lock registry records per-thread lock acquisition order and
# merges it into a process-wide graph, reporting lock-order cycles
# even when no deadlock fired.
#   off     no sanitizer; every named lock costs one `is None` check
#           per acquisition (the standard plane off-mode contract)
#   record  record edges; cycles() / report() surface inversions —
#           CI arms this across the whole test suite
#   strict  the acquisition that CLOSES a cycle (or self-deadlocks a
#           non-reentrant lock) raises LockOrderError pre-acquire
DPARK_LOCKCHECK = os.environ.get("DPARK_LOCKCHECK", "off")

# shard/bucket fetch result waits (lockcheck `unbounded-wait` fixes):
# a wedged peer read on a daemon fetch thread must surface as a fetch
# failure the scheduler can recover from, not park the driver forever.
# Seconds; generous — only a true wedge ever waits this long.
SHUFFLE_FETCH_WAIT_S = float(os.environ.get(
    "DPARK_SHUFFLE_FETCH_WAIT_S", "300") or 300)

# flight recorder (ISSUE 14): warning-and-above events ALWAYS land in
# a bounded in-memory ring (even with DPARK_TRACE=off); setting this
# directory additionally dumps a crc-framed snapshot (ring + health
# sketches + recovery summary + adapt decisions) there on job abort,
# stage degrade, or SIGUSR2 — post-mortem via tools/dtrace --flight.
# "" (the default) keeps the ring armed but writes nothing.
DPARK_FLIGHT_DIR = os.environ.get("DPARK_FLIGHT_DIR", "")

# flight ring capacity and the per-process dump cap (a crash loop
# must not fill the disk with snapshots)
FLIGHT_RING_EVENTS = int(os.environ.get("DPARK_FLIGHT_RING", "512")
                         or 512)
FLIGHT_MAX_DUMPS = int(os.environ.get("DPARK_FLIGHT_MAX_DUMPS", "16")
                       or 0)

# trace-overhead-hint lint rule: warn when DPARK_TRACE=spool and a
# reduce task's estimated spool writes (one fetch span per parent map
# bucket + the task spans) exceed this — tiny-task jobs then spend
# comparable time spooling and computing
TRACE_SPAN_WRITES_PER_TASK = int(os.environ.get(
    "DPARK_TRACE_SPAN_WRITES_PER_TASK", "64") or 64)

# ---------------------------------------------------------------------------
# columnar query plane (dpark_tpu/query/ — ISSUE 13)
# ---------------------------------------------------------------------------

# Lower table/SQL DSL actions through the rule-driven query planner:
# column-pruned vectorized tabular scans (filters evaluate over column
# batches before any row tuple materializes; chunks skip via footer
# min/max stats), group-by aggregates onto the device exchange /
# SegAggOp / SegMapOp, equi-joins onto the device join, string keys
# dictionary-encoded.  "0" pins every table action to the host row
# path (the pre-plan behavior — bisection aid and the bench A/B's
# baseline side).  Operators the planner cannot PROVE equivalent keep
# the host path per query, with the reason recorded
# (`table-host-fallback` lint rule + the planner's decision log).
QUERY_PLAN = os.environ.get("DPARK_QUERY", "1") != "0"

# ---------------------------------------------------------------------------
# pre-flight plan linter (dpark_tpu/analysis/)
# ---------------------------------------------------------------------------

# off | warn | error.  Every runJob lints the submitted lineage first:
# "warn" logs each finding once per process; "error" refuses a plan
# carrying error-severity findings (e.g. monoid-multileaf — the
# round-5 silent-wrong-answer shape) with PlanLintError BEFORE any
# task launches.  The env var wins at read time (analysis.lint_mode)
# so a single run can be escalated without editing conf.
DPARK_LINT = os.environ.get("DPARK_LINT", "warn")

# plan-wide-depth rule: more chained shuffles than this on one
# uncheckpointed lineage path draws a warning (0 disables the rule)
LINT_WIDE_DEPTH = int(os.environ.get("DPARK_LINT_WIDE_DEPTH", "4"))

# pre-flight walk budget in lineage nodes: plans bigger than this are
# linted over a truncated prefix (logged at debug) so per-tick lint
# cost on long-running streams stays bounded — streaming lineages grow
# until checkpoint truncation and each tick submits a fresh final rdd
LINT_MAX_NODES = int(os.environ.get("DPARK_LINT_MAX_NODES", "500"))

# monoid-multileaf record probing: "shallow" reads only data already
# resident on the driver (parallelize slices / unions of them);
# "deep" additionally replays narrow per-record user functions over
# the <=4 probe rows (opt-in: user functions may carry side effects,
# e.g. accumulator bumps); "off" disables probing entirely
LINT_PROBE = os.environ.get("DPARK_LINT_PROBE", "shallow")


def load_conf(path):
    """Execute a Python conf file and overlay module-level constants.

    Reference parity: dpark/conf.py (load_conf).
    """
    spec = importlib.util.spec_from_file_location("dpark_user_conf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    g = globals()
    for k in dir(mod):
        if k.isupper() and k in g:
            g[k] = getattr(mod, k)


_user_conf = os.environ.get("DPARK_CONF")
if _user_conf and os.path.exists(_user_conf):
    load_conf(_user_conf)
