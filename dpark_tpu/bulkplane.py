"""Multi-controller bulk data plane (ISSUE 12 tentpole).

Every cross-process byte in this repo used to funnel through the
single-frame pickled host bridge: one ``("bucket", ...)`` request, one
``pickle.dumps`` of Python row tuples, one monolithic response.  Coded
MapReduce and "Leveraging Coding Techniques for Speeding up Distributed
Computing" (PAPERS.md) both treat the inter-worker exchange as THE
dominant distributed cost — this module makes that path real: a
chunked, crc-framed, streaming byte channel over the existing dcn
framed transport, with zero-copy assembly into numpy views /
``device_put`` batches on the receiving controller.

Serving side (``serve``, reached through ``BucketServer._serve`` for
any ``bulk_*`` request kind):

* ``bulk_bucket`` — a map-output bucket.  Disk buckets stream the file
  bytes; HBM-resident flat ``(k, v)`` buckets serve RAW COLUMN bytes
  (``shuffle.HBM_COL_EXPORTERS`` — no per-row pickling, the pickled
  bridge's dominant cost); anything else falls back to the exporter's
  pickled payload, still chunk-framed on the bulk channel.
* ``bulk_shard`` — ONE framed erasure shard (ISSUE 6), so
  ``read_bucket_any``'s fastest-k-of-n decode race runs
  process-to-process.  An empty stream is the miss sentinel.
* ``bulk_bcast`` — one broadcast chunk file (the P2P fan-out rides the
  same channel).

Fetch side (``fetch`` + the typed helpers): pooled per-peer
connections, a per-peer concurrency WINDOW
(``conf.BULK_STREAMS_PER_PEER``), per-frame crc verification BEFORE
any byte is interpreted, and bounded retry with the dcn connect path's
exponential-full-jitter backoff (``dcn.backoff_delays`` — one
implementation, two call sites).  A torn stream (peer death
mid-transfer) or a crc-rejected frame costs a re-read on a fresh
connection, then surfaces as the transport error the shuffle layer
already translates into FetchFailed.  The ``dcn.transfer`` chaos site
fires per chunk on BOTH sides, so mid-stream connection loss and frame
corruption are deterministically injectable (tests/test_bulkplane.py).

Observability: per-peer bytes sent/received counters, an
active-stream gauge, and retry/corrupt/torn counters (``stats()`` —
/metrics exports them); every fetch and serve is a ``dcn.bulk.*``
trace span, DISTINCT from the plain protocol's ``dcn.transfer`` spans,
which is how the 2-process parity suite asserts the hot path never
touched the pickled bridge.

With ``conf.BULK_PLANE`` off nothing here is imported on the hot path.
"""

import os
import pickle
import threading
import time

from dpark_tpu import dcn
from dpark_tpu.utils.log import get_logger

logger = get_logger("bulkplane")


class BulkUnsupported(Exception):
    """The peer does not speak the bulk protocol (an old server's
    'unknown request' error).  Callers fall back to the plain
    single-frame protocol for this request — never retried here."""


class BulkCorrupt(IOError):
    """A bulk frame failed its crc32 (or the stream's advertised
    geometry) — re-read on a fresh connection up to
    conf.BULK_READ_ATTEMPTS times, then surfaced to the caller."""


# ---------------------------------------------------------------------------
# counters (per-process; /metrics and the per-stage remote-fetch bytes
# accounting read them)
# ---------------------------------------------------------------------------

class _Counters:
    def __init__(self):
        self.lock = threading.Lock()
        self.sent = {}              # peer host -> bytes served
        self.received = {}          # peer uri -> bytes fetched
        self.total_sent = 0
        self.total_received = 0
        self.streams = 0            # completed fetch streams
        self.active = 0             # in-flight fetch streams (gauge)
        self.retries = 0
        self.corrupt_frames = 0
        self.torn_streams = 0


_C = _Counters()


def _count_sent(peer, nbytes, nchunks):
    with _C.lock:
        _C.sent[peer] = _C.sent.get(peer, 0) + nbytes
        _C.total_sent += nbytes


def _count_received(uri, nbytes):
    with _C.lock:
        _C.received[uri] = _C.received.get(uri, 0) + nbytes
        _C.total_received += nbytes
        _C.streams += 1


def total_received_bytes():
    """Cumulative bulk bytes fetched by this process (cheap int read —
    the scheduler diffs it around a stage to attribute remote-fetch
    bytes per stage)."""
    return _C.total_received


def stats():
    """Snapshot for /metrics and the bench artifact."""
    with _C.lock:
        return {"sent": dict(_C.sent), "received": dict(_C.received),
                "total_sent": _C.total_sent,
                "total_received": _C.total_received,
                "streams": _C.streams, "active": _C.active,
                "retries": _C.retries,
                "corrupt_frames": _C.corrupt_frames,
                "torn_streams": _C.torn_streams}


def reset_counters():
    global _C
    _C = _Counters()


# ---------------------------------------------------------------------------
# per-peer connection pool + concurrency window
# ---------------------------------------------------------------------------

class _PeerPool:
    """Pooled sockets per peer uri: concurrent streams each check out
    their own socket (a bulk stream owns its connection until the last
    advertised frame), idle sockets are reused — the shard fan-out
    must not pay one TCP handshake per frame.  A socket that saw any
    error is closed, never returned."""

    IDLE_PER_PEER = 4

    def __init__(self):
        self.lock = threading.Lock()
        self.free = {}

    def acquire(self, uri, timeout):
        with self.lock:
            socks = self.free.get(uri)
            if socks:
                return socks.pop()
        return dcn._connect(uri, timeout)

    def release(self, uri, sock, broken):
        if broken:
            try:
                sock.close()
            except OSError:
                pass
            return
        with self.lock:
            idle = self.free.setdefault(uri, [])
            idle.append(sock)
            while len(idle) > self.IDLE_PER_PEER:
                old = idle.pop(0)
                try:
                    old.close()
                except OSError:
                    pass

    def close(self):
        with self.lock:
            for socks in self.free.values():
                for s in socks:
                    try:
                        s.close()
                    except OSError:
                        pass
            self.free.clear()


_POOL = _PeerPool()
_windows = {}
_windows_lock = threading.Lock()


def _window(uri):
    """The per-peer stream window (None = unbounded)."""
    from dpark_tpu import conf
    cap = int(getattr(conf, "BULK_STREAMS_PER_PEER", 0) or 0)
    if cap <= 0:
        return None
    with _windows_lock:
        sem = _windows.get(uri)
        if sem is None:
            sem = _windows[uri] = threading.BoundedSemaphore(cap)
        return sem


# ---------------------------------------------------------------------------
# fetch side
# ---------------------------------------------------------------------------

def _recv_into(sock, mv):
    """recv straight into the assembly buffer (zero-copy: the payload
    lands exactly once, in its final position)."""
    got = 0
    n = len(mv)
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if not r:
            raise ConnectionError("peer closed mid-stream")
        got += r


def _read_stream(sock, req):
    """One bulk request/response on an open socket: returns
    (meta, memoryview of the assembled payload).  Every frame crc is
    verified before its bytes are interpreted; with DPARK_DCN_SECRET
    set, the header and every chunk additionally carry an HMAC tag
    verified before use (same contract as the plain protocol)."""
    import hashlib
    import hmac as hmac_mod
    import struct
    from dpark_tpu import faults
    from dpark_tpu.utils import unframe_jsonl
    blob = dcn._encode_req(req)
    sock.sendall(struct.pack("!I", len(blob)) + blob)
    status, n = struct.unpack("!BQ", dcn._recv_exact(sock, 9))
    secret = dcn._secret()
    if status != dcn.BULK_STATUS:
        payload = dcn._recv_exact(sock, n)
        if secret:
            tag = dcn._recv_exact(sock, 32)
            want = hmac_mod.new(secret, bytes([status]) + payload,
                                hashlib.sha256).digest()
            if not hmac_mod.compare_digest(tag, want):
                raise dcn.ServerError("bulk peer: response MAC mismatch")
        if status == 1:
            msg = payload.decode("utf-8", "replace")
            if msg.startswith("unknown request") \
                    or msg.startswith("unknown service request"):
                raise BulkUnsupported(msg)
            raise dcn.ServerError("bulk peer: %s" % msg)
        raise BulkCorrupt("expected a bulk stream, got status %d"
                          % status)
    header = dcn._recv_exact(sock, n)
    if secret:
        tag = dcn._recv_exact(sock, 32)
        want = hmac_mod.new(secret, bytes([dcn.BULK_STATUS]) + header,
                            hashlib.sha256).digest()
        if not hmac_mod.compare_digest(tag, want):
            raise dcn.ServerError("bulk peer: header MAC mismatch")
    recs, skipped = unframe_jsonl(header)
    if skipped or not recs:
        with _C.lock:
            _C.corrupt_frames += 1
        raise BulkCorrupt("bulk header failed its crc frame")
    meta = recs[0]
    total = int(meta.get("total_bytes", 0))
    nchunks = int(meta.get("nchunks", 0))
    buf = bytearray(total)
    view = memoryview(buf)
    off = 0
    for i in range(nchunks):
        crc, ln = dcn.BULK_FRAME.unpack(
            dcn._recv_exact(sock, dcn.BULK_FRAME.size))
        if off + ln > total:
            with _C.lock:
                _C.corrupt_frames += 1
            raise BulkCorrupt(
                "chunk %d overruns the advertised stream (%d + %d > %d)"
                % (i, off, ln, total))
        chunk = view[off:off + ln]
        _recv_into(sock, chunk)
        if faults._PLANE is not None:
            # chaos site, fetch side: corrupt flips payload bytes the
            # crc below must catch; raise simulates mid-stream loss
            mutated = faults.hit("dcn.transfer", bytes(chunk))
            if mutated is not None and len(mutated) == ln:
                chunk[:] = mutated
        if secret:
            tag = dcn._recv_exact(sock, 32)
            want = hmac_mod.new(secret, chunk,
                                hashlib.sha256).digest()
            if not hmac_mod.compare_digest(tag, want):
                # a mid-stream chunk MAC mismatch is indistinguishable
                # from line corruption, so it keeps the crc path's
                # BOUNDED RETRY (a persistent attacker still exhausts
                # the attempts and surfaces as FetchFailed) — unlike
                # the pre-stream header/response MACs, where a
                # mismatch means the peer itself is not ours
                # (ServerError, never retried)
                with _C.lock:
                    _C.corrupt_frames += 1
                raise BulkCorrupt("chunk %d of %s failed its MAC"
                                  % (i, req[0]))
        if dcn.wire_crc(chunk) != crc:
            with _C.lock:
                _C.corrupt_frames += 1
            raise BulkCorrupt("chunk %d of %s failed its crc32"
                              % (i, req[0]))
        off += ln
    if off != total:
        with _C.lock:
            _C.corrupt_frames += 1
        raise BulkCorrupt("stream ended at %d of %d advertised bytes"
                          % (off, total))
    return meta, view


def fetch(uri, req, timeout=None):
    """One bulk request against a tcp:// peer with bounded retry +
    backoff; returns (meta, payload memoryview).  ServerError (the
    peer answered; asking again cannot help) and BulkUnsupported (the
    peer predates the protocol; the caller falls back to the plain
    path) pass through unretried — only transport errors and
    crc-rejected frames re-read on a fresh connection.  The socket
    deadline comes from conf.DCN_TIMEOUT_MS (ISSUE 20 satellite) and
    every outcome feeds the peer-liveness leases."""
    from dpark_tpu import conf, trace
    timeout = dcn._timeout_s(timeout)
    attempts = max(1, int(getattr(conf, "BULK_READ_ATTEMPTS", 1) or 1))
    delays = dcn.backoff_delays(attempts)
    win = _window(uri)
    if win is not None:
        win.acquire()
    with _C.lock:
        _C.active += 1
    last = None
    try:
        with trace.span("dcn.bulk.fetch", "dcn", kind=str(req[0]),
                        uri=uri) as sp:
            for k in range(attempts):
                try:
                    sock = _POOL.acquire(uri, timeout)
                except (ConnectionError, OSError):
                    # connect itself failed (after _connect's own
                    # bounded retries): the strongest death signal
                    dcn.note_peer_fail(uri)
                    raise
                ok = False
                try:
                    meta, view = _read_stream(sock, req)
                    ok = True
                except (dcn.ServerError, BulkUnsupported):
                    dcn.note_peer_ok(uri)   # the peer IS answering
                    raise
                except BulkCorrupt as e:
                    last = e
                except (ConnectionError, OSError) as e:
                    with _C.lock:
                        _C.torn_streams += 1
                    dcn.note_peer_fail(uri)
                    last = e
                finally:
                    _POOL.release(uri, sock, broken=not ok)
                if ok:
                    dcn.note_peer_ok(uri)
                    _count_received(uri, len(view))
                    if sp is not trace._NOOP:
                        sp.args["bytes"] = len(view)
                        sp.args["attempts"] = k + 1
                    return meta, view
                d = next(delays, None)
                if d is None:
                    break
                with _C.lock:
                    _C.retries += 1
                logger.debug("bulk read from %s failed (%s); retry "
                             "%d/%d in %.3fs", uri, last, k + 1,
                             attempts - 1, d)
                time.sleep(d)
        # flight recorder (ISSUE 14): every retry burned — a
        # warning-and-above event, armed even with DPARK_TRACE=off
        trace.flight("dcn.bulk.failed", "dcn", uri=uri,
                     kind=str(req[0]), attempts=attempts,
                     error=type(last).__name__ if last else "?")
        raise last
    finally:
        with _C.lock:
            _C.active -= 1
        if win is not None:
            win.release()


# -- typed fetch helpers ----------------------------------------------------

def cols_from_buf(meta, view):
    """Assemble the advertised column leaves as ZERO-COPY numpy views
    over the received buffer (np.frombuffer — the bytes are never
    copied again after landing off the socket)."""
    import numpy as np
    cols = []
    off = 0
    for leaf in meta.get("leaves", ()):
        dt = np.dtype(str(leaf["dtype"]))
        cnt = int(leaf["count"])
        cols.append(np.frombuffer(view, dtype=dt, count=cnt,
                                  offset=off))
        off += dt.itemsize * cnt
    return cols


def device_put_cols(meta, view, device=None):
    """The receiving controller's device ingest: the zero-copy column
    views go straight to jax.device_put — no host row materialization
    anywhere between the socket and HBM."""
    import jax
    return [jax.device_put(c, device) if device is not None
            else jax.device_put(c) for c in cols_from_buf(meta, view)]


def _items_from_cols(meta, view):
    cols = cols_from_buf(meta, view)
    if not cols:
        return []
    ks, vs = cols[0].tolist(), cols[1].tolist()
    if meta.get("no_combine"):
        # the host merge contract expects (k, combiner=[v]) for
        # no-combine rows — same wrap as executor._export_one
        return [(k, [v]) for k, v in zip(ks, vs)]
    return list(zip(ks, vs))


def fetch_bucket_items(uri, shuffle_id, map_id, reduce_id):
    """One map-output bucket over the bulk channel, as (k, combiner)
    items — the drop-in for the pickled ``("bucket", ...)`` bridge.
    Columnar streams reconstruct the identical rows the bridge would
    have pickled (server and client both materialize via .tolist())."""
    meta, view = fetch(uri, ("bulk_bucket", shuffle_id, map_id,
                             reduce_id))
    if meta.get("kind") == "cols":
        return _items_from_cols(meta, view)
    from dpark_tpu.utils import decompress
    return pickle.loads(decompress(bytes(view)))


def fetch_shard(uri, shuffle_id, map_id, reduce_id, idx):
    """One framed erasure shard over the bulk channel (the remote unit
    of the fastest-k-of-n decode race).  b'' is the miss sentinel,
    exactly like the plain ``bucket_shard`` protocol."""
    meta, view = fetch(uri, ("bulk_shard", shuffle_id, map_id,
                             reduce_id, idx))
    return bytes(view)


def fetch_bcast(uri, bid, i, timeout=30):
    """One broadcast chunk over the bulk channel (P2P fan-out rides
    the same frames, counters, and retry schedule as shuffle data)."""
    meta, view = fetch(uri, ("bulk_bcast", bid, i), timeout=timeout)
    return bytes(view)


# ---------------------------------------------------------------------------
# serving side (reached through BucketServer._serve for bulk_* kinds)
# ---------------------------------------------------------------------------

def _blob(data, extra=None):
    meta = {"kind": "blob"}
    if extra:
        meta.update(extra)
    chunks = dcn.chunked(data) if len(data) else []
    return dcn.BulkPayload(meta, chunks, on_sent=_count_sent)


def _cols_payload(meta, cols):
    """Raw column bytes, chunk-framed: the serving side never pickles
    a row — the bridge's dominant per-byte cost is simply gone."""
    import numpy as np
    leaves = []
    chunks = []
    for a in cols:
        a = np.ascontiguousarray(a)
        leaves.append({"dtype": str(a.dtype), "count": int(a.shape[0])})
        chunks.extend(dcn.chunked(a.data))
    out = {"kind": "cols", "leaves": leaves,
           "no_combine": bool(meta.get("no_combine"))}
    return dcn.BulkPayload(out, chunks, on_sent=_count_sent)


def serve(server, req):
    """BucketServer delegate for ``bulk_*`` request kinds; returns a
    dcn.BulkPayload (the handler writes the stream) or raises (the
    handler answers status 1)."""
    kind = req[0]
    if kind == "bulk_bucket":
        _, sid, map_id, reduce_id = req
        return _serve_bucket(server.workdir, sid, map_id, reduce_id)
    if kind == "bulk_shard":
        _, sid, map_id, reduce_id, idx = req
        return _serve_shard(server.workdir, sid, map_id, reduce_id,
                            idx)
    if kind == "bulk_bcast":
        _, bid, i = req
        path = os.path.join(server.workdir, "broadcast",
                            "b%d.%d" % (bid, i))
        with open(path, "rb") as f:
            data = f.read()
        with server._serves_lock:
            server.bcast_serves[(bid, i)] = \
                server.bcast_serves.get((bid, i), 0) + 1
        return _blob(data)
    raise ValueError("unknown request %r" % (kind,))


def _serve_bucket(workdir, sid, map_id, reduce_id):
    from dpark_tpu import shuffle as shuffle_mod
    from dpark_tpu.utils import compress
    path = os.path.join(workdir, "shuffle", str(sid), str(map_id),
                        str(reduce_id))
    if os.path.exists(path):
        with open(path, "rb") as f:
            return _blob(f.read())
    # HBM-resident: raw columns when the store's record shape allows
    # (flat (k, v), unencoded keys) ...
    for exporter in shuffle_mod.HBM_COL_EXPORTERS.values():
        try:
            meta, cols = exporter(sid, map_id, reduce_id)
        except KeyError:
            continue            # this exporter owns no such sid
        except ValueError:
            break               # owned, but not col-exportable
        return _cols_payload(meta, cols)
    # ... else the exporter's pickled payload, still chunk-framed
    for exporter in shuffle_mod.HBM_EXPORTERS.values():
        try:
            items = exporter(sid, map_id, reduce_id)
        except KeyError:
            continue
        return _blob(compress(pickle.dumps(items, -1)))
    raise FileNotFoundError(path)


def _serve_shard(workdir, sid, map_id, reduce_id, idx):
    path = os.path.join(workdir, "shuffle", str(sid), str(map_id),
                        "%d.shards" % reduce_id)
    if os.path.exists(path):
        from dpark_tpu import coding
        with open(path, "rb") as f:
            try:
                return _blob(coding.extract_container_frame(f.read(),
                                                            idx))
            except KeyError:
                return _blob(b"")       # container holds no such shard
    from dpark_tpu import shuffle as shuffle_mod
    for exporter in shuffle_mod.HBM_EXPORTERS.values():
        try:
            return _blob(exporter(sid, map_id, reduce_id, shard=idx))
        except KeyError:
            continue            # this exporter owns no such sid
        except ValueError:
            break               # no code active / bad shard index
    return _blob(b"")           # miss sentinel: fall back to plain
