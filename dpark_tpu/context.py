"""DparkContext — the user entry point.

Reference parity: dpark/context.py — root-RDD constructors
(parallelize/makeRDD/textFile/partialTextFile/csvFile/binaryFile/tableFile/
union/zip), broadcast/accumulator factories, master selection by -m
(local / process / tpu), and the runJob funnel every action goes through
(SURVEY.md sections 2.1 and 3.4).

The reference's masters are local/process/mesos; mesos is replaced by the
TPU master (`-m tpu`), which executes stages as jitted SPMD programs over a
jax device mesh (backend/tpu/).
"""

import argparse
import atexit
import os
import sys
import threading

import importlib
import itertools

_accumulator = importlib.import_module("dpark_tpu.accumulator")
import dpark_tpu.rdd as _rdd
from dpark_tpu.broadcast import Broadcast
from dpark_tpu.env import env
from dpark_tpu.utils.log import get_logger

logger = get_logger("context")

parser = argparse.ArgumentParser(add_help=False)
parser.add_argument("-m", "--master", default=None,
                    help="master: local, process[:N], tpu (default local)")
parser.add_argument("-p", "--parallel", type=int, default=0,
                    help="default parallelism")
parser.add_argument("-c", "--cpus", type=float, default=1.0,
                    help="cpus per task (process master)")
parser.add_argument("-M", "--mem", type=float, default=None,
                    help="MB per task")
parser.add_argument("--profile", action="store_true",
                    help="profile task execution")
parser.add_argument("--conf", default=None, help="path to conf file")
parser.add_argument("--webui", nargs="?", const="127.0.0.1:0",
                    default=None, metavar="HOST:PORT",
                    help="serve a live progress UI")

optParser = parser          # reference-parity alias


def parse_options(args=None):
    options, _ = parser.parse_known_args(args)
    if options.conf:
        from dpark_tpu import conf
        conf.load_conf(options.conf)
    return options


class DparkContext:
    _active = None

    def __init__(self, master=None, **kw):
        options = parse_options([])
        self.master = (master or options.master
                       or os.environ.get("DPARK_MASTER") or "local")
        self.options = options
        self.scheduler = None
        self.started = False
        self.checkpoint_dir = None
        self._parallel = kw.get("parallel", options.parallel)
        DparkContext._active = self

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self.started:
            return
        env.start(is_master=True)
        if self.options.mem:
            env.mem_limit = self.options.mem
        if self.options.profile:
            env.profile = True
        master, _, arg = self.master.partition(":")
        # resident executor service (ISSUE 9): with DPARK_SERVICE set
        # (or master "service[:spec]"), this context attaches to the
        # process-global JobServer instead of owning a scheduler — the
        # mesh, compiled-program cache, and HBM shuffle store amortize
        # across every context/job in the process.  Unset, the seam
        # costs one string check.
        from dpark_tpu import conf as _conf
        svc = _conf.DPARK_SERVICE
        if master == "service" or svc:
            from dpark_tpu import service as service_mod
            spec = arg if master == "service" and arg else (svc or None)
            self.scheduler = service_mod.client_scheduler(spec)
        elif master == "local":
            from dpark_tpu.schedule import LocalScheduler
            self.scheduler = LocalScheduler()
        elif master in ("process", "multiprocess"):
            from dpark_tpu.schedule import MultiProcessScheduler
            self.scheduler = MultiProcessScheduler(
                int(arg) if arg else None)
        elif master == "fleet":
            # N workdir-distinct inline executors on this host with
            # locality-aware placement (chunkserver / cached-partition
            # hints route tasks to the holder)
            from dpark_tpu.schedule import LocalFleetScheduler
            self.scheduler = LocalFleetScheduler(
                int(arg) if arg else 2)
        elif master == "tpu":
            try:
                from dpark_tpu.backend.tpu import TPUScheduler
            except ImportError as e:
                raise NotImplementedError(
                    "the tpu master requires dpark_tpu.backend.tpu "
                    "(import failed: %s)" % e) from e
            self.scheduler = TPUScheduler(int(arg) if arg else None)
        else:
            raise ValueError(
                "unknown master %r (local/process/fleet/tpu)"
                % self.master)
        self.scheduler.start()
        webui = self.options.webui or os.environ.get("DPARK_WEBUI")
        if webui:
            from dpark_tpu.web import start_ui
            host, _, port = str(webui).partition(":")
            self._web, url = start_ui(self.scheduler, host or "127.0.0.1",
                                      int(port or 0))
            print("dpark_tpu web ui: %s" % url, file=sys.stderr)
        self.started = True
        atexit.register(self.stop)

    def stop(self):
        if not self.started:
            return
        self.started = False
        web = getattr(self, "_web", None)
        if web is not None:
            web.shutdown()
            web.server_close()
            self._web = None
        if self.scheduler:
            prof = getattr(self.scheduler, "profile", None)
            if prof is not None:
                import sys
                print(prof.summary(20), file=sys.stderr)
            self.scheduler.stop()
        # a service-attached context shares env (workdir, fetcher,
        # trackers) with every other tenant of the resident server —
        # one tenant leaving must not tear the process down
        if not getattr(self.scheduler, "is_service_client", False):
            env.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- ids / config ----------------------------------------------------
    # process-global, not per-context: the partition cache and HBM stores
    # key by rdd id, and multiple contexts (e.g. streaming recovery)
    # share those singletons in one process
    _rdd_id_counter = [0]
    # concurrent drivers on a resident job server (ISSUE 9) mint rdd
    # ids from their own threads; the read-modify-write must be atomic
    _rdd_id_lock = threading.Lock()

    def new_rdd_id(self):
        with DparkContext._rdd_id_lock:
            DparkContext._rdd_id_counter[0] += 1
            return DparkContext._rdd_id_counter[0]

    @classmethod
    def advance_rdd_ids(cls, minimum):
        """Recovery: never re-mint ids at or below a restored high-water
        mark (checkpoint dirs are keyed rdd-<id> in a persistent dir)."""
        with cls._rdd_id_lock:
            cls._rdd_id_counter[0] = max(cls._rdd_id_counter[0],
                                         int(minimum))

    @property
    def default_parallelism(self):
        if self._parallel:
            return self._parallel
        self.start()
        return self.scheduler.default_parallelism()

    defaultParallelism = default_parallelism

    def setCheckpointDir(self, path):
        os.makedirs(path, exist_ok=True)
        self.checkpoint_dir = path

    # -- root RDD constructors ------------------------------------------
    def parallelize(self, seq, numSlices=None):
        return _rdd.ParallelCollection(self, seq, numSlices)

    def makeRDD(self, seq, numSlices=None):
        return self.parallelize(seq, numSlices)

    def textFile(self, path, ext="", followLink=True, numSplits=None,
                 splitSize=None):
        if path.endswith(".gz"):
            return _rdd.GZipFileRDD(self, path, splitSize, numSplits)
        if path.endswith(".bz2"):
            return _rdd.BZip2FileRDD(self, path, splitSize, numSplits)
        return _rdd.TextFileRDD(self, path, numSplits, splitSize)

    def partialTextFile(self, path, begin, end, splitSize=None):
        return _rdd.PartialTextFileRDD(self, path, begin, end, splitSize)

    def csvFile(self, path, dialect="excel", numSplits=None,
                splitSize=None):
        # record-aware splits: quoted fields may contain newlines
        return _rdd.CSVFileRDD(self, path, dialect, splitSize,
                               numSplits)

    def binaryFile(self, path, fmt="I", length=None, numSplits=None):
        return _rdd.BinaryFileRDD(self, path, fmt, length, numSplits)

    def tableFile(self, path, numSplits=None):
        """Pickle-part-file table reader (pairs with saveAsTableFile)."""
        return _rdd.CheckpointRDD(self, path)

    def table(self, rdd_or_path, fields=None):
        from dpark_tpu.table import TableRDD
        if isinstance(rdd_or_path, str):
            rdd_or_path = self.tableFile(rdd_or_path)
        return TableRDD(rdd_or_path, fields)

    def sql(self, query, /, **tables):
        """Minimal SELECT front over TableRDDs:
        ctx.sql("select region, sum(qty) as q from t group by region",
                t=my_table)."""
        from dpark_tpu.table import execute
        return execute(query, tables)

    def beansdb(self, path, raw=False, check_crc=True):
        from dpark_tpu.beansdb import BeansdbFileRDD
        return BeansdbFileRDD(self, path, raw, check_crc)

    def tabular(self, path, fields=None, wanted=None,
                predicate_ranges=None):
        from dpark_tpu.tabular import TabularRDD
        return TabularRDD(self, path, fields, wanted, predicate_ranges)

    def union(self, rdds):
        return _rdd.UnionRDD(self, list(rdds))

    def zip(self, rdds):
        return _rdd.ZippedRDD(self, list(rdds))

    # -- shared state ----------------------------------------------------
    def accumulator(self, init=0, param=None):
        return _accumulator.Accumulator(
            init, param or _accumulator.numAcc)

    def broadcast(self, value):
        self.start()
        return Broadcast(value)

    # -- execution -------------------------------------------------------
    def runJob(self, rdd, func, partitions=None, allow_local=False):
        self.start()
        # pre-flight gate (dpark_tpu/analysis/): lint the lineage —
        # shuffle anti-patterns and silent-wrong-answer shapes — before
        # the scheduler sees it.  Runs EAGERLY here (run_job returns a
        # lazy generator), so DPARK_LINT=error refuses a bad plan at
        # submit time, not at first iteration.
        from dpark_tpu.analysis import preflight
        preflight(rdd, master=self.master, func=func)
        return self.scheduler.run_job(rdd, func, partitions, allow_local)

    def clear(self):
        pass
