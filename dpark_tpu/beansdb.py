"""Bitcask-style data-file codec (beansdb format).

Reference parity: dpark/utils/beansdb.py (SURVEY.md section 2.4) — record
codec for Douban's beansdb KV store: append-only data files of records
  [crc32c(4) | tstamp(4) | flag(4) | ver(4) | ksz(4) | vsz(4) | key | val]
with optional zlib value compression, backing ctx.beansdb() reads and
rdd.saveAsBeansdb().  Layout here is an original design with the same
capabilities (the reference uses fnv1a + quicklz; we use crc32c from the
native layer + zlib, documented divergence).
"""

import os
import struct
import time
import zlib

from dpark_tpu.native import crc32c
from dpark_tpu.utils import atomic_file

_HEADER = struct.Struct("<IIiIII")      # crc, tstamp, flag, ver, ksz, vsz

FLAG_COMPRESSED = 0x0001
PADDING = 256


class BeansdbWriter:
    def __init__(self, f, compress_threshold=256):
        self.f = f
        self.compress_threshold = compress_threshold

    def write_record(self, key, value, version=1, flag=0, tstamp=None):
        if isinstance(key, str):
            key = key.encode("utf-8")
        if isinstance(value, str):
            value = value.encode("utf-8")
        if len(value) >= self.compress_threshold:
            packed = zlib.compress(value)
            if len(packed) < len(value):
                value = packed
                flag |= FLAG_COMPRESSED
        tstamp = int(tstamp if tstamp is not None else time.time())
        body = key + value
        crc = crc32c(struct.pack("<IiIII", tstamp, flag, version,
                                 len(key), len(value)) + body)
        rec = _HEADER.pack(crc, tstamp, flag, version,
                           len(key), len(value)) + body
        pad = (-len(rec)) % PADDING
        self.f.write(rec + b"\x00" * pad)


def read_records(f, check_crc=True):
    """Yield (key, value, version, flag, tstamp) from a beansdb data file."""
    while True:
        header = f.read(_HEADER.size)
        if len(header) < _HEADER.size:
            return
        crc, tstamp, flag, version, ksz, vsz = _HEADER.unpack(header)
        if ksz == 0 and vsz == 0 and crc == 0:
            return                      # zero padding at EOF
        body = f.read(ksz + vsz)
        if len(body) < ksz + vsz:
            raise IOError("truncated beansdb record")
        if check_crc:
            expect = crc32c(struct.pack(
                "<IiIII", tstamp, flag, version, ksz, vsz) + body)
            if expect != crc:
                raise IOError("beansdb crc mismatch")
        key = body[:ksz]
        value = body[ksz:]
        if flag & FLAG_COMPRESSED:
            value = zlib.decompress(value)
        # skip padding
        consumed = _HEADER.size + ksz + vsz
        pad = (-consumed) % PADDING
        if pad:
            f.read(pad)
        yield key.decode("utf-8", "replace"), value, version, flag, tstamp


# --------------------------------------------------------------------------
# RDD integration
# --------------------------------------------------------------------------

from dpark_tpu.rdd import RDD, Split, OutputRDDBase       # noqa: E402


class BeansdbSplit(Split):
    def __init__(self, index, path):
        super().__init__(index)
        self.path = path


class BeansdbFileRDD(RDD):
    """ctx.beansdb(path): each .data file is one split; yields
    (key, value_bytes) or (key, (value, version, tstamp)) with raw=True."""

    def __init__(self, ctx, path, raw=False, check_crc=True):
        super().__init__(ctx)
        self.path = path
        self.raw = raw
        self.check_crc = check_crc
        if os.path.isdir(path):
            self.files = sorted(
                os.path.join(path, n) for n in os.listdir(path)
                if n.endswith(".data"))
        else:
            self.files = [path]

    def _make_splits(self):
        return [BeansdbSplit(i, p) for i, p in enumerate(self.files)]

    def compute(self, split):
        with open(split.path, "rb") as f:
            for key, value, version, flag, tstamp in read_records(
                    f, self.check_crc):
                if self.raw:
                    yield (key, (value, version, tstamp))
                else:
                    yield (key, value)


class OutputBeansdbRDD(OutputRDDBase):
    def __init__(self, prev, path, overwrite=True):
        super().__init__(prev, path, overwrite, ".data")
        self.compress_threshold = 256

    def _target(self, split):
        return os.path.join(self.path, "%03d.data" % split.index)

    def _write(self, f, it):
        w = BeansdbWriter(f, self.compress_threshold)
        have = False
        for k, v in it:
            w.write_record(k, v)
            have = True
        return have
