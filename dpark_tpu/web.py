"""Minimal web UI: live job/stage progress over stdlib http.server.

Reference parity: dpark/web/ (optional flask app showing stages and
progress, SURVEY.md section 2.5).  flask is not in this image, so the
same capability ships on http.server: an HTML overview at / and JSON at
/api/jobs, fed by the scheduler's event history.
"""

import http.server
import json
import threading

from dpark_tpu.utils.log import get_logger

logger = get_logger("web")

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>dpark_tpu</title>
<style>
 body { font-family: monospace; margin: 2em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #999; padding: 4px 10px; text-align: left; }
 .done { color: #2a2; } .run { color: #d80; }
</style></head>
<body>
<h2>dpark_tpu jobs</h2>
<table id="t"><tr><th>job</th><th>scope</th><th>parts</th>
<th>finished</th><th>stages</th><th>seconds</th><th>state</th></tr></table>
<h2>stages</h2>
<table id="s"><tr><th>job</th><th>stage</th><th>dag</th><th>rdd</th>
<th>parts</th><th>kind</th><th>seconds</th><th>device run s</th>
<th>HBM bytes</th><th>wire bytes</th><th>pad eff</th></tr></table>
<script>
async function tick() {
  const r = await fetch('/api/jobs'); const jobs = await r.json();
  const t = document.getElementById('t');
  while (t.rows.length > 1) t.deleteRow(1);
  const s = document.getElementById('s');
  while (s.rows.length > 1) s.deleteRow(1);
  for (const j of jobs) {
    const row = t.insertRow();
    for (const v of [j.id, j.scope, j.parts, j.finished, j.stages,
                     j.seconds, j.state])
      row.insertCell().textContent = v;
    row.className = j.state === 'done' ? 'done' : 'run';
    for (const st of (j.stage_info || [])) {
      const sr = s.insertRow();
      const dag = (st.parents && st.parents.length)
        ? st.parents.join(',') + ' → ' + st.id : String(st.id);
      for (const v of [j.id, st.id, dag, st.rdd, st.parts, st.kind,
                       st.seconds, st.run_seconds, st.hbm_bytes,
                       st.wire_bytes, st.pad_efficiency])
        sr.insertCell().textContent = v === undefined ? '' : v;
      sr.className = st.seconds === null ? 'run' : 'done';
    }
  }
}
setInterval(tick, 1000); tick();
</script></body></html>"""


def start_ui(scheduler, host="127.0.0.1", port=0):
    """Serve the scheduler's job history; returns (server, url)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.startswith("/api/jobs"):
                body = json.dumps(
                    list(getattr(scheduler, "history", []))).encode()
                ctype = "application/json"
            else:
                body = _PAGE.encode()
                ctype = "text/html; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = "http://%s:%d/" % server.server_address
    logger.info("web ui at %s", url)
    return server, url
