"""Web UI: live job/stage progress over stdlib http.server.

Reference parity: dpark/web/ (optional flask app showing stages and
progress, SURVEY.md section 2.5).  flask is not in this image, so the
same capability ships on http.server: an HTML overview at /, JSON at
/api/jobs, the merged task profile (when --profile ran) at
/api/profile, fed by the scheduler's event history.  r5 (VERDICT r4
weak #5): per-job stage DAG view, per-task drill-down (click a stage
row), profile panel.  ISSUE 8: /metrics (Prometheus text format,
job/stage/task + fault/decode/degrade/adapt counters and
phase-seconds histograms) and /api/trace?job=N (the trace plane's
span timeline; stage rows link to it).
"""

import http.server
import json
import threading
import urllib.parse

from dpark_tpu.utils.log import get_logger

logger = get_logger("web")

def _esc(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", " ")


def render_metrics(scheduler):
    """The /metrics payload (Prometheus text exposition format 0.0.4):
    job/stage/task counters, fault/decode/degrade/adapt counters, and
    phase-seconds histograms.  Built from a defensive snapshot — a
    scrape racing a mutating job record returns valid text, never an
    error (ISSUE 8 satellite)."""
    lines = []

    def metric(name, mtype, help_text, samples):
        lines.append("# HELP %s %s" % (name, help_text))
        lines.append("# TYPE %s %s" % (name, mtype))
        for labels, value in samples:
            if labels:
                lab = ",".join('%s="%s"' % (k, _esc(v))
                               for k, v in sorted(labels.items()))
                lines.append("%s{%s} %s" % (name, lab, value))
            else:
                lines.append("%s %s" % (name, value))

    try:
        snap = scheduler.metrics_snapshot()
    except Exception:
        snap = {"jobs": {}, "stages": {}, "tasks": {},
                "counters": {}, "adapt_decisions": {},
                "phases": {}, "export_seconds": 0.0,
                "jobs_running": 0}
    metric("dpark_jobs_total", "counter", "jobs by final state",
           [({"state": s}, n) for s, n in sorted(snap["jobs"].items())]
           or [({"state": "none"}, 0)])
    metric("dpark_jobs_running", "gauge", "jobs currently in flight",
           [({}, snap.get("jobs_running", 0))])
    # resident service (ISSUE 9): jobs waiting on admission, and the
    # bounded compiled-program cache's counters — the warm-submit
    # acceptance ("0 re-compiles") is asserted from these
    svc = snap.get("service") or {}
    metric("dpark_jobs_queued", "gauge",
           "jobs waiting for service admission",
           [({}, svc.get("jobs_queued", 0))])
    pc = snap.get("program_cache") or {}
    for key, help_text in (
            ("hits", "compiled-program cache hits"),
            ("misses", "compiled-program cache misses (compiles)"),
            ("evictions", "compiled-program cache LRU evictions")):
        metric("dpark_program_cache_%s_total" % key, "counter",
               help_text, [({}, pc.get(key, 0))])
    # persistent AOT executable cache (ISSUE 17): the disk tier's
    # load/store/warm/evict counters — the restart acceptance ("0
    # backend compiles on a warm process") is asserted from these
    aot = pc.get("aot") or {}
    for key, help_text in (
            ("loads", "aot executables loaded from the disk cache"),
            ("load_misses", "aot disk-cache misses (fell back to "
                            "compile)"),
            ("stores", "aot executables serialized to the disk cache"),
            ("warmed", "aot executables preloaded by boot warming"),
            ("warm_hits", "boot-warm preloads consumed by programs"),
            ("evict_writebacks", "aot write-backs at LRU eviction"),
            ("fallbacks", "aot executables dropped back to the jit "
                          "path")):
        metric("dpark_aot_%s_total" % key, "counter", help_text,
               [({}, aot.get(key, 0))])
    # shared-computation result cache (ISSUE 18): the planner-probe
    # counters — the two-tenant reuse acceptance ("zero scan chunks
    # on the repeated query") is asserted from these
    try:
        from dpark_tpu import resultcache
        rc = resultcache.stats() or {}
    except Exception:
        rc = {}
    for key, help_text in (
            ("hits", "sub-plan results served whole from the shared "
                     "result cache"),
            ("partial_hits", "partial-aggregate merges served from "
                             "cached partials + a residual scan"),
            ("misses", "result-cache probes that fell through to "
                       "execution"),
            ("stores", "query results stored into the result cache"),
            ("evictions", "result-cache LRU evictions past the byte "
                          "budget"),
            ("disk_loads", "result entries loaded from the disk "
                           "tier"),
            ("disk_stores", "result entries written through to the "
                            "disk tier")):
        metric("dpark_resultcache_%s_total" % key, "counter",
               help_text, [({}, rc.get(key, 0))])
    metric("dpark_resultcache_bytes", "gauge",
           "resident result-cache memory-tier bytes",
           [({}, rc.get("bytes", 0))])
    # per-tenant SLO accounting (ISSUE 14): attainment + multi-window
    # burn gauges and the monotonic violation counter, one series per
    # tenant that declared a target
    tenants = svc.get("tenants") or {}
    rows = sorted(tenants.items())
    metric("dpark_tenant_slo_attainment", "gauge",
           "fraction of a tenant's jobs inside its declared SLO",
           [({"tenant": c}, t.get("attainment", 1.0))
            for c, t in rows] or [({"tenant": "none"}, 1.0)])
    metric("dpark_tenant_slo_burn", "gauge",
           "SLO error-budget burn rate per window (1.0 = budget "
           "consumed exactly as fast as allowed)",
           [({"tenant": c, "window": w}, b)
            for c, t in rows
            for w, b in sorted((t.get("burn") or {}).items())]
           or [({"tenant": "none", "window": "none"}, 0.0)])
    metric("dpark_tenant_slo_violations_total", "counter",
           "jobs that finished outside their tenant's SLO",
           [({"tenant": c}, t.get("violations_total", 0))
            for c, t in rows] or [({"tenant": "none"}, 0)])
    # resource attribution plane (ISSUE 15): per-tenant mesh
    # consumption counters.  Monotonic by construction — accounts only
    # ever grow and HBM byte-seconds accrue at release.
    try:
        from dpark_tpu import ledger
        ltenants = ledger.tenant_totals()
    except Exception:
        ltenants = {}
    lrows = sorted(ltenants.items())
    for key, help_text in (
            ("device_seconds", "attributed device wall seconds "
                               "(mesh-lock-held stage execution)"),
            ("lock_wait_seconds", "seconds spent waiting for the "
                                  "mesh lock (contention)"),
            ("hbm_byte_seconds", "HBM shuffle-store bytes x resident "
                                 "seconds, accrued at release"),
            ("bulk_bytes", "bulk data-plane payload bytes attributed "
                           "to the tenant's jobs")):
        metric("dpark_tenant_%s_total" % key, "counter", help_text,
               [({"tenant": c}, t.get(key, 0)) for c, t in lrows]
               or [({"tenant": "none"}, 0)])
    metric("dpark_stages_total", "counter", "stages by execution kind",
           [({"kind": k}, n) for k, n in sorted(snap["stages"].items())]
           or [({"kind": "none"}, 0)])
    metric("dpark_tasks_total", "counter", "recorded task completions",
           [({"ok": str(bool(k == "ok")).lower()}, n)
            for k, n in sorted(snap["tasks"].items())])
    for key, help_text in (
            ("retries", "task retries"),
            ("resubmits", "parent-stage lineage resubmissions"),
            ("recomputes", "intact-parent recomputes"),
            ("fetch_failed", "reduce-side fetch failures"),
            ("speculated", "speculative task duplicates"),
            ("replans", "mid-job reduce-side re-plans"),
            ("resumed_stages", "stages resumed from the crash journal "
                               "instead of re-executed")):
        metric("dpark_%s_total" % key, "counter", help_text,
               [({}, snap["counters"].get(key, 0))])
    # crash-consistent control plane (ISSUE 20): journal replay and
    # peer-lease counters — the kill -9 certification asserts
    # journal_replays/recovered_stages from these, and lease_expiries
    # is the liveness layer's detection count
    try:
        from dpark_tpu import journal
        jstats = journal.stats() or {}
    except Exception:
        jstats = {}
    jcounters = jstats.get("counters") or {}
    for key, help_text in (
            ("journal_replays", "journal replay passes that seeded at "
                                "least one completed stage"),
            ("recovered_stages", "completed stages recovered from the "
                                 "journal after a restart"),
            ("seeded_partitions", "map outputs re-registered from "
                                  "journaled locations"),
            ("skipped_frames", "corrupt/truncated journal frames "
                               "skipped during replay"),
            ("refused_files", "journal files refused (newer schema "
                              "than this process understands)")):
        metric("dpark_%s_total" % key, "counter", help_text,
               [({}, jcounters.get(key, 0))])
    try:
        from dpark_tpu import dcn
        lv = dcn.liveness_stats() or {}
    except Exception:
        lv = {}
    lcounters = lv.get("counters") or {}
    for key, help_text in (
            ("lease_expiries", "peer leases that lapsed into "
                               "suspicion (liveness detections)"),
            ("fast_fails", "fetch attempts failed fast on a "
                           "suspect peer's lease")):
        metric("dpark_%s_total" % key, "counter", help_text,
               [({}, lcounters.get(key, 0))])
    metric("dpark_peers_suspect", "gauge",
           "peers currently in the lease-expired suspect window",
           [({}, len(lv.get("suspect") or ()))])
    try:
        from dpark_tpu import faults
        fstats = scheduler.recovery_summary().get("faults", {}) \
            if hasattr(scheduler, "recovery_summary") \
            else faults.stats()
    except Exception:
        fstats = {}
    metric("dpark_faults_injected_total", "counter",
           "chaos-plane firings by site",
           [({"site": s}, st.get("fired", 0))
            for s, st in sorted(fstats.items())]
           or [({"site": "none"}, 0)])
    try:
        from dpark_tpu import coding
        dstats = coding.stats()
    except Exception:
        dstats = {}
    metric("dpark_decodes_total", "counter",
           "erasure-decode outcomes",
           [({"kind": k}, v) for k, v in sorted(dstats.items())
            if isinstance(v, int)
            and k not in ("parity_bytes",)]
           or [({"kind": "none"}, 0)])
    # per-peer decode attribution (ISSUE 19): which serving peer's
    # shards the policy repaired / raced / failed on — the evidence
    # behind a per-exchange escalation
    metric("dpark_decodes_by_peer_total", "counter",
           "erasure-decode outcomes by serving peer",
           [({"kind": k, "peer": p}, v)
            for p, counts in sorted((dstats.get("per_peer")
                                     or {}).items())
            for k, v in sorted(counts.items())]
           or [({"kind": "none", "peer": "none"}, 0)])
    metric("dpark_parity_bytes_total", "counter",
           "erasure-parity bytes written to shuffle buckets",
           [({}, int(dstats.get("parity_bytes", 0) or 0))])
    metric("dpark_adapt_decisions_total", "counter",
           "cost-model decisions (applied=steered)",
           [({"applied": "true"},
             snap["adapt_decisions"].get("applied", 0)),
            ({"applied": "false"},
             snap["adapt_decisions"].get("logged", 0)
             - snap["adapt_decisions"].get("applied", 0))])
    try:
        from dpark_tpu import trace as trace_mod
        emitted, dropped = trace_mod.counts()
        tmode = trace_mod.mode()
    except Exception:
        emitted = dropped = 0
        tmode = "off"
    metric("dpark_trace_spans_total", "counter",
           "trace spans emitted (mode label = DPARK_TRACE)",
           [({"mode": tmode}, emitted)])
    metric("dpark_trace_spans_dropped_total", "counter",
           "trace spans dropped (spool cap)", [({}, dropped)])
    # the host-bridge export total is cumulative wall time, not a
    # per-stage observation — a counter, so rate() works on it
    metric("dpark_export_seconds_total", "counter",
           "cumulative host-bridge export wall seconds",
           [({}, round(float(snap.get("export_seconds", 0.0)), 6))])
    # multi-controller bulk data plane (ISSUE 12): per-peer byte
    # counters both directions, stream totals, and the live
    # active-stream gauge
    try:
        from dpark_tpu import bulkplane
        bstats = bulkplane.stats()
    except Exception:
        bstats = {"sent": {}, "received": {}, "streams": 0,
                  "active": 0, "retries": 0, "corrupt_frames": 0,
                  "torn_streams": 0}
    metric("dpark_bulk_bytes_total", "counter",
           "bulk data-plane payload bytes by peer and direction",
           [({"peer": p, "direction": "received"}, v)
            for p, v in sorted(bstats["received"].items())]
           + [({"peer": p, "direction": "sent"}, v)
              for p, v in sorted(bstats["sent"].items())]
           or [({"peer": "none", "direction": "none"}, 0)])
    metric("dpark_bulk_streams_total", "counter",
           "completed bulk fetch streams", [({}, bstats["streams"])])
    metric("dpark_bulk_streams_active", "gauge",
           "bulk fetch streams currently in flight",
           [({}, bstats["active"])])
    for key, help_text in (
            ("retries", "bulk reads retried after a torn stream or "
                        "rejected frame"),
            ("corrupt_frames", "bulk frames rejected by crc"),
            ("torn_streams", "bulk streams cut mid-transfer")):
        metric("dpark_bulk_%s_total" % key, "counter", help_text,
               [({}, bstats[key])])
    # pane-plane stream gauges (ISSUE 10): live per-windowed-stream
    # state from the panes registry — resident pane partials, merge
    # activity, watermark lag, and late-record accounting
    try:
        from dpark_tpu import panes as panes_mod
        sstats = panes_mod.stream_stats()
    except Exception:
        sstats = {}
    rows = sorted(sstats.items())
    metric("dpark_stream_panes", "gauge",
           "resident pane partial aggregates per windowed stream",
           [({"stream": s}, st.get("panes", 0)) for s, st in rows]
           or [({"stream": "none"}, 0)])
    metric("dpark_stream_pane_merges_total", "counter",
           "pane merge-tree nodes built per windowed stream",
           [({"stream": s}, st.get("node_builds", 0))
            for s, st in rows] or [({"stream": "none"}, 0)])
    metric("dpark_stream_watermark_lag_seconds", "gauge",
           "processing-time distance back to the event-time watermark",
           [({"stream": s}, round(st["watermark_lag_s"], 6))
            for s, st in rows
            if st.get("watermark_lag_s") is not None]
           or [({"stream": "none"}, 0)])
    metric("dpark_stream_late_dropped_total", "counter",
           "late records dropped below the watermark / buffer bound",
           [({"stream": s}, st.get("late_dropped", 0))
            for s, st in rows] or [({"stream": "none"}, 0)])
    metric("dpark_stream_late_patched_rows_total", "counter",
           "admitted late records folded into pane patches",
           [({"stream": s}, st.get("late_patched_rows", 0))
            for s, st in rows] or [({"stream": "none"}, 0)])
    # phase-seconds histograms: one observation per streamed stage per
    # phase, pre-folded (with the trimmed-history archive) by
    # metrics_snapshot so the series stay monotonic
    from dpark_tpu.schedule import PHASE_BUCKETS
    lines.append("# HELP dpark_phase_seconds per-stage phase wall "
                 "seconds")
    lines.append("# TYPE dpark_phase_seconds histogram")
    phases = snap.get("phases", {})
    for phase in sorted(phases):
        h = phases[phase]
        acc = 0
        for i, le in enumerate(PHASE_BUCKETS):
            acc += h["buckets"][i]
            lines.append(
                'dpark_phase_seconds_bucket{phase="%s",le="%s"} %d'
                % (phase, le, acc))
        lines.append(
            'dpark_phase_seconds_bucket{phase="%s",le="+Inf"} %d'
            % (phase, h["count"]))
        lines.append('dpark_phase_seconds_sum{phase="%s"} %s'
                     % (phase, round(h["sum"], 6)))
        lines.append('dpark_phase_seconds_count{phase="%s"} %d'
                     % (phase, h["count"]))
    return "\n".join(lines) + "\n"

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>dpark_tpu</title>
<style>
 body { font-family: monospace; margin: 2em; }
 table { border-collapse: collapse; margin-bottom: 1em; }
 td, th { border: 1px solid #999; padding: 4px 10px; text-align: left; }
 .done { color: #2a2; } .run { color: #d80; } .fail { color: #c22; }
 .dag { white-space: pre; background: #f6f6f6; padding: 8px;
        display: inline-block; margin: 2px 0 10px; }
 .tasks { margin-left: 2em; }
 .stage { cursor: pointer; }
 pre { background: #f6f6f6; padding: 8px; }
</style></head>
<body>
<h2>dpark_tpu jobs</h2>
<table id="t"><tr><th>job</th><th>scope</th><th>parts</th>
<th>finished</th><th>stages</th><th>seconds</th><th>state</th>
<th>client</th><th>queue ms</th><th>SLO (attain %)</th>
<th>cache (hit/miss)</th>
<th>recovery (resubmit/recompute/retry)</th>
<th>decodes (repair/straggler/fail)</th>
<th>adapt (steered/logged)</th></tr></table>
<h2>stages <small>(click a row for its tasks; DAG per job below)</small></h2>
<table id="s"><tr><th>job</th><th>stage</th><th>rdd</th>
<th>parts</th><th>kind</th><th>seconds</th><th>device run s</th>
<th>HBM bytes</th><th>wire bytes</th><th>remote fetch B</th>
<th>pad eff</th>
<th>waves</th><th>idle %</th><th>pipeline ms (in/cmp/xchg/spill)</th>
<th>decodes</th>
<th>fetch p99 ms</th>
<th>stream</th>
<th>fallback / degrade</th>
</tr></table>
<h2>resource ledger <small>(per-tenant mesh attribution)</small></h2>
<div id="util" style="width:480px;height:18px;display:flex;
 border:1px solid #999;margin-bottom:6px"></div>
<div id="utiltxt" style="margin-bottom:8px"></div>
<div id="aotline" style="margin-bottom:8px"></div>
<table id="l"><tr><th>tenant</th><th>device s</th>
<th>lock wait s</th><th>HBM byte-s</th><th>bulk bytes</th>
<th>spill bytes</th><th>fetches</th><th>compiles (ms)</th>
<th>waves</th></tr></table>
<h2>streams <small>(pane plane: windowed DStreams)</small></h2>
<table id="w"><tr><th>stream</th><th>type</th><th>mode</th>
<th>window/slide</th><th>panes</th><th>nodes (built)</th>
<th>watermark lag s</th><th>late rows (patched/dropped)</th>
<th>patches</th><th>ticks</th></tr></table>
<div id="dags"></div>
<h2>profile</h2>
<pre id="prof">(run with --profile)</pre>
<script>
const open = new Set();
function dagText(j) {
  // topological-ish text DAG: each stage with its parents as edges
  const lines = ['job ' + j.id + '  (' + (j.scope || '') + ')'];
  for (const st of (j.stage_info || [])) {
    const par = (st.parents && st.parents.length)
      ? st.parents.map(p => 'stage ' + p).join(', ') : 'source';
    lines.push('  ' + par + '  ->  stage ' + st.id +
               '  [' + (st.rdd || '?') + ', ' + (st.kind || '?') + ']');
  }
  return lines.join('\\n');
}
function taskRows(st) {
  const ts = st.tasks || [];
  if (!ts.length) return '(no per-task records)';
  let h = '<table><tr><th>part</th><th>seconds</th><th>host</th>' +
          '<th>ok</th></tr>';
  for (const t of ts)
    h += '<tr class="' + (t.ok ? 'done' : 'fail') + '"><td>' + t.p +
         '</td><td>' + t.s + '</td><td>' + (t.host || '') +
         '</td><td>' + t.ok + '</td></tr>';
  return h + '</table>';
}
async function tick() {
  // health registry feed (ISSUE 14): per-stage fetch p99s, tenant SLO
  // attainment/burn — one defensive snapshot per tick
  let hd = {};
  try { hd = await (await fetch('/api/health')).json(); }
  catch (e) { hd = {}; }
  // persistent AOT executable cache (ISSUE 17): disk-tier counters —
  // a warm restart shows loads/warm hits with zero backend compiles
  const ao = hd.aot || null;
  document.getElementById('aotline').textContent = ao
    ? 'aot cache [' + ao.mode + ']: ' + (ao.loads || 0) + ' loaded / '
      + (ao.load_misses || 0) + ' missed / ' + (ao.stores || 0)
      + ' stored / ' + (ao.warmed || 0) + ' warmed ('
      + (ao.warm_hits || 0) + ' consumed) / '
      + (ao.evict_writebacks || 0) + ' evict write-backs / '
      + (ao.fallbacks || 0) + ' fallbacks'
    : '';
  const r = await fetch('/api/jobs'); const jobs = await r.json();
  const t = document.getElementById('t');
  while (t.rows.length > 1) t.deleteRow(1);
  const s = document.getElementById('s');
  while (s.rows.length > 1) s.deleteRow(1);
  const dags = document.getElementById('dags');
  dags.innerHTML = '';
  for (const j of jobs) {
    const row = t.insertRow();
    // lineage-recovery accounting (ISSUE 5): FetchFailed parent
    // resubmits / intact-parent recomputes / task retries per job
    const rec = (j.resubmits || 0) + '/' + (j.recomputes || 0) + '/' +
                (j.retries || 0);
    // coded-shuffle decode accounting (ISSUE 6): parity repairs /
    // straggler wins / failed decodes attributed to this job, with
    // the active code mode when one is configured
    const dj = j.decodes || {};
    const dec = dj.mode
      ? (dj.repair || 0) + '/' + (dj.straggler_win || 0) + '/' +
        (dj.decode_failures || 0) + ' [' + dj.mode + ']' : '';
    // adaptive-execution decisions (ISSUE 7): cost-model choices taken
    // during this job — applied steers vs observe-mode would-bes, with
    // the mode; hover a stage's why column for the per-stage reason
    const aj = j.adapt || {};
    const ads = aj.decisions || [];
    const adp = aj.mode
      ? ads.filter(d => d.applied).length + '/' + ads.length +
        ' [' + aj.mode + ']' : '';
    // resident service (ISSUE 9): submitting tenant, admission/queue
    // wait, and the job's compiled-program cache delta (a warm
    // re-submission shows hits/0 — zero compiles)
    const pc = j.program_cache || {};
    const cache = pc.hits !== undefined
      ? pc.hits + '/' + pc.misses : '';
    const qw = j.queue_wait_ms !== undefined ? j.queue_wait_ms : '';
    // per-tenant SLO column (ISSUE 14): this job's latency vs its
    // tenant's target, plus the tenant's lifetime attainment from
    // the health registry — red when the tenant is burning budget
    const ten = ((hd.tenants || {})[j.client]) || null;
    const burning = ten &&
      Math.max(...Object.values(ten.burn || {0: 0})) >= 1.0;
    let slo = '';
    if (j.slo)
      slo = j.slo.latency_ms + '/' + j.slo.slo_ms + 'ms' +
            (j.slo.ok ? '' : ' VIOLATED');
    if (ten)
      slo += (slo ? ' ' : '') +
             '(' + (100 * ten.attainment).toFixed(1) + '%)';
    for (const v of [j.id, j.scope, j.parts, j.finished, j.stages,
                     j.seconds, j.state, j.client || '', qw, slo,
                     cache, rec, dec, adp])
      row.insertCell().textContent = v;
    if (slo)
      row.cells[9].className =
        burning || (j.slo && !j.slo.ok) ? 'fail' : 'done';
    row.className = j.state === 'done' ? 'done' : 'run';
    const d = document.createElement('div');
    d.className = 'dag'; d.textContent = dagText(j);
    dags.appendChild(d); dags.appendChild(document.createElement('br'));
    for (const st of (j.stage_info || [])) {
      const sr = s.insertRow();
      // overlapped wave pipeline (streamed stages): waves, device-idle
      // fraction, and the per-stage ingest/compute/exchange/spill ms —
      // live while the stream runs; the idle-percent drop IS the overlap
      const p = st.pipeline || {};
      const pms = p.waves ? (p.ingest_ms + '/' + p.compute_ms + '/' +
                             p.exchange_ms + '/' + p.spill_ms) : '';
      const idle = p.waves ? (100 * p.device_idle_frac).toFixed(1) : '';
      // why the stage left (or nearly left) the array path: the
      // analyze-time fallback_reason, the runtime degrade_reason, or
      // the cost model's adapt_reason (ISSUE 7: predicted, not
      // assumed, admission)
      const why = st.fallback_reason || st.degrade_reason ||
        st.adapt_reason || '';
      // per-stage decode deltas: activity against THIS stage's map
      // outputs (the parent whose buckets were decoded from parity)
      const ds = st.decodes || {};
      const sdec = Object.keys(ds).length
        ? (ds.repair || 0) + '/' + (ds.straggler_win || 0) + '/' +
          (ds.decode_failures || 0) : '';
      // pane-plane attribution (ISSUE 10): which stream + role
      // (pane-build / tree-merge / late-patch / window-emit) this
      // stage served, with the pane index when one applies
      const sw = st.stream || {};
      const srole = sw.stream
        ? sw.stream + ' ' + (sw.role || '') +
          (sw.pane !== undefined ? ' #' + sw.pane : '') : '';
      // cross-controller bytes this stage fetched over the bulk data
      // plane (ISSUE 12) — nonzero only when a reduce read a remote
      // peer's map outputs
      // per-stage fetch p99 from the health registry's streaming
      // sketches (ISSUE 14) — live while the stage fetches
      const sf = ((hd.stage_fetch || {})[j.id + ':' + st.id]) || {};
      const fp99 = sf.p99_ms !== undefined ? sf.p99_ms : '';
      for (const v of [j.id, st.id, st.rdd, st.parts, st.kind,
                       st.seconds, st.run_seconds, st.hbm_bytes,
                       st.wire_bytes, st.remote_fetch_bytes,
                       st.pad_efficiency,
                       p.waves, idle, pms, sdec, fp99, srole, why])
        sr.insertCell().textContent = v === undefined ? '' : v;
      // span timeline link (ISSUE 8): the stage's job timeline from
      // the trace plane ring/spool via /api/trace
      sr.cells[1].innerHTML = '<a href="/api/trace?job=' + j.id +
        '" target="_blank">' + st.id + '</a>';
      sr.className = 'stage ' + (st.seconds === null ? 'run' : 'done');
      const key = j.id + ':' + st.id;
      sr.onclick = () => {
        if (open.has(key)) open.delete(key); else open.add(key);
        tick();
      };
      if (open.has(key)) {
        const dr = s.insertRow();
        const c = dr.insertCell(); c.colSpan = 18;
        c.className = 'tasks'; c.innerHTML = taskRows(st);
      }
    }
  }
  // resource ledger (ISSUE 15): per-tenant attribution table + the
  // mesh busy/idle/contended utilization bar
  try {
    const lr = await fetch('/api/ledger'); const led = await lr.json();
    const lt = document.getElementById('l');
    while (lt.rows.length > 1) lt.deleteRow(1);
    const tenants = led.tenants || {};
    for (const name of Object.keys(tenants).sort()) {
      const a = tenants[name];
      const row = lt.insertRow();
      for (const v of [name, a.device_seconds, a.lock_wait_seconds,
                       a.hbm_byte_seconds, a.bulk_bytes,
                       a.spill_bytes, a.fetches,
                       a.compiles + ' (' + a.compile_ms + ')',
                       a.waves])
        row.insertCell().textContent = v === undefined ? '' : v;
    }
    const u = led.utilization || {};
    const bar = document.getElementById('util');
    bar.innerHTML = '';
    for (const [frac, color, label] of
         [[u.busy_frac, '#2a2', 'busy'],
          [u.contended_frac, '#c22', 'contended'],
          [u.idle_frac, '#ddd', 'idle']]) {
      const seg = document.createElement('div');
      seg.style.width = (100 * (frac || 0)) + '%';
      seg.style.background = color;
      seg.title = label + ' ' + (100 * (frac || 0)).toFixed(1) + '%';
      bar.appendChild(seg);
    }
    const cons = led.conservation || {};
    document.getElementById('utiltxt').textContent =
      'mesh busy ' + (100 * (u.busy_frac || 0)).toFixed(1) +
      '% / contended ' + (100 * (u.contended_frac || 0)).toFixed(1) +
      '% / idle ' + (100 * (u.idle_frac || 0)).toFixed(1) +
      '%  |  conservation: ' +
      (cons.ratio === null || cons.ratio === undefined
        ? 'n/a' : (100 * cons.ratio).toFixed(1) +
          '% of busy time attributed');
  } catch (e) {}
  // pane-plane streams (ISSUE 10): live pane counts, watermark lag,
  // late-record accounting per windowed stream
  const wr = await fetch('/api/streams'); const streams = await wr.json();
  const w = document.getElementById('w');
  while (w.rows.length > 1) w.deleteRow(1);
  for (const sid of Object.keys(streams).sort()) {
    const st = streams[sid];
    const row = w.insertRow();
    const lag = st.watermark_lag_s === null ||
                st.watermark_lag_s === undefined
      ? '' : st.watermark_lag_s.toFixed(3);
    for (const v of [sid, st.type, st.mode,
                     st.window + '/' + st.slide, st.panes,
                     st.nodes + ' (' + st.node_builds + ')', lag,
                     st.late_patched_rows + '/' + st.late_dropped,
                     st.late_patches, st.ticks])
      row.insertCell().textContent = v === undefined ? '' : v;
  }
  const pr = await fetch('/api/profile');
  document.getElementById('prof').textContent = await pr.text();
}
setInterval(tick, 1000); tick();
</script></body></html>"""


def start_ui(scheduler, host="127.0.0.1", port=0):
    """Serve the scheduler's job history; returns (server, url)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.startswith("/api/jobs"):
                body = json.dumps(
                    list(getattr(scheduler, "history", []))).encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics"):
                # Prometheus text exposition; never throws on a job
                # mid-mutation (defensive snapshot under the
                # scheduler lock)
                try:
                    body = render_metrics(scheduler).encode()
                except Exception as e:
                    body = ("# metrics unavailable: %s\n"
                            % e).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.startswith("/api/trace"):
                # span timeline (trace plane, ISSUE 8): ?job=N filters
                # to one job; spool mode merges worker-process spans
                from dpark_tpu import trace as trace_mod
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query)
                job = None
                try:
                    job = int(q["job"][0])
                except (KeyError, ValueError, IndexError):
                    pass
                try:
                    recs = trace_mod.collected(job=job)
                except Exception:
                    recs = []
                body = json.dumps(
                    {"mode": trace_mod.mode(), "job": job,
                     "spans": recs}).encode()
                ctype = "application/json"
            elif self.path.startswith("/api/health"):
                # online health plane (ISSUE 14): graded subsystems
                # with evidence, per-site tail summaries, per-tenant
                # SLO stats, per-stage fetch p99s — built from
                # defensive snapshots under the registry locks (same
                # discipline as /metrics: a scrape racing a running
                # job returns valid JSON, never an error)
                try:
                    from dpark_tpu import health as health_mod
                    body = json.dumps(
                        health_mod.api_health(scheduler)).encode()
                except Exception as e:
                    body = json.dumps(
                        {"mode": "error", "error": str(e)}).encode()
                ctype = "application/json"
            elif self.path.startswith("/api/ledger"):
                # resource attribution plane (ISSUE 15): per-tenant
                # accounts, the mesh utilization split, and the
                # conservation check — defensive snapshots, never an
                # error
                try:
                    from dpark_tpu import ledger as ledger_mod
                    body = json.dumps(
                        ledger_mod.api_ledger(scheduler)).encode()
                except Exception as e:
                    body = json.dumps(
                        {"mode": "error", "error": str(e)}).encode()
                ctype = "application/json"
            elif self.path.startswith("/api/streams"):
                # pane-plane live stats (ISSUE 10): one row per
                # windowed stream from the panes registry
                try:
                    from dpark_tpu import panes as panes_mod
                    body = json.dumps(panes_mod.stream_stats()).encode()
                except Exception:
                    body = b"{}"
                ctype = "application/json"
            elif self.path.startswith("/api/profile"):
                prof = getattr(scheduler, "profile", None)
                body = (prof.summary() if prof is not None
                        else "(run with --profile)").encode()
                ctype = "text/plain; charset=utf-8"
            else:
                body = _PAGE.encode()
                ctype = "text/html; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = "http://%s:%d/" % server.server_address
    logger.info("web ui at %s", url)
    return server, url
