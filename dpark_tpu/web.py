"""Web UI: live job/stage progress over stdlib http.server.

Reference parity: dpark/web/ (optional flask app showing stages and
progress, SURVEY.md section 2.5).  flask is not in this image, so the
same capability ships on http.server: an HTML overview at /, JSON at
/api/jobs, the merged task profile (when --profile ran) at
/api/profile, fed by the scheduler's event history.  r5 (VERDICT r4
weak #5): per-job stage DAG view, per-task drill-down (click a stage
row), profile panel.
"""

import http.server
import json
import threading

from dpark_tpu.utils.log import get_logger

logger = get_logger("web")

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>dpark_tpu</title>
<style>
 body { font-family: monospace; margin: 2em; }
 table { border-collapse: collapse; margin-bottom: 1em; }
 td, th { border: 1px solid #999; padding: 4px 10px; text-align: left; }
 .done { color: #2a2; } .run { color: #d80; } .fail { color: #c22; }
 .dag { white-space: pre; background: #f6f6f6; padding: 8px;
        display: inline-block; margin: 2px 0 10px; }
 .tasks { margin-left: 2em; }
 .stage { cursor: pointer; }
 pre { background: #f6f6f6; padding: 8px; }
</style></head>
<body>
<h2>dpark_tpu jobs</h2>
<table id="t"><tr><th>job</th><th>scope</th><th>parts</th>
<th>finished</th><th>stages</th><th>seconds</th><th>state</th>
<th>recovery (resubmit/recompute/retry)</th>
<th>decodes (repair/straggler/fail)</th>
<th>adapt (steered/logged)</th></tr></table>
<h2>stages <small>(click a row for its tasks; DAG per job below)</small></h2>
<table id="s"><tr><th>job</th><th>stage</th><th>rdd</th>
<th>parts</th><th>kind</th><th>seconds</th><th>device run s</th>
<th>HBM bytes</th><th>wire bytes</th><th>pad eff</th>
<th>waves</th><th>idle %</th><th>pipeline ms (in/cmp/xchg/spill)</th>
<th>decodes</th>
<th>fallback / degrade</th>
</tr></table>
<div id="dags"></div>
<h2>profile</h2>
<pre id="prof">(run with --profile)</pre>
<script>
const open = new Set();
function dagText(j) {
  // topological-ish text DAG: each stage with its parents as edges
  const lines = ['job ' + j.id + '  (' + (j.scope || '') + ')'];
  for (const st of (j.stage_info || [])) {
    const par = (st.parents && st.parents.length)
      ? st.parents.map(p => 'stage ' + p).join(', ') : 'source';
    lines.push('  ' + par + '  ->  stage ' + st.id +
               '  [' + (st.rdd || '?') + ', ' + (st.kind || '?') + ']');
  }
  return lines.join('\\n');
}
function taskRows(st) {
  const ts = st.tasks || [];
  if (!ts.length) return '(no per-task records)';
  let h = '<table><tr><th>part</th><th>seconds</th><th>host</th>' +
          '<th>ok</th></tr>';
  for (const t of ts)
    h += '<tr class="' + (t.ok ? 'done' : 'fail') + '"><td>' + t.p +
         '</td><td>' + t.s + '</td><td>' + (t.host || '') +
         '</td><td>' + t.ok + '</td></tr>';
  return h + '</table>';
}
async function tick() {
  const r = await fetch('/api/jobs'); const jobs = await r.json();
  const t = document.getElementById('t');
  while (t.rows.length > 1) t.deleteRow(1);
  const s = document.getElementById('s');
  while (s.rows.length > 1) s.deleteRow(1);
  const dags = document.getElementById('dags');
  dags.innerHTML = '';
  for (const j of jobs) {
    const row = t.insertRow();
    // lineage-recovery accounting (ISSUE 5): FetchFailed parent
    // resubmits / intact-parent recomputes / task retries per job
    const rec = (j.resubmits || 0) + '/' + (j.recomputes || 0) + '/' +
                (j.retries || 0);
    // coded-shuffle decode accounting (ISSUE 6): parity repairs /
    // straggler wins / failed decodes attributed to this job, with
    // the active code mode when one is configured
    const dj = j.decodes || {};
    const dec = dj.mode
      ? (dj.repair || 0) + '/' + (dj.straggler_win || 0) + '/' +
        (dj.decode_failures || 0) + ' [' + dj.mode + ']' : '';
    // adaptive-execution decisions (ISSUE 7): cost-model choices taken
    // during this job — applied steers vs observe-mode would-bes, with
    // the mode; hover a stage's why column for the per-stage reason
    const aj = j.adapt || {};
    const ads = aj.decisions || [];
    const adp = aj.mode
      ? ads.filter(d => d.applied).length + '/' + ads.length +
        ' [' + aj.mode + ']' : '';
    for (const v of [j.id, j.scope, j.parts, j.finished, j.stages,
                     j.seconds, j.state, rec, dec, adp])
      row.insertCell().textContent = v;
    row.className = j.state === 'done' ? 'done' : 'run';
    const d = document.createElement('div');
    d.className = 'dag'; d.textContent = dagText(j);
    dags.appendChild(d); dags.appendChild(document.createElement('br'));
    for (const st of (j.stage_info || [])) {
      const sr = s.insertRow();
      // overlapped wave pipeline (streamed stages): waves, device-idle
      // fraction, and the per-stage ingest/compute/exchange/spill ms —
      // live while the stream runs; the idle-percent drop IS the overlap
      const p = st.pipeline || {};
      const pms = p.waves ? (p.ingest_ms + '/' + p.compute_ms + '/' +
                             p.exchange_ms + '/' + p.spill_ms) : '';
      const idle = p.waves ? (100 * p.device_idle_frac).toFixed(1) : '';
      // why the stage left (or nearly left) the array path: the
      // analyze-time fallback_reason, the runtime degrade_reason, or
      // the cost model's adapt_reason (ISSUE 7: predicted, not
      // assumed, admission)
      const why = st.fallback_reason || st.degrade_reason ||
        st.adapt_reason || '';
      // per-stage decode deltas: activity against THIS stage's map
      // outputs (the parent whose buckets were decoded from parity)
      const ds = st.decodes || {};
      const sdec = Object.keys(ds).length
        ? (ds.repair || 0) + '/' + (ds.straggler_win || 0) + '/' +
          (ds.decode_failures || 0) : '';
      for (const v of [j.id, st.id, st.rdd, st.parts, st.kind,
                       st.seconds, st.run_seconds, st.hbm_bytes,
                       st.wire_bytes, st.pad_efficiency,
                       p.waves, idle, pms, sdec, why])
        sr.insertCell().textContent = v === undefined ? '' : v;
      sr.className = 'stage ' + (st.seconds === null ? 'run' : 'done');
      const key = j.id + ':' + st.id;
      sr.onclick = () => {
        if (open.has(key)) open.delete(key); else open.add(key);
        tick();
      };
      if (open.has(key)) {
        const dr = s.insertRow();
        const c = dr.insertCell(); c.colSpan = 15;
        c.className = 'tasks'; c.innerHTML = taskRows(st);
      }
    }
  }
  const pr = await fetch('/api/profile');
  document.getElementById('prof').textContent = await pr.text();
}
setInterval(tick, 1000); tick();
</script></body></html>"""


def start_ui(scheduler, host="127.0.0.1", port=0):
    """Serve the scheduler's job history; returns (server, url)."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.startswith("/api/jobs"):
                body = json.dumps(
                    list(getattr(scheduler, "history", []))).encode()
                ctype = "application/json"
            elif self.path.startswith("/api/profile"):
                prof = getattr(scheduler, "profile", None)
                body = (prof.summary() if prof is not None
                        else "(run with --profile)").encode()
                ctype = "text/plain; charset=utf-8"
            else:
                body = _PAGE.encode()
                ctype = "text/html; charset=utf-8"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    url = "http://%s:%d/" % server.server_address
    logger.info("web ui at %s", url)
    return server, url
