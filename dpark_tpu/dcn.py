"""DCN bulk data plane: TCP serving of shuffle buckets and broadcast
chunks between hosts.

Reference parity: dpark/shuffle.py serves map-output bucket files over a
per-worker HTTP server, and dpark/broadcast.py distributes ~1MB
compressed chunks P2P over zmq (SURVEY.md section 2.8).  Here one
threaded TCP server per process fronts both: bucket requests resolve to
the workdir bucket files (or the HBM export bridge for device-resident
shuffles), broadcast requests to the chunk files written by
dpark_tpu.broadcast.  The tracker (dpark_tpu/tracker.py) remains the
metadata plane that carries the tcp:// URIs.

Framing: 4-byte length + JSON request array (never pickle — requests
arrive from the network, and unpickling untrusted bytes is arbitrary
code execution; all request fields are ints/strings so JSON loses
nothing).  Response: status byte + 8-byte length + raw payload bytes
(already compressed on disk — the server never recompresses); error
payloads are UTF-8 strings.

Bulk streams (ISSUE 12): a serve callable may return a
:class:`BulkPayload` instead of bytes — the handler then answers with
status byte 2, a crc-framed JSON HEADER line (utils.frame_jsonl — the
spill/adapt/trace framing, one home), and the advertised number of
RAW chunk frames, each ``!IQ`` (crc32, length) + payload bytes.  The
receiving side (dpark_tpu/bulkplane.py) verifies every frame crc
before any byte is interpreted, assembles chunks zero-copy into one
buffer, and translates a torn stream (peer death mid-transfer) into
a bounded-backoff retry.  Wire-frame crcs use zlib.crc32 explicitly —
unlike spill runs, bulk frames cross INSTALLATIONS, so both ends must
agree on the polynomial regardless of who has the native crc32c lib.

Response payloads can still be hostile: shuffle/broadcast clients
unpickle the data they fetch, so a poisoned peer URI or a MITM could
answer with a crafted pickle.  Setting DPARK_DCN_SECRET on every host
closes both directions: requests carry an HMAC-SHA256 tag (only secret
holders can issue requests at all) and responses carry a tag over
status+payload that the client verifies BEFORE any deserialization.
Without the secret, request parsing is still non-executable (JSON),
but fetched payloads are trusted exactly as far as the tracker that
advertised the peer.
"""

import hashlib
import hmac
import json
import os
import pickle  # encode-only: serializing OUR data for peers, never
               # deserializing network input
import random
import socket
import socketserver
import struct
import threading
import time

from dpark_tpu import locks
from dpark_tpu.utils.log import get_logger

logger = get_logger("dcn")


def _routable_host():
    """This host's address as other machines can reach it; loopback only
    as a last resort (single-machine deployments)."""
    name = socket.gethostname()
    try:
        addr = socket.gethostbyname(name)
        if not addr.startswith("127."):
            return addr
    except OSError:
        pass
    try:
        # the address of the default route's interface, no traffic sent
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf += part
    return buf


def _secret():
    return os.environ.get("DPARK_DCN_SECRET", "").encode()


def _encode_req(req):
    blob = json.dumps(list(req), separators=(",", ":")).encode()
    secret = _secret()
    if secret:
        blob = hmac.new(secret, blob, hashlib.sha256).digest() + blob
    return blob


def _decode_req(blob):
    secret = _secret()
    if secret:
        tag, blob = blob[:32], blob[32:]
        want = hmac.new(secret, blob, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise PermissionError("request MAC mismatch")
    return tuple(json.loads(blob.decode("utf-8")))


def wire_crc(blob):
    """Frame checksum for BULK WIRE frames: zlib.crc32, always.  Spill
    runs use the native crc32c when loaded (spill_crc) because they
    never leave the installation that wrote them; wire frames cross
    hosts, and a native-lib asymmetry between peers must not reject
    every frame as corrupt."""
    import zlib
    return zlib.crc32(bytes(blob) if isinstance(blob, memoryview)
                      else blob) & 0xFFFFFFFF


# one chunk frame of a bulk stream: crc32 of the payload + its length
BULK_FRAME = struct.Struct("!IQ")
BULK_STATUS = 2


class BulkPayload:
    """A streaming response from a serve callable: `meta` (a JSON-able
    dict; `nchunks`/`total_bytes` are filled in from `chunks` when the
    chunks are a list) plus the payload chunk iterable.  `on_sent`
    (peer_host, bytes, nchunks) fires after a fully written stream —
    the bulkplane's per-peer sent counters."""

    __slots__ = ("meta", "chunks", "on_sent")

    def __init__(self, meta, chunks, on_sent=None):
        self.meta = dict(meta)
        if not isinstance(chunks, (list, tuple)) \
                and ("nchunks" not in self.meta
                     or "total_bytes" not in self.meta):
            # the receiver reads EXACTLY the advertised geometry: a
            # lazy iterable without it would stream frames the client
            # never reads — an empty "successful" fetch plus a
            # desynced pooled connection.  Materialize rather than
            # trust the caller.
            chunks = list(chunks)
        if isinstance(chunks, (list, tuple)):
            self.meta.setdefault("nchunks", len(chunks))
            self.meta.setdefault("total_bytes",
                                 sum(len(c) for c in chunks))
        self.chunks = chunks
        self.on_sent = on_sent


def chunked(buf, chunk_bytes=None):
    """Split one bytes-like payload into bulk chunk views (memoryview
    slices — no copies server-side).  Typed buffers (numpy column
    .data views) are cast to unsigned bytes FIRST: a memoryview slices
    in elements, and an int64 column advertised as "5 bytes" while 40
    went over the wire would desync every following frame."""
    from dpark_tpu import conf
    step = int(chunk_bytes or conf.BULK_CHUNK_BYTES) or (1 << 20)
    mv = memoryview(buf)
    if mv.format != "B":
        mv = mv.cast("B")
    return [mv[i:i + step] for i in range(0, len(mv), step)]


def _send_bulk(sock, payload):
    """Write one bulk stream: status 2 + framed header + chunk frames.
    The chaos site `dcn.transfer` fires per chunk on the SERVING side
    too, so a deterministic mid-stream peer death is one env var away
    (kind=raise aborts the stream after the header went out — exactly
    what a killed peer looks like to the fetcher)."""
    from dpark_tpu import faults
    from dpark_tpu.utils import frame_jsonl
    header = frame_jsonl(payload.meta)
    secret = _secret()
    tag = hmac.new(secret, bytes([BULK_STATUS]) + header,
                   hashlib.sha256).digest() if secret else b""
    sock.sendall(struct.pack("!BQ", BULK_STATUS, len(header))
                 + header + tag)
    sent = 0
    nchunks = 0
    for chunk in payload.chunks:
        # crc over the TRUE bytes, computed before the chaos site may
        # corrupt them — exactly what in-flight corruption does, and
        # exactly what the receiver's per-frame crc must catch (same
        # contract as the spill-chunk framing).  kind=raise aborts the
        # stream mid-transfer: a deterministic peer death.
        crc = wire_crc(chunk)
        body = faults.hit("dcn.transfer", chunk) \
            if faults._PLANE is not None else chunk
        sock.sendall(BULK_FRAME.pack(crc, len(chunk)))
        sock.sendall(body)
        if secret:
            sock.sendall(hmac.new(secret, chunk,
                                  hashlib.sha256).digest())
        sent += len(chunk)
        nchunks += 1
    return sent, nchunks


class FramedServer:
    """Threaded length-prefixed request/response TCP server shared by
    the bucket server and the chunk-server filesystem: requests are
    JSON arrays of ints/strings (optionally HMAC-tagged — see module
    docstring), responses raw payload bytes with a status byte
    (1 = UTF-8 error string, 2 = bulk stream follows)."""

    def __init__(self, serve, host="0.0.0.0", port=0,
                 name="dpark-framed-server"):
        outer_serve = serve

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        raw = _recv_exact(self.request, 4)
                        (n,) = struct.unpack("!I", raw)
                        frame = _recv_exact(self.request, n)
                        try:
                            req = _decode_req(frame)
                        except Exception:
                            # malformed or unauthenticated frame: hang
                            # up, never answer
                            return
                        try:
                            payload = outer_serve(req)
                            status = 0
                        except Exception as e:
                            payload = str(e).encode(
                                "utf-8", "replace")
                            status = 1
                        if isinstance(payload, BulkPayload):
                            from dpark_tpu import trace
                            with trace.span(
                                    "dcn.bulk.serve", "dcn",
                                    kind=str(req[0]),
                                    peer=self.client_address[0]) as sp:
                                sent, nchunks = _send_bulk(
                                    self.request, payload)
                                if sp is not trace._NOOP:
                                    sp.args["bytes"] = sent
                                    sp.args["chunks"] = nchunks
                            if payload.on_sent is not None:
                                payload.on_sent(
                                    self.client_address[0], sent,
                                    nchunks)
                            continue
                        secret = _secret()
                        tag = hmac.new(
                            secret, bytes([status]) + payload,
                            hashlib.sha256).digest() if secret else b""
                        self.request.sendall(
                            struct.pack("!BQ", status, len(payload))
                            + payload + tag)
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name=name)

    @property
    def bind_address(self):
        return self._server.server_address[:2]

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class BucketServer(FramedServer):
    """Serves this process's shuffle buckets and broadcast chunks."""

    def __init__(self, workdir, host="0.0.0.0", port=0):
        self.workdir = workdir
        self.bcast_serves = {}        # (bid, chunk) -> times served
        self._serves_lock = locks.named_lock("dcn.serves")
        super().__init__(self._serve, host, port,
                         name="dpark-bucket-server")

    @property
    def addr(self):
        """The ADVERTISED uri: must be routable from other hosts (it
        ships in map-output locations and pickled Broadcast handles)."""
        host, port = self.bind_address
        if host == "0.0.0.0":
            host = os.environ.get("DPARK_DCN_HOST") or _routable_host()
        return "tcp://%s:%d" % (host, port)

    def start(self):
        super().start()
        logger.debug("bucket server on %s", self.addr)
        return self

    # -- request handling ----------------------------------------------
    def _serve(self, req):
        kind = req[0]
        if isinstance(kind, str) and kind.startswith("bulk_"):
            # multi-controller bulk data plane (ISSUE 12): chunked
            # crc-framed streams for buckets / coded shard frames /
            # raw HBM columns / broadcast chunks
            from dpark_tpu import bulkplane
            return bulkplane.serve(self, req)
        if kind == "bucket":
            _, sid, map_id, reduce_id = req
            path = os.path.join(self.workdir, "shuffle", str(sid),
                                str(map_id), str(reduce_id))
            if os.path.exists(path):
                with open(path, "rb") as f:
                    return f.read()
            # device-resident shuffle: export through the HBM bridge
            from dpark_tpu import shuffle as shuffle_mod
            from dpark_tpu.utils import compress
            for exporter in shuffle_mod.HBM_EXPORTERS.values():
                try:
                    items = exporter(sid, map_id, reduce_id)
                    return compress(pickle.dumps(items, -1))
                except KeyError:
                    continue
            raise FileNotFoundError(path)
        if kind == "bucket_shard":
            # coded shuffle (ISSUE 6): ONE framed erasure shard of a
            # map output bucket.  An empty payload is the MISS
            # sentinel — the bucket was written uncoded (or this
            # server's coding is off for its HBM store), and the
            # fetch side falls back to the plain bucket protocol.
            _, sid, map_id, reduce_id, idx = req
            path = os.path.join(self.workdir, "shuffle", str(sid),
                                str(map_id),
                                "%d.shards" % reduce_id)
            if os.path.exists(path):
                from dpark_tpu import coding
                with open(path, "rb") as f:
                    try:
                        return coding.extract_container_frame(
                            f.read(), idx)
                    except KeyError:
                        return b""      # container holds no such shard
            from dpark_tpu import shuffle as shuffle_mod
            for exporter in shuffle_mod.HBM_EXPORTERS.values():
                try:
                    return exporter(sid, map_id, reduce_id, shard=idx)
                except KeyError:
                    continue        # this exporter owns no such sid
                except ValueError:
                    break           # no code active / bad shard index
            return b""
        if kind == "bcast_meta":
            _, bid = req
            path = os.path.join(self.workdir, "broadcast",
                                "b%d.meta" % bid)
            with open(path, "rb") as f:
                return f.read()
        if kind == "bcast":
            _, bid, i = req
            path = os.path.join(self.workdir, "broadcast",
                                "b%d.%d" % (bid, i))
            with open(path, "rb") as f:
                data = f.read()
            with self._serves_lock:   # handler threads are concurrent
                self.bcast_serves[(bid, i)] = \
                    self.bcast_serves.get((bid, i), 0) + 1
            return data
        raise ValueError("unknown request %r" % (req[0],))


class ServerError(IOError):
    """The peer answered with an application-level error (status 1) or
    a response that failed MAC verification — as opposed to a transport
    failure.  Retrying the same request on a fresh connection cannot
    help, so connection-pool retry logic must let this through."""


def _request(sock, req):
    from dpark_tpu import trace
    if trace._PLANE is None:
        return _request_impl(sock, req)
    try:
        # per-peer health sketches (ISSUE 14) key on this
        peer = sock.getpeername()[0]
    except OSError:
        peer = "?"
    with trace.span("dcn.transfer", "dcn", kind=str(req[0]),
                    peer=peer) as sp:
        payload = _request_impl(sock, req)
        sp.args["bytes"] = len(payload)
        return payload


def _request_impl(sock, req):
    blob = _encode_req(req)
    sock.sendall(struct.pack("!I", len(blob)) + blob)
    status, n = struct.unpack("!BQ", _recv_exact(sock, 9))
    payload = _recv_exact(sock, n)
    secret = _secret()
    if secret:
        # verify the response BEFORE any caller deserializes it
        tag = _recv_exact(sock, 32)
        want = hmac.new(secret, bytes([status]) + payload,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ServerError("bucket server: response MAC mismatch")
    if status:
        raise ServerError("bucket server: %s"
                          % payload.decode("utf-8", "replace"))
    return payload


def backoff_delays(attempts, base=None, rand=None):
    """The sleep schedule between transient-connect retries:
    exponential with FULL JITTER — attempt k (0-based) sleeps uniform
    in [base * 2^k / 2, base * 2^k], so a fleet of reduce tasks
    retrying a briefly-down peer doesn't reconnect in lockstep.
    Yields attempts-1 delays.  `rand` is injectable for deterministic
    unit tests (tests/test_faults.py runs the schedule on a fake
    clock)."""
    from dpark_tpu import conf
    if base is None:
        base = conf.DCN_CONNECT_BACKOFF
    rand = rand if rand is not None else random
    for k in range(max(0, attempts - 1)):
        span = base * (2 ** k)
        yield span * (0.5 + 0.5 * rand.random())


def _connect(uri, timeout, attempts=None, sleep=time.sleep, rand=None):
    """Connect to a peer bucket server with bounded retry + backoff.

    Only TRANSPORT-level errors (refused/reset/timeout — transient by
    nature: the peer may be restarting or its accept queue full) are
    retried.  The non-retryable classification is preserved: the
    application-level ServerError (status-1 responses, MAC mismatches)
    originates in _request, never here, and callers like FetchPool
    continue to let it through untouched."""
    assert uri.startswith("tcp://"), uri
    from dpark_tpu import conf, faults, trace
    host, _, port = uri[len("tcp://"):].partition(":")
    attempts = max(1, conf.DCN_CONNECT_ATTEMPTS
                   if attempts is None else attempts)
    delays = backoff_delays(attempts, rand=rand)
    last_err = None
    for k in range(attempts):
        try:
            faults.hit("dcn.connect")
            with trace.span("dcn.connect", "dcn", uri=uri,
                            attempt=k + 1):
                return socket.create_connection((host, int(port)),
                                                timeout=timeout)
        except (ConnectionError, OSError) as e:
            last_err = e
            d = next(delays, None)
            if d is None:
                break
            logger.debug("connect to %s failed (%s); retry %d/%d in "
                         "%.3fs", uri, e, k + 1, attempts - 1, d)
            sleep(d)
    raise last_err


# ---------------------------------------------------------------------------
# peer-liveness leases (ISSUE 20 tentpole b).  Every successful
# transfer renews the serving peer's lease for conf.PEER_LEASE_MS; a
# transport failure AFTER the lease lapsed (or against a peer never
# heard from) marks the peer SUSPECT — counted once per transition as
# `lease_expiries` — and the coded fetch path fails that peer's shard
# attempts fast, racing parity shards from live peers instead of
# waiting out socket timeouts.  A suspect peer re-probes after the
# same interval, so a restarted process rejoins without operator
# action.  conf.PEER_LEASE_MS == 0 disables tracking entirely.
# ---------------------------------------------------------------------------

_LIVE_LOCK = threading.Lock()
_LEASES = {}        # peer key -> monotonic lease expiry
_SUSPECT = {}       # peer key -> monotonic suspected-at time
_LIVE_COUNTERS = {"lease_expiries": 0, "renewals": 0, "fast_fails": 0}


def _lease_s():
    from dpark_tpu import conf
    return float(getattr(conf, "PEER_LEASE_MS", 0) or 0) / 1000.0


def peer_key(uri):
    """Lease registry key: host:port for tcp:// uris (two controllers
    on one host are distinct peers), the uri itself otherwise."""
    if uri.startswith("tcp://"):
        return uri[len("tcp://"):]
    return uri


def note_peer_ok(uri, now=None):
    """A transfer from `uri` succeeded: renew its lease, clear any
    suspicion."""
    lease = _lease_s()
    if not lease:
        return
    now = time.monotonic() if now is None else now
    key = peer_key(uri)
    with _LIVE_LOCK:
        _LEASES[key] = now + lease
        _SUSPECT.pop(key, None)
        _LIVE_COUNTERS["renewals"] += 1


def note_peer_fail(uri, now=None):
    """A TRANSPORT failure against `uri` (application-level
    ServerError is the peer answering fine — never reported here).
    Marks the peer suspect only once its lease has lapsed; failures
    within a live lease are ordinary transients the retry path owns."""
    lease = _lease_s()
    if not lease:
        return
    now = time.monotonic() if now is None else now
    key = peer_key(uri)
    with _LIVE_LOCK:
        if key in _SUSPECT:
            return
        expiry = _LEASES.get(key)
        if expiry is None or now > expiry:
            _SUSPECT[key] = now
            _LIVE_COUNTERS["lease_expiries"] += 1
            logger.warning("peer %s lease expired; marking suspect "
                           "(hedging to parity/replicas)", key)


def peer_alive(uri, now=None):
    """False while `uri` is suspect inside its re-probe window.  The
    coded fetch path consults this to fail a dead peer's shard
    attempts fast; callers must treat False as a HINT (race parity
    first), never as permission to skip lineage recovery."""
    lease = _lease_s()
    if not lease:
        return True
    now = time.monotonic() if now is None else now
    key = peer_key(uri)
    with _LIVE_LOCK:
        t = _SUSPECT.get(key)
        if t is None:
            return True
        if now - t > lease:
            # re-probe window: give the peer one fresh chance
            _SUSPECT.pop(key, None)
            return True
        _LIVE_COUNTERS["fast_fails"] += 1
        return False


def liveness_stats():
    """Counters + current suspect set for /metrics and
    recovery_summary(); None when leases are disabled."""
    if not _lease_s():
        return None
    with _LIVE_LOCK:
        out = dict(_LIVE_COUNTERS)
        out["suspect"] = sorted(_SUSPECT)
        out["leased_peers"] = len(_LEASES)
    return out


def reset_liveness():
    with _LIVE_LOCK:
        _LEASES.clear()
        _SUSPECT.clear()
        for k in _LIVE_COUNTERS:
            _LIVE_COUNTERS[k] = 0


def _timeout_s(timeout):
    """Resolve the conf-driven fetch deadline (ISSUE 20 satellite:
    DPARK_DCN_TIMEOUT_MS replaces the old hardcoded 30s)."""
    if timeout is not None:
        return timeout
    from dpark_tpu import conf
    return float(getattr(conf, "DCN_TIMEOUT_MS", 30000)) / 1000.0


def fetch(uri, req, timeout=None, attempts=None):
    """One request against a tcp:// bucket server; returns payload
    bytes.  Raises on any transport or server error (callers translate
    to FetchFailed for lineage recovery).  Transport failures retry up
    to conf.DCN_RETRIES total attempts on a fresh connection with the
    shared exponential-full-jitter backoff; ServerError never retries.
    Outcomes feed the peer-liveness leases."""
    from dpark_tpu import conf
    timeout = _timeout_s(timeout)
    attempts = max(1, int(getattr(conf, "DCN_RETRIES", 1) or 1)
                   if attempts is None else attempts)
    delays = backoff_delays(attempts)
    last_err = None
    for _ in range(attempts):
        try:
            with _connect(uri, timeout) as sock:
                payload = _request(sock, req)
            note_peer_ok(uri)
            return payload
        except ServerError:
            note_peer_ok(uri)    # the peer is alive; it just said no
            raise
        except (ConnectionError, OSError) as e:
            last_err = e
            note_peer_fail(uri)
            d = next(delays, None)
            if d is None:
                break
            time.sleep(d)
    raise last_err


def fetch_many(uri, reqs, timeout=None):
    """Several requests over ONE connection (the server handler loops);
    yields payloads in request order — e.g. all chunks of a broadcast
    without per-chunk connect/teardown."""
    timeout = _timeout_s(timeout)
    try:
        with _connect(uri, timeout) as sock:
            out = [_request(sock, req) for req in reqs]
    except ServerError:
        note_peer_ok(uri)
        raise
    except (ConnectionError, OSError):
        note_peer_fail(uri)
        raise
    note_peer_ok(uri)
    return out


class FetchPool:
    """One open connection per uri, reused across requests — the
    P2P broadcast fetch re-plans its source per chunk, which would
    otherwise mean one TCP handshake per chunk."""

    def __init__(self, timeout=None):
        self.timeout = _timeout_s(timeout)
        self._socks = {}

    def fetch(self, uri, req):
        sock = self._socks.get(uri)
        if sock is None:
            sock = self._socks[uri] = _connect(uri, self.timeout)
        try:
            payload = _request(sock, req)
        except ServerError:
            note_peer_ok(uri)   # application error: the connection is
            raise               # fine and a resend would just fail again
        except (ConnectionError, OSError):
            # one reconnect: the cached socket may be stale
            self.close_uri(uri)
            try:
                sock = self._socks[uri] = _connect(uri, self.timeout)
                payload = _request(sock, req)
            except ServerError:
                note_peer_ok(uri)
                raise
            except (ConnectionError, OSError):
                note_peer_fail(uri)
                raise
        note_peer_ok(uri)
        return payload

    def close_uri(self, uri):
        sock = self._socks.pop(uri, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        for uri in list(self._socks):
            self.close_uri(uri)
