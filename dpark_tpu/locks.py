"""Named-lock registry + runtime lock-order sanitizer (ISSUE 16).

The resident JobServer multiplexes many tenants onto one mesh behind a
web of locks (the metered mesh lock, the scheduler's graph/metrics
locks, service slot queues, the ledger/health sink locks).  Every
deadlock to date was found by luck at runtime: the PR 3 export-bucket
collective wedge and the PR 9 mesh->shard_build inversion each cost a
debugging session that a cycle detector would have flagged from one
clean run.  This module is that detector — the dynamic half of the
concurrency sanitizer plane (the static half lives in
``dpark_tpu.analysis.concurrency``).

Modes (``DPARK_LOCKCHECK`` / conf.DPARK_LOCKCHECK):

  off     no sanitizer installed.  Every named lock costs exactly one
          module-global load + ``is None`` check per acquisition on
          top of the raw ``threading`` primitive — the same off-mode
          contract as the faults/trace/health/ledger planes, and
          machine-checked by the ``plane-contract`` dlint rule.
  record  per-thread acquisition order is recorded and merged into a
          process-wide edge graph; :func:`cycles` reports every cycle
          OBSERVED ACROSS THE WHOLE RUN even when no deadlock fired
          (two threads that each survived their inverted acquisitions
          still drew the edges).  CI arms this across the full test
          suite, so a future PR that inverts an order fails fast.
  strict  like record, but the acquisition that CLOSES a cycle (or
          re-acquires a non-reentrant lock the same thread already
          holds) raises :class:`LockOrderError` naming the cycle
          before the lock is taken — the deadlock becomes a stack
          trace instead of a wedge.

Lock identity is the NAME, not the instance: every ``named_lock`` and
every :class:`~dpark_tpu.backend.tpu.executor._MeshLock` acquisition
under one name merges into the same node of the order graph, so a
cycle between e.g. ``executor.mesh`` and ``executor.shard_build`` is
reported no matter which executor instance drew it.

The documented global order lives in :data:`DOCUMENTED_ORDER`; the
first entry pair records the rule PR 9 fixed (``executor.mesh`` before
``executor.shard_build``, never inverted).  ``report()`` grades the
observed edges against it.
"""

import sys
import threading

from dpark_tpu import conf

MODES = ("off", "record", "strict")

_SANITIZER = None            # the `is None` check every acquisition makes
_install_mu = threading.Lock()

# The documented global lock order: a lock earlier in this tuple may be
# held while acquiring a later one, NEVER the reverse.  Locks absent
# from the tuple are unordered (the sanitizer still catches their
# cycles; it just can't grade them against documentation).  Keep the
# README "Concurrency sanitizer" section in sync.
DOCUMENTED_ORDER = (
    "service.server",        # JobServer lifecycle (start/stop)
    "schedule.graph",        # DAG registration
    "schedule.metrics",      # per-stage metric folds
    "executor.mesh",         # THE mesh lock: every device dispatch
    "executor.shard_build",  # PR 9 rule: mesh -> shard_build only
    "executor.program_cache",
    "aot.store",             # AOT disk-tier counters/preload map: a
    #                          program-cache eviction write-back and
    #                          a proxy resolving under a device
    #                          dispatch both reach it, never the
    #                          reverse
    "shuffle.shard_pool",
    "dcn.serves",
    "resultcache.store",     # shared result cache LRU/counters: the
    #                          planner probes it before any job
    #                          exists and offers under a finished
    #                          query; its trace events emit AFTER
    #                          release, so it must order before
    #                          trace.plane and never nest under it
    "trace.plane",           # span ring/spool (spans emit under mesh)
    "health.sink",
    "ledger.sink",
    "ledger.cost",
)
_ORDER_INDEX = {n: i for i, n in enumerate(DOCUMENTED_ORDER)}


class LockOrderError(RuntimeError):
    """Strict mode: this acquisition would close a lock-order cycle
    (or self-deadlock a non-reentrant lock).  ``.cycle`` carries the
    named path, e.g. ``["executor.mesh", "executor.shard_build",
    "executor.mesh"]``."""

    def __init__(self, message, cycle=()):
        super().__init__(message)
        self.cycle = list(cycle)


class Sanitizer:
    """Process-wide acquisition-order recorder.

    Per-thread state is a held-lock stack (thread-local: no lock
    needed); the global edge graph merges under one internal mutex
    which is deliberately a RAW ``threading.Lock`` — the sanitizer
    must never observe itself."""

    def __init__(self, strict=False):
        self.strict = strict
        self._mu = threading.Lock()
        self._tls = threading.local()
        self.locks = {}          # name -> {"count", "reentrant"}
        self.edges = {}          # (held, acquired) -> {"count", "site"}
        self.findings = []       # self-deadlock shapes seen in record mode
        self.acquisitions = 0

    # -- per-thread stack ------------------------------------------------
    def _held(self):
        tls = self._tls
        held = getattr(tls, "held", None)
        if held is None:
            held = tls.held = []           # acquisition-ordered names
            tls.counts = {}                # name -> depth
        return held

    # -- notes -----------------------------------------------------------
    def acquiring(self, name, reentrant=True):
        """Called BEFORE the underlying acquire so a strict-mode cycle
        (or self-deadlock) raises instead of wedging."""
        held = self._held()
        counts = self._tls.counts
        depth = counts.get(name, 0)
        if depth:
            if not reentrant:
                msg = ("self-deadlock: thread %r re-acquires "
                       "non-reentrant lock %r it already holds"
                       % (threading.current_thread().name, name))
                with self._mu:
                    self.findings.append(
                        {"kind": "self-deadlock", "lock": name,
                         "detail": msg})
                if self.strict:
                    raise LockOrderError(msg, [name, name])
            counts[name] = depth + 1
            return
        new = [(h, name) for h in held if h != name]
        site = None
        cycle = None
        with self._mu:
            self.acquisitions += 1
            ent = self.locks.get(name)
            if ent is None:
                self.locks[name] = {"count": 1, "reentrant": reentrant}
            else:
                ent["count"] += 1
            for e in new:
                eent = self.edges.get(e)
                if eent is not None:
                    eent["count"] += 1
                    continue
                if site is None:
                    site = _caller_site()
                self.edges[e] = {"count": 1, "site": site}
                if cycle is None:
                    path = self._path(e[1], e[0])
                    if path is not None:
                        cycle = [e[0]] + path
        counts[name] = 1
        held.append(name)
        if cycle is not None:
            msg = ("lock-order cycle closed by acquiring %r while "
                   "holding %r: %s (first drawn at %s)"
                   % (name, cycle[0], " -> ".join(cycle), site))
            with self._mu:
                self.findings.append(
                    {"kind": "lock-order-cycle", "lock": name,
                     "cycle": cycle, "detail": msg})
            if self.strict:
                raise LockOrderError(msg, cycle)

    def released(self, name):
        tls = self._tls
        counts = getattr(tls, "counts", None)
        if not counts:
            return                       # armed mid-hold: tolerate
        depth = counts.get(name, 0)
        if depth > 1:
            counts[name] = depth - 1
            return
        if depth == 1:
            del counts[name]
            try:
                tls.held.remove(name)
            except ValueError:
                pass

    def abandon(self, name):
        """Un-note an acquisition whose underlying acquire failed."""
        self.released(name)

    # -- graph queries (all under _mu) -----------------------------------
    def _succ(self):
        succ = {}
        for (a, b) in self.edges:
            succ.setdefault(a, []).append(b)
        return succ

    def _path(self, src, dst):
        """Shortest edge path src -> ... -> dst, or None.  Caller holds
        _mu."""
        if src == dst:
            return [src]
        succ = self._succ()
        seen = {src}
        frontier = [[src]]
        while frontier:
            nxt = []
            for path in frontier:
                for b in succ.get(path[-1], ()):
                    if b in seen:
                        continue
                    if b == dst:
                        return path + [b]
                    seen.add(b)
                    nxt.append(path + [b])
            frontier = nxt
        return None

    def cycles(self):
        """Every distinct cycle in the observed order graph, each as a
        named path closing on its first element.  Empty list = no
        inversion was ever observed."""
        with self._mu:
            succ = self._succ()
            nodes = sorted(set(succ)
                           | {b for bs in succ.values() for b in bs})
            sccs = _tarjan(nodes, succ)
            out = []
            for scc in sccs:
                group = set(scc)
                if len(scc) == 1:
                    n = scc[0]
                    if n not in succ.get(n, ()):
                        continue
                    out.append([n, n])
                    continue
                # one representative cycle: walk within the SCC from
                # its smallest node back to itself
                start = min(scc)
                path = self._scc_cycle(start, group, succ)
                if path:
                    out.append(path)
            return out

    @staticmethod
    def _scc_cycle(start, group, succ):
        seen = {start}
        frontier = [[start]]
        while frontier:
            nxt = []
            for path in frontier:
                for b in succ.get(path[-1], ()):
                    if b == start:
                        return path + [start]
                    if b in group and b not in seen:
                        seen.add(b)
                        nxt.append(path + [b])
            frontier = nxt
        return None

    def order_violations(self):
        """Observed edges that contradict DOCUMENTED_ORDER (held a
        later lock while acquiring an earlier one)."""
        out = []
        with self._mu:
            for (a, b), ent in sorted(self.edges.items()):
                ia, ib = _ORDER_INDEX.get(a), _ORDER_INDEX.get(b)
                if ia is not None and ib is not None and ia > ib:
                    out.append({"held": a, "acquired": b,
                                "count": ent["count"],
                                "site": ent["site"]})
        return out

    def report(self):
        cyc = self.cycles()
        with self._mu:
            edges = [{"from": a, "to": b, "count": e["count"],
                      "site": e["site"]}
                     for (a, b), e in sorted(self.edges.items())]
            locks = {n: dict(v) for n, v in sorted(self.locks.items())}
            findings = list(self.findings)
            acq = self.acquisitions
        return {"mode": "strict" if self.strict else "record",
                "acquisitions": acq, "locks": locks, "edges": edges,
                "cycles": cyc, "findings": findings,
                "order_violations": self.order_violations()}


def _tarjan(nodes, succ):
    """Strongly connected components (iterative Tarjan)."""
    index = {}
    low = {}
    onstack = set()
    stack = []
    sccs = []
    counter = [0]
    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        onstack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for child in it:
                if child not in index:
                    index[child] = low[child] = counter[0]
                    counter[0] += 1
                    stack.append(child)
                    onstack.add(child)
                    work.append((child, iter(succ.get(child, ()))))
                    advanced = True
                    break
                if child in onstack:
                    low[node] = min(low[node], index[child])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    n = stack.pop()
                    onstack.discard(n)
                    scc.append(n)
                    if n == node:
                        break
                sccs.append(sorted(scc))
    return sccs


def _caller_site():
    """file:line of the acquisition site (first frame outside this
    module) — computed only when an edge is FIRST drawn."""
    f = sys._getframe(1)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "?"
    import os
    return "%s:%d" % (os.path.basename(f.f_code.co_filename),
                      f.f_lineno)


class _NamedLock:
    """A ``threading.Lock``/``RLock`` wrapped with a stable name.  With
    the sanitizer off (``_SANITIZER is None``) an acquisition is the
    raw primitive plus exactly one global load + ``is None`` check —
    the plane off-mode contract."""

    __slots__ = ("_lock", "name", "reentrant")

    def __init__(self, name, reentrant=False):
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self.name = name
        self.reentrant = reentrant

    def __enter__(self):
        san = _SANITIZER
        if san is not None:
            san.acquiring(self.name, self.reentrant)
            try:
                self._lock.acquire()
            except BaseException:
                san.abandon(self.name)
                raise
            return self
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        san = _SANITIZER
        if san is not None:
            san.released(self.name)
        self._lock.release()
        return False

    def acquire(self, blocking=True, timeout=-1):
        san = _SANITIZER
        if san is None:
            return self._lock.acquire(blocking, timeout)
        if blocking:
            san.acquiring(self.name, self.reentrant)
            try:
                got = self._lock.acquire(blocking, timeout)
            except BaseException:
                san.abandon(self.name)
                raise
            if not got:
                san.abandon(self.name)
            return got
        got = self._lock.acquire(False)
        if got:
            # can't wedge: note post-acquire (edges are identical)
            san.acquiring(self.name, self.reentrant)
        return got

    def release(self):
        san = _SANITIZER
        if san is not None:
            san.released(self.name)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __repr__(self):
        return "<_NamedLock %s%s>" % (self.name,
                                      " (reentrant)" if self.reentrant
                                      else "")


def named_lock(name, reentrant=False):
    """A registry lock: behaves exactly like ``threading.Lock()`` (or
    ``RLock()``) with the sanitizer off; with it on, every acquisition
    records into the process-wide order graph under ``name``."""
    return _NamedLock(name, reentrant)


# ---------------------------------------------------------------------------
# notes for externally-managed locks (the metered _MeshLock keeps its
# own RLock; it calls these around its depth-0 acquisitions)
# ---------------------------------------------------------------------------

def note_acquire(name, reentrant=True):
    san = _SANITIZER
    if san is not None:
        san.acquiring(name, reentrant)


def note_release(name):
    san = _SANITIZER
    if san is not None:
        san.released(name)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def configure(mode=None):
    """Install (record/strict) or clear (off) the process sanitizer.
    None reads conf.DPARK_LOCKCHECK.  Returns the Sanitizer or None."""
    global _SANITIZER
    if mode is None:
        mode = str(getattr(conf, "DPARK_LOCKCHECK", "off") or "off")
    mode = str(mode).strip().lower()
    if mode in ("", "0", "none", "disable", "disabled"):
        mode = "off"
    if mode not in MODES:
        raise ValueError("DPARK_LOCKCHECK=%r (expected "
                         "off|record|strict)" % mode)
    with _install_mu:
        _SANITIZER = (None if mode == "off"
                      else Sanitizer(strict=(mode == "strict")))
        return _SANITIZER


def active():
    return _SANITIZER is not None


def mode():
    san = _SANITIZER
    if san is None:
        return "off"
    return "strict" if san.strict else "record"


def sanitizer():
    return _SANITIZER


def cycles():
    san = _SANITIZER
    return san.cycles() if san is not None else []


def report():
    san = _SANITIZER
    return san.report() if san is not None else {"mode": "off"}


def render_report(rep=None):
    """Human-readable cycle report (the README documents how to read
    it): every observed edge with its first site, then each cycle as a
    named path, then documented-order violations."""
    rep = rep or report()
    lines = ["lockcheck mode=%s acquisitions=%d locks=%d"
             % (rep.get("mode"), rep.get("acquisitions", 0),
                len(rep.get("locks", {})))]
    for e in rep.get("edges", ()):
        lines.append("  edge %-24s -> %-24s x%-5d first at %s"
                     % (e["from"], e["to"], e["count"], e["site"]))
    for c in rep.get("cycles", ()):
        lines.append("  CYCLE %s" % " -> ".join(c))
    for v in rep.get("order_violations", ()):
        lines.append("  ORDER VIOLATION held %s while acquiring %s "
                     "(documented order says the reverse; first at %s)"
                     % (v["held"], v["acquired"], v["site"]))
    for f in rep.get("findings", ()):
        lines.append("  FINDING %s: %s" % (f["kind"], f["detail"]))
    return "\n".join(lines)


class scoped:
    """Context manager installing a FRESH sanitizer and restoring the
    previous one on exit — unit tests draw deliberate cycles without
    polluting the suite-wide recorder CI grades at session end."""

    def __init__(self, mode="record"):
        self._mode = mode

    def __enter__(self):
        global _SANITIZER
        with _install_mu:
            self._prev = _SANITIZER
            _SANITIZER = (None if self._mode == "off"
                          else Sanitizer(strict=(self._mode == "strict")))
            return _SANITIZER

    def __exit__(self, *exc):
        global _SANITIZER
        with _install_mu:
            _SANITIZER = self._prev
        return False


def _init_from_conf():
    m = str(getattr(conf, "DPARK_LOCKCHECK", "off") or "off")
    if m not in ("off", ""):
        configure(m)


_init_from_conf()
