"""Device-native Bagel: the Pregel superstep as fused XLA programs.

Reference: dpark/bagel.py superstep loop (SURVEY.md 3.2).  The survey's
[H] TPU mapping is implemented literally: messages ride a hash(dst)
all_to_all, the message combine is a monoid segment reduction, the
global aggregator is a psum over the mesh axis, and the halting counters
come back to the host loop each superstep.

Vertex state is columnar — int64 ids, numeric value leaves, bool active
flags — sharded over the mesh by hash(id), so hash-routed messages land
on the device that owns their target.  Edges are stored with their
SOURCE vertex, making message generation a local gather; the per-edge
messages are pre-combined per destination (the Combiner optimization)
before the exchange.  The Python superstep loop stays on the host,
exactly like the reference; everything between two host iterations is
three jitted shard_map programs plus the count-exchange rounds.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dpark_tpu import conf
from dpark_tpu.bagel import (
    PREGEL_MONOIDS, PregelInputError, as_leaves, monoid_identity,
    rewrap)
from dpark_tpu.backend.tpu import collectives, layout
from dpark_tpu.backend.tpu.executor import _shard_map
from dpark_tpu.utils.log import get_logger
from dpark_tpu.utils.phash import phash_np

logger = get_logger("tpu.bagel")

AXIS = conf.MESH_AXIS
_SENT = np.iinfo(np.int64).max


def _local_reduce(kind, x):
    return {"add": jnp.sum, "min": jnp.min,
            "max": jnp.max, "mul": jnp.prod}[kind](x)


def _axis_reduce(kind, x):
    """Cross-device reduction of a per-device scalar (the psum of the
    survey mapping; min/max ride pmin/pmax, mul gathers — it's one
    scalar per device)."""
    if kind == "add":
        return lax.psum(x, AXIS)
    if kind == "min":
        return lax.pmin(x, AXIS)
    if kind == "max":
        return lax.pmax(x, AXIS)
    return jnp.prod(lax.all_gather(x, AXIS))


class DevicePregel:
    """One Pregel run over the executor's mesh.  See bagel.run_pregel for
    the user-facing contract."""

    def __init__(self, executor, ids, values, edges, compute, send,
                 combine="add", edge_values=None, active=None,
                 initial_messages=None, aggregator=None,
                 max_superstep=80, static_superstep=False,
                 send_gate_leaf=None):
        if combine not in PREGEL_MONOIDS:
            raise ValueError(
                "combine must be one of %s" % (PREGEL_MONOIDS,))
        # static_superstep: compile one step program PER superstep with
        # `s` as a Python int (user compute branches on it — e.g. the
        # columnarized object-Bagel adapter); default traces s as data
        # so one program serves every superstep
        self.static_superstep = bool(static_superstep)
        # send_gate_leaf: index of a bool vertex-state leaf that
        # REPLACES post-compute `active` as the send mask (the object
        # contract delivers messages from a vertex that emitted and
        # then halted, and nothing from an active vertex that emitted
        # none — neither is expressible with the active gate alone)
        self.send_gate = send_gate_leaf
        self.ex = executor
        self.ndev = executor.ndev
        self.mesh = executor.mesh
        self.compute = compute
        self.send = send
        self.combine = combine
        self.aggregator = aggregator
        self.max_superstep = max_superstep
        self._compiled = {}
        self._setup(ids, values, edges, edge_values, active,
                    initial_messages)

    # ------------------------------------------------------------------
    # host-side setup: partition vertices by hash(id), edges by source
    # ------------------------------------------------------------------
    def _setup(self, ids, values, edges, edge_values, active, init_msgs):
        ndev = self.ndev
        ids = np.ascontiguousarray(np.asarray(ids, np.int64))
        n = ids.shape[0]
        if np.unique(ids).shape[0] != n:
            raise PregelInputError("vertex ids must be unique")
        if n and int(ids.max()) == _SENT:
            raise PregelInputError(
                "vertex id equals the padding sentinel")
        vleaves, self.v_tuple = as_leaves(values)
        vleaves = [np.asarray(l) for l in vleaves]
        act = (np.ones(n, bool) if active is None
               else np.asarray(active, bool))

        vdev = (phash_np(ids) % np.uint32(ndev)).astype(np.int64)
        sid = np.argsort(ids)
        sorted_ids = ids[sid]

        src, dst = np.asarray(edges[0], np.int64), \
            np.asarray(edges[1], np.int64)
        eleaves, self.e_tuple = ((None, False) if edge_values is None
                                 else as_leaves(edge_values))
        eleaves = [np.asarray(l) for l in eleaves] if eleaves else []
        pos = np.searchsorted(sorted_ids, src)
        pos = np.clip(pos, 0, max(0, n - 1))
        src_idx = sid[pos] if n else pos
        if src.size and (n == 0
                         or not np.array_equal(ids[src_idx], src)):
            raise PregelInputError("edge source not in vertex ids")
        deg = np.bincount(src_idx, minlength=n) if src.size \
            else np.zeros(n, np.int64)
        edev = vdev[src_idx] if src.size else src_idx

        # per-device vertex tables, sorted by id (searchsorted
        # alignment).  One lexsort by (device, id) gives contiguous
        # per-device runs — no O(n*ndev) mask scans.
        vorder = np.lexsort((ids, vdev))
        vbounds = np.searchsorted(vdev[vorder], np.arange(ndev + 1))
        vcnt = np.diff(vbounds).astype(np.int32)
        self.cap_v = layout.round_capacity(int(vcnt.max()) if n else 1)
        vid = np.full((ndev, self.cap_v), _SENT, np.int64)
        h_vals = [np.zeros((ndev, self.cap_v) + l.shape[1:], l.dtype)
                  for l in vleaves]
        h_act = np.zeros((ndev, self.cap_v), bool)
        # device-local sorted position of every vertex (for edge gather)
        local_slot = np.zeros(n, np.int64)
        local_slot[vorder] = np.arange(n) - vbounds[vdev[vorder]]
        for d in range(ndev):
            lo, hi = int(vbounds[d]), int(vbounds[d + 1])
            c = hi - lo
            if not c:
                continue
            sel = vorder[lo:hi]
            vid[d, :c] = ids[sel]
            for hl, l in zip(h_vals, vleaves):
                hl[d, :c] = l[sel]
            h_act[d, :c] = act[sel]

        # per-device edge tables, living with their source vertex
        eorder = np.argsort(edev, kind="stable")
        ebounds = np.searchsorted(edev[eorder], np.arange(ndev + 1))
        ecnt = np.diff(ebounds).astype(np.int32)
        self.cap_e = layout.round_capacity(
            int(ecnt.max()) if src.size else 1)
        e_dst = np.full((ndev, self.cap_e), _SENT, np.int64)
        e_slot = np.zeros((ndev, self.cap_e), np.int32)
        e_deg = np.ones((ndev, self.cap_e), np.int64)
        h_evals = [np.zeros((ndev, self.cap_e) + l.shape[1:], l.dtype)
                   for l in eleaves]
        for d in range(ndev):
            lo, hi = int(ebounds[d]), int(ebounds[d + 1])
            c = hi - lo
            if not c:
                continue
            sel = eorder[lo:hi]
            e_dst[d, :c] = dst[sel]
            e_slot[d, :c] = local_slot[src_idx[sel]]
            e_deg[d, :c] = deg[src_idx[sel]]
            for hl, l in zip(h_evals, eleaves):
                hl[d, :c] = l[sel]

        sh = self._sharding()
        put = lambda a: jax.device_put(a, sh)       # noqa: E731
        self.vid = put(vid)
        self.vcnt = put(vcnt)
        self.values = [put(l) for l in h_vals]
        self.active = put(h_act)
        self.e_dst = put(e_dst)
        self.e_slot = put(e_slot)
        self.e_deg = put(e_deg)
        self.e_vals = [put(l) for l in h_evals]
        self.ecnt = put(ecnt)

        # message leaf specs, discovered by tracing `send` once (the
        # per-edge/per-vertex structs keep their trailing dims — a
        # vector vertex state must probe as a vector, or the discovered
        # message shape collapses to a scalar)
        e_structs = [jax.ShapeDtypeStruct(l.shape[1:], l.dtype)
                     for l in eleaves]
        v_structs = [jax.ShapeDtypeStruct(l.shape[1:], l.dtype)
                     for l in vleaves]
        out = jax.eval_shape(
            lambda sv, ev, dg: self.send(
                rewrap(list(sv), self.v_tuple),
                rewrap(list(ev), self.e_tuple) if eleaves else None, dg),
            tuple(v_structs), tuple(e_structs),
            jax.ShapeDtypeStruct((), np.int64))
        m_leaves, self.m_tuple = as_leaves(out)
        for s in m_leaves:
            if len(s.shape) > 1:
                raise PregelInputError("message leaves must be scalars "
                                       "or 1-D vectors")
        self.msg_dtypes = [np.dtype(s.dtype) for s in m_leaves]
        # trailing per-message shape of each leaf: () scalars, or (k,)
        # sum-vector leaves riding as one rank-2 exchange column
        self.msg_shapes = [tuple(s.shape) for s in m_leaves]

        # initial messages, routed to their target's device
        self.init = None
        if init_msgs is not None:
            idst = np.asarray(init_msgs[0], np.int64)
            ivls, _ = as_leaves(init_msgs[1])
            ivls = [np.asarray(l) for l in ivls]
            if idst.size:
                if len(ivls) != len(self.msg_dtypes):
                    raise PregelInputError(
                        "initial message leaves mismatch: got %d, send "
                        "produces %d" % (len(ivls),
                                         len(self.msg_dtypes)))
                mdev = (phash_np(idst) % np.uint32(self.ndev)) \
                    .astype(np.int64)
                mc = np.bincount(mdev, minlength=ndev)
                cap_m = layout.round_capacity(int(mc.max() or 1))
                hm_d = np.full((ndev, cap_m), _SENT, np.int64)
                hm_v = [np.zeros((ndev, cap_m) + shp, dt)
                        for dt, shp in zip(self.msg_dtypes,
                                           self.msg_shapes)]
                mcnt = np.zeros(ndev, np.int32)
                for d in range(ndev):
                    m = mdev == d
                    c = int(m.sum())
                    mcnt[d] = c
                    if c:
                        hm_d[d, :c] = idst[m]
                        for hl, l in zip(hm_v, ivls):
                            hl[d, :c] = l[m].astype(hl.dtype)
                self.init = (put(mcnt), put(hm_d),
                             [put(l) for l in hm_v])

    def _sharding(self):
        return NamedSharding(self.mesh, P(AXIS))

    # ------------------------------------------------------------------
    # the three programs
    # ------------------------------------------------------------------
    def _jit(self, key, fn, n_in, n_out):
        if key not in self._compiled:
            wrapped = _shard_map(fn, self.mesh,
                                 in_specs=(P(AXIS),) * n_in,
                                 out_specs=(P(AXIS),) * n_out)
            self._compiled[key] = jax.jit(wrapped)
        return self._compiled[key]

    def _p_init(self):
        """Bucketize the user's initial messages by hash(dst)."""
        ndev = self.ndev
        combine = self.combine
        nm = len(self.msg_dtypes)

        def per_device(mcnt, mdst, *mvals):
            m, d = mcnt[0], mdst[0]
            vs = [v[0] for v in mvals]
            kk, vv, counts, offsets = collectives.bucketize_combine(
                d, vs, m, ndev, None, monoid=combine)
            out = (counts, offsets, kk) + tuple(vv)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        return self._jit(("init",), per_device, 2 + nm, 3 + nm)

    def _p_gen(self):
        """Generate per-edge messages from the current vertex state,
        pre-combine per destination, bucketize by hash(dst)."""
        ndev = self.ndev
        cap_e = self.cap_e
        combine = self.combine
        nv = len(self.values)
        ne = len(self.e_vals)

        def per_device(vcnt, act, edst, eslot, edeg, ecnt, *rest):
            a = act[0]
            slot = eslot[0]
            vals = [v[0] for v in rest[:nv]]
            evs = [v[0] for v in rest[nv:]]
            ev = jnp.arange(cap_e) < ecnt[0]
            sv = [v[slot] for v in vals]
            if self.send_gate is not None:
                sa = vals[self.send_gate][slot].astype(bool) & ev
            else:
                sa = a[slot] & ev
            msg = self.send(
                rewrap(sv, self.v_tuple),
                rewrap(evs, self.e_tuple) if ne else None, edeg[0])
            m_leaves, _ = as_leaves(msg)
            m_leaves = [jnp.broadcast_to(jnp.asarray(l),
                                         (cap_e,) + shp)
                        for l, shp in zip(m_leaves, self.msg_shapes)]
            dstk = jnp.where(sa, edst[0], collectives._sentinel(jnp.int64))
            packed, cnt = collectives.compact([dstk] + m_leaves, sa)
            kk, vv, counts, offsets = collectives.bucketize_combine(
                packed[0], packed[1:], cnt, ndev, None, monoid=combine)
            out = (counts, offsets, kk) + tuple(vv) + (
                jnp.reshape(cnt, (1,)),)
            return tuple(jnp.expand_dims(o, 0) for o in out)

        nm = len(self.msg_dtypes)
        return self._jit(("gen",), per_device, 6 + nv + ne, 4 + nm)

    def _p_step(self, rounds, slot, s_static=None):
        """Deliver combined messages, run the vertex compute, count the
        still-active vertices.  aggregated (if any) is computed from the
        PRE-compute state and psum'd across the mesh."""
        cap_v = self.cap_v
        combine = self.combine
        nv = len(self.values)
        nm = len(self.msg_dtypes)
        nleaves = 1 + nm                        # dst key + msg leaves
        static = self.static_superstep

        def per_device(*all_args):
            if static:
                vcnt, vid, act = all_args[:3]
                rest = all_args[3:]
                s = s_static
            else:
                sstep, vcnt, vid, act = all_args[:4]
                rest = all_args[4:]
                s = sstep[0]
            cnt = vcnt[0]
            ids = vid[0]
            a = act[0]
            vals = [v[0] for v in rest[:nv]]
            valid_v = jnp.arange(cap_v) < cnt

            ag = None
            if self.aggregator is not None:
                create, amon = self.aggregator
                a_leaves, a_tuple = as_leaves(
                    create(rewrap(vals, self.v_tuple)))
                glob = []
                for leaf in a_leaves:
                    ident = monoid_identity(amon, leaf.dtype)
                    masked = jnp.where(
                        collectives._bcast(valid_v, leaf), leaf, ident)
                    glob.append(_axis_reduce(
                        amon, _local_reduce(amon, masked)))
                ag = rewrap(glob, a_tuple)

            if rounds:
                cnts = [c[0] for c in rest[nv:nv + rounds]]
                bufs = rest[nv + rounds:]
                recvs = []
                for r in range(rounds):
                    recvs.append([bufs[r * nleaves + li][0]
                                  for li in range(nleaves)])
                flat, mask = collectives.flatten_received(recvs, cnts)
                uk, uv, _ = collectives.segment_reduce(
                    flat[0], flat[1:], mask, None, monoid=combine)
                pos = jnp.clip(jnp.searchsorted(uk, ids), 0,
                               uk.shape[0] - 1)
                has = (uk[pos] == ids) & valid_v \
                    & (ids != collectives._sentinel(jnp.int64))
                msg = [jnp.where(collectives._bcast(has, u[pos]),
                                 u[pos],
                                 monoid_identity(combine, dt))
                       for u, dt in zip(uv, self.msg_dtypes)]
            else:
                has = jnp.zeros(cap_v, bool)
                msg = [jnp.full((cap_v,) + shp,
                                monoid_identity(combine, dt), dt)
                       for dt, shp in zip(self.msg_dtypes,
                                          self.msg_shapes)]

            nv_, na_ = self.compute(
                rewrap(vals, self.v_tuple),
                rewrap(msg, self.m_tuple), has, a & valid_v, ag, s)
            new_leaves, _ = as_leaves(nv_)
            new_act = jnp.broadcast_to(
                jnp.asarray(na_, bool), (cap_v,)) & valid_v
            new_leaves = [
                jnp.where(collectives._bcast(valid_v, l), l,
                          jnp.zeros((), l.dtype))
                for l in [jnp.broadcast_to(l, (cap_v,) + l.shape[1:])
                          for l in new_leaves]]
            n_active = jnp.sum(new_act).astype(jnp.int32)
            out = tuple(new_leaves) + (new_act,
                                       jnp.reshape(n_active, (1,)))
            return tuple(jnp.expand_dims(o, 0) for o in out)

        n_in = (3 if static else 4) + nv + rounds + rounds * nleaves
        return self._jit(("step", rounds, slot,
                          s_static if static else None), per_device,
                         n_in, nv + 2)

    # ------------------------------------------------------------------
    def run(self):
        nv = len(self.values)
        nm = len(self.msg_dtypes)
        sh = self._sharding()
        pending = None            # (counts, offsets, kk, vv) bucketized
        total_msgs = 0
        if self.init is not None:
            mcnt, mdst, mvals = self.init
            outs = self._p_init()(mcnt, mdst, *mvals)
            pending = (outs[0], outs[1], outs[2], list(outs[3:]))
            total_msgs = int(np.asarray(
                jax.device_get(outs[0])).sum())

        s = 0
        n_active = None
        while s < self.max_superstep:
            if self.static_superstep:
                head = [self.vcnt, self.vid, self.active]
            else:
                head = [jax.device_put(
                    np.full((self.ndev,), s, np.int32), sh),
                    self.vcnt, self.vid, self.active]
            if pending is not None and total_msgs > 0:
                counts, offsets, kk, vv = pending
                recv_rounds, cnt_rounds, slot = self.ex._exchange_all(
                    [kk] + vv, counts, offsets)
                rounds = len(recv_rounds)
                step = self._p_step(rounds, slot, s_static=s)
                args = head + self.values + list(cnt_rounds)
                for r in range(rounds):
                    args.extend(recv_rounds[r])
            else:
                step = self._p_step(0, 0, s_static=s)
                args = head + self.values
            outs = step(*args)
            self.values = list(outs[:nv])
            self.active = outs[nv]
            n_active = int(np.asarray(
                jax.device_get(outs[nv + 1])).sum())

            gouts = self._p_gen()(
                self.vcnt, self.active, self.e_dst, self.e_slot,
                self.e_deg, self.ecnt, *(self.values + self.e_vals))
            pending = (gouts[0], gouts[1], gouts[2],
                       list(gouts[3:3 + nm]))
            total_msgs = int(np.asarray(
                jax.device_get(gouts[3 + nm])).sum())
            s += 1
            logger.debug("superstep %d: active=%d msgs=%d",
                         s, n_active, total_msgs)
            if n_active == 0 and total_msgs == 0:
                break
        return self._collect()

    def _collect(self):
        """Pull the final state to host, unpad, sort by id."""
        vid = np.asarray(jax.device_get(self.vid))
        vcnt = np.asarray(jax.device_get(self.vcnt))
        vals = [np.asarray(jax.device_get(l)) for l in self.values]
        act = np.asarray(jax.device_get(self.active))
        ids, leaves, actv = [], [[] for _ in vals], []
        for d in range(self.ndev):
            c = int(vcnt[d])
            ids.append(vid[d, :c])
            for i, l in enumerate(vals):
                leaves[i].append(l[d, :c])
            actv.append(act[d, :c])
        ids = np.concatenate(ids) if ids else np.zeros(0, np.int64)
        order = np.argsort(ids)
        leaves = [np.concatenate(ls)[order] for ls in leaves]
        return (ids[order],
                rewrap(leaves, self.v_tuple),
                np.concatenate(actv)[order] if actv
                else np.zeros(0, bool))
